"""Pallas TPU hash-join probe kernel (north-star: "hash join as a Pallas
radix-partitioned join", SURVEY §8.2.2).

Scope (v1, deliberately narrow): single 64-bit key, UNIQUE build keys —
the primary-key joins that dominate TPC-H (lineitem->orders on orderkey,
orders->customer on custkey). The general path (duplicate keys, multi-key,
nulls) stays on the sort+searchsorted join in ops/join.py; this kernel is
the VMEM-resident fast path for the common shape.

Design:
  build (XLA, once per join): vectorized open-addressing insert — every
    build row claims slots by scatter-min of its row id, lockstep linear
    probing (same deterministic scheme as ops/agg.compute_groups_hashed).
    Table = (key lo32, key hi32, row id) arrays, capacity 2x rows, pow2.
  probe (Pallas): grid over probe-row blocks; each block loads its keys
    into VMEM, computes the initial slot from the mixed key, then runs K
    bounded probe rounds entirely on the VPU — gather table entries,
    compare lo/hi words, advance unresolved lanes to the next slot.
    Returns the matching build row id or -1 per probe row.

u64 handling: TPU lanes are 32-bit, so keys travel as (lo32, hi32) int32
pairs and the table is int32 throughout — no 64-bit emulation inside the
kernel. The table must fit VMEM (~16 MB: up to ~1M build rows); larger
builds stay on the sort join (the caller checks).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_EMPTY = jnp.int32(-1)


def _split64(keys: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    u = keys.astype(jnp.uint64)
    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32).astype(jnp.int32)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    return lo, hi


def _mix32(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """32-bit finalizer (murmur3 fmix32 over both words) for slot
    addressing; equality is verified on the full (lo, hi) pair."""
    h = lo.astype(jnp.uint32) ^ (hi.astype(jnp.uint32) *
                                 jnp.uint32(0x85EBCA6B))
    h ^= h >> jnp.uint32(16)
    h = h * jnp.uint32(0x85EBCA6B)
    h ^= h >> jnp.uint32(13)
    h = h * jnp.uint32(0xC2B2AE35)
    h ^= h >> jnp.uint32(16)
    return h


def build_table(
    keys: jnp.ndarray, valid: jnp.ndarray, table_cap: int,
    max_iters: int = 64,
):
    """Open-addressing insert of (unique) build keys, fully vectorized.

    Returns (tab_lo, tab_hi, tab_row) int32[table_cap] plus an overflow
    flag (unresolved rows after max_iters — callers fall back to the
    sort join)."""
    n = keys.shape[0]
    lo, hi = _split64(keys)
    h = _mix32(lo, hi)
    mask = jnp.uint32(table_cap - 1)
    slot0 = (h & mask).astype(jnp.int32)
    row_idx = jnp.arange(n, dtype=jnp.int32)
    BIG = jnp.int32(n)

    def settled(owner, slot):
        win = owner[slot]
        return valid & (win == row_idx)

    def cond(state):
        owner, slot, it = state
        return jnp.any(valid & ~settled(owner, slot)) & (it < max_iters)

    def body(state):
        owner, slot, it = state
        done = settled(owner, slot)
        claim = jnp.where(done | ~valid, BIG, row_idx)
        owner = owner.at[slot].min(claim)
        done2 = settled(owner, slot)
        nxt = (slot.astype(jnp.uint32) + jnp.uint32(1)) & mask
        slot = jnp.where(done2 | ~valid, slot, nxt.astype(jnp.int32))
        return owner, slot, it + 1

    owner0 = jnp.full((table_cap,), BIG, dtype=jnp.int32)
    owner, slot, _ = jax.lax.while_loop(
        cond, body, (owner0, slot0, jnp.int32(0))
    )
    ok = settled(owner, slot)
    overflow = jnp.any(valid & ~ok)
    tab_row = jnp.full((table_cap,), _EMPTY, dtype=jnp.int32)
    tab_row = tab_row.at[jnp.where(ok, slot, table_cap)].set(
        row_idx, mode="drop"
    )
    tab_lo = jnp.zeros((table_cap,), dtype=jnp.int32).at[
        jnp.where(ok, slot, table_cap)
    ].set(lo, mode="drop")
    tab_hi = jnp.zeros((table_cap,), dtype=jnp.int32).at[
        jnp.where(ok, slot, table_cap)
    ].set(hi, mode="drop")
    return (tab_lo, tab_hi, tab_row), overflow


def _probe_kernel(plo_ref, phi_ref, tlo_ref, thi_ref, trow_ref, out_ref,
                  *, table_cap: int, max_probes: int):
    plo = plo_ref[:]
    phi = phi_ref[:]
    h = _mix32(plo, phi)
    mask = jnp.uint32(table_cap - 1)
    slot = (h & mask).astype(jnp.int32)
    result = jnp.full(plo.shape, -1, dtype=jnp.int32)
    live = jnp.ones(plo.shape, dtype=jnp.bool_)

    def body(_i, carry):
        slot, result, live = carry
        tlo = tlo_ref[slot]
        thi = thi_ref[slot]
        trow = trow_ref[slot]
        hit = live & (trow != -1) & (tlo == plo) & (thi == phi)
        result = jnp.where(hit, trow, result)
        # stop on hit or empty slot; otherwise advance
        live = live & ~hit & (trow != -1)
        nxt = ((slot.astype(jnp.uint32) + jnp.uint32(1)) & mask)
        slot = jnp.where(live, nxt.astype(jnp.int32), slot)
        return slot, result, live

    slot, result, live = jax.lax.fori_loop(
        0, max_probes, body, (slot, result, live)
    )
    out_ref[:] = result


def probe(
    probe_keys: jnp.ndarray,
    table,
    *,
    block_rows: int = 2048,
    max_probes: int = 64,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas probe: per probe key, the matching build row id or -1.

    probe_keys length must be a multiple of block_rows (pad with any
    value; unmatched padding returns -1 naturally unless it collides —
    callers mask by validity anyway)."""
    from jax.experimental import pallas as pl

    tab_lo, tab_hi, tab_row = table
    table_cap = tab_lo.shape[0]
    n = probe_keys.shape[0]
    assert n % block_rows == 0, (n, block_rows)
    plo, phi = _split64(probe_keys)

    grid = (n // block_rows,)
    blk = pl.BlockSpec((block_rows,), lambda i: (i,))
    whole = pl.BlockSpec((table_cap,), lambda i: (0,))
    kernel = functools.partial(
        _probe_kernel, table_cap=table_cap, max_probes=max_probes
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        grid=grid,
        in_specs=[blk, blk, whole, whole, whole],
        out_specs=blk,
        interpret=interpret,
    )(plo, phi, tab_lo, tab_hi, tab_row)


def table_capacity(build_rows: int) -> int:
    """2x-rows open-addressing capacity, pow2 (load factor <= 0.5)."""
    return max(16, 1 << (2 * build_rows - 1).bit_length())


def probe_any(
    probe_keys: jnp.ndarray, table, *, interpret: bool = False
) -> jnp.ndarray:
    """probe() for ANY input length: Pallas rank-1 blocks must evenly
    tile the array (multiples of 128 in practice), so inputs are padded
    to a 2048 multiple and the pad lanes sliced off. Pad keys are zeros;
    callers mask results by probe validity regardless."""
    n = probe_keys.shape[0]
    pad = (-n) % 2048
    if pad:
        probe_keys = jnp.concatenate(
            [probe_keys, jnp.zeros((pad,), probe_keys.dtype)]
        )
    rid = probe(probe_keys, table, block_rows=2048, interpret=interpret)
    return rid[:n]


def join_unique(
    build_keys: jnp.ndarray,
    build_valid: jnp.ndarray,
    probe_keys: jnp.ndarray,
    probe_valid: jnp.ndarray,
    *,
    interpret: bool = False,
):
    """End-to-end unique-key inner-join mapping: for each probe row the
    matching build row id or -1. Returns (row_ids, overflow)."""
    nb = int(build_keys.shape[0])
    table, overflow = build_table(build_keys, build_valid,
                                  table_capacity(nb))
    rid = probe_any(probe_keys, table, interpret=interpret)
    rid = jnp.where(probe_valid, rid, -1)
    # reject matches onto invalid build rows (valid rows never share slots
    # with them because invalid rows never settle)
    return rid, overflow
