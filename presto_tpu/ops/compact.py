"""Selection-mask materialization: compact valid rows to a dense prefix and
gather rows by index.

Reference analog: presto-main operator/project/PageProcessor.java materializes
selectedPositions into output Blocks; operator/PartitionedOutputOperator
appends selected rows into per-partition PageBuilders. On TPU, compaction is a
cumsum + scatter (stable, branch-free) and happens only at exchange/output
boundaries — inside a stage, masks are free and compaction is wasted HBM
bandwidth.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from presto_tpu.page import Block, Page


def compact_indices(valid: jnp.ndarray, out_capacity: int):
    """Stable scatter targets: row i goes to slot cumsum(valid)[i]-1.

    Returns (targets[int, cap_in], out_valid[bool, out_capacity], num_rows).
    Rows that are invalid or overflow out_capacity scatter to index
    out_capacity (dropped by jax scatter mode='drop').
    """
    pos = jnp.cumsum(valid.astype(jnp.int64)) - 1
    num = jnp.sum(valid.astype(jnp.int64))
    targets = jnp.where(valid & (pos < out_capacity), pos, out_capacity)
    out_valid = jnp.arange(out_capacity, dtype=jnp.int64) < num
    return targets, out_valid, num


def scatter_column(
    data: jnp.ndarray, targets: jnp.ndarray, out_capacity: int
) -> jnp.ndarray:
    out = jnp.zeros((out_capacity,), dtype=data.dtype)
    return out.at[targets].set(data, mode="drop")


def compact_page(page: Page, out_capacity: Optional[int] = None) -> Page:
    """Materialize the selection mask: valid rows move to a dense prefix.

    If out_capacity < num valid rows, overflow rows are silently dropped —
    callers that can overflow must check num_rows first (the compiled-branch
    escape described in SURVEY §8.2.1).
    """
    cap_out = out_capacity or page.capacity
    targets, out_valid, _ = compact_indices(page.valid, cap_out)
    new_blocks = []
    for blk in page.blocks:
        if isinstance(blk.data, tuple):
            data = tuple(scatter_column(d, targets, cap_out) for d in blk.data)
        else:
            data = scatter_column(blk.data, targets, cap_out)
        nulls = (
            scatter_column(blk.nulls, targets, cap_out)
            if blk.nulls is not None
            else None
        )
        new_blocks.append(blk.with_data(data, nulls=nulls))
    return Page(blocks=tuple(new_blocks), valid=out_valid)


def gather_rows(
    page: Page,
    indices: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    force_null: Optional[jnp.ndarray] = None,
) -> Page:
    """Row gather: output row j = input row indices[j] (valid[j] gates).

    force_null marks gathered rows entirely NULL (outer-join padding:
    reference analog LookupJoinOperator emitting probe rows with null build
    side).
    """
    idx = jnp.clip(indices, 0, page.capacity - 1)
    new_blocks = []
    for blk in page.blocks:
        if isinstance(blk.data, tuple):
            data = tuple(d[idx] for d in blk.data)
        else:
            data = blk.data[idx]
        nulls = blk.nulls[idx] if blk.nulls is not None else None
        if force_null is not None:
            base = (
                nulls
                if nulls is not None
                else jnp.zeros(idx.shape, dtype=jnp.bool_)
            )
            nulls = base | force_null
        new_blocks.append(blk.with_data(data, nulls=nulls))
    return Page(blocks=tuple(new_blocks), valid=valid)


def concat_pages(a: Page, b: Page) -> Page:
    """Concatenate two pages with identical schemas (capacities add).

    Dictionary columns with differing dictionaries are merged: the output
    dictionary is a's values followed by b's unseen values, and b's codes are
    remapped through a static translation table (dictionaries are host-side
    static data, so the remap is a compile-time constant gather).
    """
    import numpy as np

    from presto_tpu.page import Block, Dictionary

    blocks = []
    for ba, bb in zip(a.blocks, b.blocks):
        out_dict = ba.dictionary
        bb_data = bb.data
        if ba.dictionary is not None or bb.dictionary is not None:
            da = ba.dictionary or Dictionary([])
            db = bb.dictionary or Dictionary([])
            if da != db:
                merged_vals = list(da.values) + [
                    v for v in db.values if da.code_of(v) < 0
                ]
                out_dict = Dictionary(merged_vals)
                remap = np.array(
                    [out_dict.code_of(v) for v in db.values] or [0],
                    dtype=np.int32,
                )
                codes = jnp.clip(bb.data, 0, max(len(db) - 1, 0))
                bb_data = jnp.asarray(remap)[codes]
        if isinstance(ba.data, tuple):
            data = tuple(
                jnp.concatenate([x, y]) for x, y in zip(ba.data, bb_data)
            )
        else:
            data = jnp.concatenate([ba.data, bb_data])
        if ba.nulls is None and bb.nulls is None:
            nulls = None
        else:
            na = ba.nulls_or_false()
            nb = bb.nulls_or_false()
            nulls = jnp.concatenate([na, nb])
        blocks.append(
            Block(data=data, type=ba.type, nulls=nulls, dictionary=out_dict)
        )
    return Page(
        blocks=tuple(blocks), valid=jnp.concatenate([a.valid, b.valid])
    )
