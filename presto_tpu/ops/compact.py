"""Selection-mask materialization: compact valid rows to a dense prefix and
gather rows by index.

Reference analog: presto-main operator/project/PageProcessor.java materializes
selectedPositions into output Blocks; operator/PartitionedOutputOperator
appends selected rows into per-partition PageBuilders. On TPU, compaction is a
cumsum + scatter (stable, branch-free) and happens only at exchange/output
boundaries — inside a stage, masks are free and compaction is wasted HBM
bandwidth.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from presto_tpu.page import Block, Page


def compact_indices(valid: jnp.ndarray, out_capacity: int):
    """Stable scatter targets: row i goes to slot cumsum(valid)[i]-1.

    Returns (targets[int, cap_in], out_valid[bool, out_capacity], num_rows).
    Rows that are invalid or overflow out_capacity scatter to index
    out_capacity (dropped by jax scatter mode='drop').
    """
    pos = jnp.cumsum(valid.astype(jnp.int64)) - 1
    num = jnp.sum(valid.astype(jnp.int64))
    targets = jnp.where(valid & (pos < out_capacity), pos, out_capacity)
    out_valid = jnp.arange(out_capacity, dtype=jnp.int64) < num
    return targets, out_valid, num


def scatter_column(
    data: jnp.ndarray, targets: jnp.ndarray, out_capacity: int
) -> jnp.ndarray:
    out = jnp.zeros((out_capacity,), dtype=data.dtype)
    return out.at[targets].set(data, mode="drop")


def compact_page(page: Page, out_capacity: Optional[int] = None) -> Page:
    """Materialize the selection mask: valid rows move to a dense prefix.

    If out_capacity < num valid rows, overflow rows are silently dropped —
    callers that can overflow must check num_rows first (the compiled-branch
    escape described in SURVEY §8.2.1).

    Implementation is one stable argsort of the validity mask (valid rows
    first, original order preserved) followed by per-column GATHERS of the
    output prefix — scatter is the slowest primitive on TPU (~14M rows/s)
    while sort+gather run at 140M/25M rows/s, and the gathers are sized by
    the OUTPUT capacity, so compacting sparse pages down is nearly free.
    """
    cap_out = out_capacity or page.capacity
    n = page.capacity
    order = jnp.argsort(~page.valid, stable=True)
    num = jnp.sum(page.valid.astype(jnp.int64))
    if cap_out <= n:
        src = order[:cap_out]
    else:
        src = jnp.concatenate(
            [order, jnp.zeros((cap_out - n,), dtype=order.dtype)]
        )
    out_valid = jnp.arange(cap_out, dtype=jnp.int64) < num
    new_blocks = []
    for blk in page.blocks:
        if isinstance(blk.data, tuple):
            data = tuple(d[src] for d in blk.data)
        else:
            data = blk.data[src]
        nulls = blk.nulls[src] if blk.nulls is not None else None
        new_blocks.append(blk.with_data(data, nulls=nulls))
    return Page(blocks=tuple(new_blocks), valid=out_valid)


def gather_rows(
    page: Page,
    indices: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    force_null: Optional[jnp.ndarray] = None,
) -> Page:
    """Row gather: output row j = input row indices[j] (valid[j] gates).

    force_null marks gathered rows entirely NULL (outer-join padding:
    reference analog LookupJoinOperator emitting probe rows with null build
    side).
    """
    idx = jnp.clip(indices, 0, page.capacity - 1)
    new_blocks = [
        blk.take(idx, extra_nulls=force_null) for blk in page.blocks
    ]
    return Page(blocks=tuple(new_blocks), valid=valid)


def concat_all(pages) -> Page:
    """n-way page concat with dictionary reconciliation (one
    jnp.concatenate per column, not a fold of pairwise copies).

    Dictionary columns with differing dictionaries are merged through one
    value universe and codes are remapped via static host luts (dictionaries
    are compile-time data, so the remaps are constant gathers).
    """
    import numpy as np

    from presto_tpu.page import Block, Dictionary

    pages = list(pages)
    if len(pages) == 1:
        return pages[0]
    blocks = []
    for ch in range(pages[0].channel_count):
        blks = [p.block(ch) for p in pages]
        dic = None
        datas = [b.data for b in blks]
        if any(b.dictionary is not None for b in blks):
            dics = [b.dictionary for b in blks]
            if all(d == dics[0] for d in dics):
                dic = dics[0]
            else:
                pos = {}
                for d in dics:
                    for v in (d.values if d is not None else []):
                        pos.setdefault(v, len(pos))
                dic = Dictionary(list(pos))
                remapped = []
                for b, d in zip(blks, dics):
                    if d is None or len(d) == 0:
                        remapped.append(jnp.zeros_like(b.data))
                        continue
                    lut = np.array([pos[v] for v in d.values], np.int32)
                    codes = jnp.clip(b.data, 0, len(d) - 1)
                    remapped.append(jnp.asarray(lut)[codes])
                datas = remapped
        if isinstance(datas[0], tuple):
            data = tuple(
                jnp.concatenate([d[i] for d in datas])
                for i in range(len(datas[0]))
            )
        else:
            data = jnp.concatenate(datas)
        if all(b.nulls is None for b in blks):
            nulls = None
        else:
            nulls = jnp.concatenate([b.nulls_or_false() for b in blks])
        blocks.append(
            Block(data=data, type=blks[0].type, nulls=nulls, dictionary=dic)
        )
    valid = jnp.concatenate([p.valid for p in pages])
    return Page(blocks=tuple(blocks), valid=valid)


def concat_pages(a: Page, b: Page) -> Page:
    """Two-page concat (see concat_all)."""
    return concat_all([a, b])


def slice_page(page: Page, start: int, size: int) -> Page:
    """Static row-window slice [start, start+size) of a page — every
    block's data (and nulls) sliced with compile-time bounds, validity
    preserved. Used by the per-partition skew rebalancer to chunk a hot
    join partition's build rows by POSITION (a genuinely hot key cannot
    be split by key hash; reference analog: PartitionedLookupSource
    dividing one partition's addresses across probe passes)."""
    stop = min(start + size, page.capacity)

    def cut(x):
        return x[start:stop]

    blocks = []
    for blk in page.blocks:
        data = (
            tuple(cut(d) for d in blk.data)
            if isinstance(blk.data, tuple) else cut(blk.data)
        )
        nulls = cut(blk.nulls) if blk.nulls is not None else None
        blocks.append(blk.with_data(data, nulls=nulls))
    return Page(blocks=tuple(blocks), valid=cut(page.valid))
