"""Key encodings: map typed Blocks to uint64 arrays for equality (join /
group-by) and total order (sort / merge).

Reference analog: the reference compares typed values through Type
equalTo/compareTo per position (spi/type/*); on TPU we precompute branch-free
uint64 encodings once per page and then every comparison is integer compare.

Equality encoding: values are equal iff encodings are equal (plus null flags).
Order encoding: encoding order == SQL ascending order for non-null values:
  - signed ints: flip sign bit  (x ^ 0x8000...),
  - floats: IEEE-754 total order trick (flip all bits if negative, else set
    sign bit); -0.0 normalized to +0.0 first so -0.0 == 0.0 (SQL equality);
    NaN sorts above +inf which matches the engine's NaN-is-largest rule,
  - dictionary codes: order via Dictionary.sort_rank (host, static), equality
    via raw codes,
  - booleans: 0/1.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.page import Block

_SIGN64 = jnp.uint64(0x8000000000000000)


def _int_order_u64(x: jnp.ndarray) -> jnp.ndarray:
    return x.astype(jnp.int64).astype(jnp.uint64) ^ _SIGN64


def _float_order_u64(x: jnp.ndarray) -> jnp.ndarray:
    x64 = x.astype(jnp.float64)
    x64 = jnp.where(x64 == 0.0, 0.0, x64)  # -0.0 -> +0.0
    bits = jax_bitcast_f64_u64(x64)
    neg = (bits & _SIGN64) != 0
    return jnp.where(neg, ~bits, bits | _SIGN64)


def jax_bitcast_f64_u64(x: jnp.ndarray) -> jnp.ndarray:
    import jax.lax as lax

    return lax.bitcast_convert_type(x, jnp.uint64)


def equality_encoding(block: Block) -> List[jnp.ndarray]:
    """uint64 array(s) such that rows are SQL-equal iff encodings equal.

    For floats we use the order encoding (normalizes -0.0; NaN==NaN under this
    encoding, documented divergence: SQL `=` on NaN is false, but GROUP BY /
    join on NaN grouping-equal matches the reference's distinct-value
    semantics, which treat NaN as one value).

    Dictionary columns canonicalize codes by *value* through a static host
    lut — dictionaries produced by string transforms (substr/lower/...)
    carry duplicate values, so raw codes are not equality-faithful.
    """
    t = block.type
    if isinstance(block.data, tuple):  # long decimal limbs
        hi, lo = block.data
        return [hi.astype(jnp.uint64), lo.astype(jnp.uint64)]
    if isinstance(t, (T.DoubleType, T.RealType)):
        return [_float_order_u64(block.data)]
    if isinstance(t, T.BooleanType):
        return [block.data.astype(jnp.uint64)]
    if (
        block.dictionary is not None
        and len(block.dictionary)
        and block.dictionary.has_duplicate_values()
    ):
        import numpy as np

        values = block.dictionary.values
        first: dict = {}
        lut = np.empty(len(values), dtype=np.uint64)
        for i, v in enumerate(values):
            lut[i] = first.setdefault(v, i)
        codes = jnp.clip(block.data, 0, len(values) - 1)
        return [jnp.asarray(lut)[codes]]
    return [block.data.astype(jnp.int64).astype(jnp.uint64)]


def order_encoding(
    block: Block,
    *,
    ascending: bool = True,
    nulls_first: bool = False,
) -> List[jnp.ndarray]:
    """uint64 key columns (most-significant first) whose ascending order is
    the requested SQL order, including the null position. Invalid rows are
    handled by the caller (sorted to the end via a leading validity key)."""
    t = block.type
    if isinstance(block.data, tuple):
        hi, lo = block.data
        keys = [_int_order_u64(hi), lo.astype(jnp.uint64)]
    elif isinstance(t, (T.DoubleType, T.RealType)):
        keys = [_float_order_u64(block.data)]
    elif isinstance(t, T.BooleanType):
        keys = [block.data.astype(jnp.uint64)]
    elif t.is_dictionary_encoded and block.dictionary is not None:
        if len(block.dictionary) == 0:
            # all-NULL column: only the null key matters
            keys = [jnp.zeros(block.data.shape, dtype=jnp.uint64)]
        else:
            rank = jnp.asarray(block.dictionary.sort_rank())
            codes = jnp.clip(block.data, 0, len(block.dictionary) - 1)
            keys = [rank[codes].astype(jnp.uint64)]
    else:
        keys = [_int_order_u64(block.data)]

    if not ascending:
        keys = [~k for k in keys]

    null = block.nulls
    if null is None:
        null_key = jnp.zeros(keys[0].shape, dtype=jnp.uint64)
    elif nulls_first:
        null_key = jnp.where(null, jnp.uint64(0), jnp.uint64(1))
    else:
        null_key = jnp.where(null, jnp.uint64(1), jnp.uint64(0))
    return [null_key] + keys


def block_key_columns(
    blocks,
) -> Tuple[List[jnp.ndarray], List[Optional[jnp.ndarray]]]:
    """Equality encodings + null masks for a list of key Blocks (flattened:
    a long-decimal key contributes two uint64 columns sharing one null)."""
    cols: List[jnp.ndarray] = []
    nulls: List[Optional[jnp.ndarray]] = []
    for b in blocks:
        enc = equality_encoding(b)
        cols.extend(enc)
        nulls.extend([b.nulls] * len(enc))
    return cols, nulls
