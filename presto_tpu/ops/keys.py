"""Key encodings: map typed Blocks to uint64 arrays for equality (join /
group-by) and total order (sort / merge).

Reference analog: the reference compares typed values through Type
equalTo/compareTo per position (spi/type/*); on TPU we precompute branch-free
uint64 encodings once per page and then every comparison is integer compare.

Equality encoding: values are equal iff encodings are equal (plus null flags).
Order encoding: encoding order == SQL ascending order for non-null values:
  - signed ints: flip sign bit  (x ^ 0x8000...),
  - floats: IEEE-754 total order trick (flip all bits if negative, else set
    sign bit); -0.0 normalized to +0.0 first so -0.0 == 0.0 (SQL equality);
    NaN sorts above +inf which matches the engine's NaN-is-largest rule,
  - dictionary codes: order via Dictionary.sort_rank (host, static), equality
    via raw codes,
  - booleans: 0/1.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.page import Block

# numpy scalar, not jnp: module-level device buffers embedded as jit
# constants permanently degrade the axon TPU runtime (see ops/hashing.py)
_SIGN64 = np.uint64(0x8000000000000000)


def _int_order_u64(x: jnp.ndarray) -> jnp.ndarray:
    return x.astype(jnp.int64).astype(jnp.uint64) ^ _SIGN64


def _float_order_u64(x: jnp.ndarray) -> jnp.ndarray:
    """IEEE-754 total-order u64 key for DOUBLE/REAL values.

    Backend-split, because the TPU backend (a) rejects f64<->u64 bitcasts at
    compile time and (b) *represents* f64 as an (hi, lo) pair of f32s — f32
    exponent range, ~49-bit mantissa; hi = RN32(x), lo = RN32(x - hi), and
    hi + lo reconstructs every storable value exactly (verified on-device,
    tests/test_tpu_smoke.py). On TPU the faithful order key is therefore the
    pair key (order32(hi) << 32) | order32(lo): hi is monotone in x, and lo
    breaks ties exactly. On CPU (true f64) we keep the classic bitcast trick.
    Both: -0.0 normalized to +0.0, NaN sorts above +inf (engine's
    NaN-is-largest rule).
    """
    import jax
    import jax.lax as lax

    x64 = x.astype(jnp.float64)
    x64 = jnp.where(x64 == 0.0, 0.0, x64)  # -0.0 -> +0.0
    isnan = jnp.isnan(x64)
    if jax.default_backend() != "tpu":
        bits = lax.bitcast_convert_type(x64, jnp.uint64)
        neg = (bits & _SIGN64) != 0
        out = jnp.where(neg, ~bits, bits | _SIGN64)
        return jnp.where(isnan, jnp.uint64(0xFFFFFFFFFFFFFFFF), out)

    sign32 = jnp.uint32(0x80000000)

    def order32(f):
        f = jnp.where(f == 0.0, jnp.float32(0.0), f)  # -0.0f -> +0.0f
        bits = lax.bitcast_convert_type(f.astype(jnp.float32), jnp.uint32)
        neg = (bits & sign32) != 0
        return jnp.where(neg, ~bits, bits | sign32)

    hi = x64.astype(jnp.float32)
    resid = jnp.where(
        jnp.isfinite(hi), x64 - hi.astype(jnp.float64), 0.0
    )
    lo = resid.astype(jnp.float32)
    key = (order32(hi).astype(jnp.uint64) << 32) | order32(lo).astype(
        jnp.uint64
    )
    return jnp.where(isnan, jnp.uint64(0xFFFFFFFFFFFFFFFF), key)


def equality_encoding(block: Block) -> List[jnp.ndarray]:
    """uint64 array(s) such that rows are SQL-equal iff encodings equal.

    For floats we use the order encoding (normalizes -0.0; NaN==NaN under this
    encoding, documented divergence: SQL `=` on NaN is false, but GROUP BY /
    join on NaN grouping-equal matches the reference's distinct-value
    semantics, which treat NaN as one value).

    Dictionary columns canonicalize codes by *value* through a static host
    lut — dictionaries produced by string transforms (substr/lower/...)
    carry duplicate values, so raw codes are not equality-faithful.
    """
    t = block.type
    if isinstance(block.data, tuple):  # long decimal limbs
        hi, lo = block.data
        return [hi.astype(jnp.uint64), lo.astype(jnp.uint64)]
    if isinstance(t, (T.DoubleType, T.RealType)):
        return [_float_order_u64(block.data)]
    if isinstance(t, T.BooleanType):
        return [block.data.astype(jnp.uint64)]
    if (
        block.dictionary is not None
        and len(block.dictionary)
        and block.dictionary.has_duplicate_values()
    ):
        import numpy as np

        values = block.dictionary.values
        first: dict = {}
        lut = np.empty(len(values), dtype=np.uint64)
        for i, v in enumerate(values):
            lut[i] = first.setdefault(v, i)
        codes = jnp.clip(block.data, 0, len(values) - 1)
        return [jnp.asarray(lut)[codes]]
    return [block.data.astype(jnp.int64).astype(jnp.uint64)]


def order_encoding_parts(
    block: Block,
    *,
    ascending: bool = True,
    nulls_first: bool = False,
) -> List[Tuple[jnp.ndarray, int]]:
    """order_encoding with static bit widths: (u64 key, bits) pairs whose
    MSB-first concatenation orders rows correctly.

    Bit widths come from static knowledge — dictionary size, or the type's
    value range (DATE fits 24 bits, INTEGER 32, ...). Narrow widths let
    pack_sort_keys() fuse several sort keys into one u64 word, which matters
    enormously on TPU: XLA's sort compile time roughly doubles per extra
    operand, so a 5-operand lexsort is minutes while a packed 1-2 operand
    sort is seconds.
    """
    t = block.type
    parts: List[Tuple[jnp.ndarray, int]] = []
    if isinstance(block.data, tuple):  # long decimal limbs
        hi, lo = block.data
        parts = [(_int_order_u64(hi), 64), (lo.astype(jnp.uint64), 64)]
    elif isinstance(t, (T.DoubleType, T.RealType)):
        parts = [(_float_order_u64(block.data), 64)]
    elif isinstance(t, T.BooleanType):
        parts = [(block.data.astype(jnp.uint64), 1)]
    elif t.is_dictionary_encoded and block.dictionary is not None:
        if len(block.dictionary) == 0:
            parts = [(jnp.zeros(block.data.shape, dtype=jnp.uint64), 1)]
        else:
            rank = jnp.asarray(block.dictionary.sort_rank())
            codes = jnp.clip(block.data, 0, len(block.dictionary) - 1)
            bits = max(1, (len(block.dictionary) - 1).bit_length())
            parts = [(rank[codes].astype(jnp.uint64), bits)]
    else:
        bits = 64
        if isinstance(t, T.DateType):
            bits = 24  # Presto DATE range (years 1582..9999) < 2^23 days
        elif isinstance(t, T.IntegerType):
            bits = 32
        elif isinstance(t, T.SmallintType):
            bits = 16
        elif isinstance(t, T.TinyintType):
            bits = 8
        x = block.data.astype(jnp.int64)
        if bits == 64:
            enc = x.astype(jnp.uint64) ^ _SIGN64
        else:
            lo_b, hi_b = -(1 << (bits - 1)), (1 << (bits - 1)) - 1
            enc = (
                jnp.clip(x, lo_b, hi_b) + jnp.int64(1 << (bits - 1))
            ).astype(jnp.uint64)
        parts = [(enc, bits)]

    if not ascending:
        parts = [
            ((~k if b == 64 else (jnp.uint64((1 << b) - 1) - k)), b)
            for k, b in parts
        ]

    null = block.nulls
    if null is None:
        null_key = jnp.zeros(parts[0][0].shape, dtype=jnp.uint64)
    elif nulls_first:
        null_key = jnp.where(null, jnp.uint64(0), jnp.uint64(1))
    else:
        null_key = jnp.where(null, jnp.uint64(1), jnp.uint64(0))
    return [(null_key, 1)] + parts


def pack_sort_keys(
    parts: List[Tuple[jnp.ndarray, int]]
) -> List[jnp.ndarray]:
    """Greedily pack (key, bits) pairs MSB-first into u64 words. Lexicographic
    order of the packed words equals lexicographic order of the unpacked key
    sequence (same static layout for every row)."""
    words: List[jnp.ndarray] = []
    acc = None
    used = 0
    for key, bits in parts:
        if acc is not None and used + bits > 64:
            words.append(acc)
            acc, used = None, 0
        if acc is None:
            acc = key.astype(jnp.uint64)
            used = bits
        else:
            acc = (acc << jnp.uint64(bits)) | key.astype(jnp.uint64)
            used += bits
    if acc is not None:
        words.append(acc)
    return words


def block_key_columns(
    blocks,
) -> Tuple[List[jnp.ndarray], List[Optional[jnp.ndarray]]]:
    """Equality encodings + null masks for a list of key Blocks (flattened:
    a long-decimal key contributes two uint64 columns sharing one null)."""
    cols: List[jnp.ndarray] = []
    nulls: List[Optional[jnp.ndarray]] = []
    for b in blocks:
        enc = equality_encoding(b)
        cols.extend(enc)
        nulls.extend([b.nulls] * len(enc))
    return cols, nulls
