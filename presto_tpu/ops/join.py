"""Equi-join kernel: sort-searchsorted hash join with fixed-capacity match
expansion.

Reference: presto-main operator/HashBuilderOperator.java builds a PagesIndex +
JoinHash (open-addressing over row addresses); operator/LookupJoinOperator
probes row-at-a-time via JoinProbe. Pointer-chasing again — the TPU design
replaces both with sorted arrays + vectorized binary search:

  build:  hash build keys -> sort build rows by hash (one lexsort)
  probe:  searchsorted(left/right) gives each probe row a candidate range
          [lo, hi); range width = candidate match count
  expand: fixed-capacity output; slot j belongs to probe row
          searchsorted(cumsum(counts), j) at offset j - prefix — a branch-free
          flattening of the variable-fanout probe loop
  verify: gathered candidate keys compared for true equality, so 64-bit hash
          collisions cost only wasted slots, never wrong results

Dynamic output cardinality is handled capacity+overflow-flag style (SURVEY
§8.2.1): callers size out_capacity, check ``overflow``, and retry bigger. The
planner picks build/probe sides (reference: AddExchanges join distribution);
outer-row emission (LEFT/RIGHT/FULL) and semi joins assemble from the match
statistics returned here (reference: LookupJoinOperators factories,
HashSemiJoinOperator).

The Pallas radix-partitioned kernels in presto_tpu/ops/pallas_join.py
(north-star requirement) replace the searchsorted range finder on TPU —
they produce the same per-probe-row [lo, lo+count) candidate ranges and
share expand_matches() below for verified expansion. The executor picks
per join (pallas_join_enabled=auto: Pallas on TPU, sort elsewhere).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax.numpy as jnp

from presto_tpu.ops import hashing as H


@dataclasses.dataclass
class JoinMatches:
    probe_idx: jnp.ndarray  # int64[out_cap] probe row per slot
    build_idx: jnp.ndarray  # int64[out_cap] build row per slot
    match: jnp.ndarray  # bool[out_cap] verified match
    probe_match_count: jnp.ndarray  # int64[probe_cap]
    build_matched: jnp.ndarray  # bool[build_cap]
    total_candidates: jnp.ndarray  # traced scalar (pre-verification)
    overflow: jnp.ndarray  # traced bool


def _fold_nulls(
    cols: Sequence[jnp.ndarray],
    nulls: Sequence[Optional[jnp.ndarray]],
    null_equals_null: bool,
) -> tuple[List[jnp.ndarray], jnp.ndarray]:
    """Returns (normalized key cols, any_null_disqualifies mask).

    SQL equi-join: a NULL key never matches (unless IS NOT DISTINCT FROM
    semantics, null_equals_null=True, where NULL matches NULL)."""
    n = cols[0].shape[0]
    any_null = jnp.zeros((n,), dtype=jnp.bool_)
    out_cols: List[jnp.ndarray] = []
    for c, nl in zip(cols, nulls):
        if nl is None:
            out_cols.append(c)
            if null_equals_null:
                # keep column counts symmetric across sides even when only
                # one side has a nulls mask
                out_cols.append(jnp.zeros((n,), dtype=jnp.uint64))
            continue
        out_cols.append(jnp.where(nl, jnp.uint64(0), c))
        if null_equals_null:
            out_cols.append(nl.astype(jnp.uint64))
        else:
            any_null = any_null | nl
    return out_cols, any_null


def build_join_index(
    build_cols: Sequence[jnp.ndarray],
    build_nulls: Sequence[Optional[jnp.ndarray]],
    build_valid: jnp.ndarray,
    *,
    null_equals_null: bool = False,
):
    """Build-side index, computed ONCE per join and reused by every probe
    page (reference: HashBuilderOperator's LookupSource shared across
    LookupJoinOperators). The index is a pytree: (folded key cols,
    validity, hash-sorted array, sort permutation).

    Build rows sort by hash with invalid rows poisoned to the max hash —
    ONE sort operand, not two: every extra u64 sort operand roughly
    doubles XLA:TPU's sort compile time, and the equality verification in
    the probe rejects any real-hash collisions with the poison value."""
    bcols, b_null_out = _fold_nulls(build_cols, build_nulls, null_equals_null)
    bvalid = build_valid & ~b_null_out
    bhash = H.hash_columns(bcols, [None] * len(bcols))
    poisoned = jnp.where(bvalid, bhash, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    perm = jnp.argsort(poisoned)
    return (tuple(bcols), bvalid, poisoned[perm], perm)


def hash_join_match(
    build_cols: Optional[Sequence[jnp.ndarray]],
    build_nulls: Optional[Sequence[Optional[jnp.ndarray]]],
    build_valid: Optional[jnp.ndarray],
    probe_cols: Sequence[jnp.ndarray],
    probe_nulls: Sequence[Optional[jnp.ndarray]],
    probe_valid: jnp.ndarray,
    out_capacity: int,
    *,
    null_equals_null: bool = False,
    index=None,
) -> JoinMatches:
    """Match probe rows against build rows on equality-encoded uint64 keys.

    Pass a prebuilt ``index`` (build_join_index) to skip re-sorting the
    build side per probe page."""
    if index is None:
        index = build_join_index(
            build_cols, build_nulls, build_valid,
            null_equals_null=null_equals_null,
        )
    bcols, bvalid, sorted_hash, perm = index

    pcols, p_null_out = _fold_nulls(probe_cols, probe_nulls, null_equals_null)
    pvalid = probe_valid & ~p_null_out
    phash = H.hash_columns(pcols, [None] * len(pcols))

    # method="sort" lowers to a concat-sort rank computation instead
    # of a log2(n)-iteration gather loop — measured 13x faster on TPU
    # (64ms vs 1.06s for lo+hi at 2M x 1M; round-4 microbench)
    lo = jnp.searchsorted(sorted_hash, phash, side="left", method="sort")
    hi = jnp.searchsorted(sorted_hash, phash, side="right", method="sort")
    counts = (hi - lo).astype(jnp.int64)

    return expand_matches(
        bcols, bvalid, perm, pcols, pvalid, lo, counts, out_capacity
    )


def expand_matches(
    bcols,
    bvalid: jnp.ndarray,
    perm: jnp.ndarray,
    pcols,
    pvalid: jnp.ndarray,
    lo: jnp.ndarray,
    counts: jnp.ndarray,
    out_capacity: int,
) -> JoinMatches:
    """Flatten per-probe-row candidate ranges [lo, lo+counts) over the
    hash-sorted build order `perm` into a fixed-capacity match list,
    verifying true key equality per slot. Shared tail of the sort join
    (searchsorted ranges) and the Pallas radix join (kernel-probed
    ranges) — the range *finder* is the only thing that differs."""
    build_cap = bvalid.shape[0]
    probe_cap = pvalid.shape[0]
    counts = jnp.where(pvalid, counts.astype(jnp.int64), 0)

    cum = jnp.cumsum(counts)
    total = cum[-1] if counts.shape[0] else jnp.int64(0)
    overflow = total > out_capacity

    slots = jnp.arange(out_capacity, dtype=jnp.int64)
    pid = jnp.searchsorted(cum, slots, side="right", method="sort")
    pid_c = jnp.clip(pid, 0, probe_cap - 1)
    prev = jnp.concatenate([jnp.zeros((1,), dtype=cum.dtype), cum[:-1]])
    off = slots - prev[pid_c]
    sorted_pos = jnp.clip(lo[pid_c].astype(jnp.int64) + off, 0, build_cap - 1)
    bid = perm[sorted_pos].astype(jnp.int64)

    in_range = slots < total
    match = in_range & pvalid[pid_c] & bvalid[bid]
    for bc, pc in zip(bcols, pcols):
        match = match & (bc[bid] == pc[pid_c])

    probe_match_count = (
        jnp.zeros((probe_cap + 1,), dtype=jnp.int64)
        .at[jnp.where(match, pid_c, probe_cap)]
        .add(1, mode="drop")[:probe_cap]
    )
    build_matched = (
        jnp.zeros((build_cap + 1,), dtype=jnp.bool_)
        .at[jnp.where(match, bid, build_cap)]
        .max(True, mode="drop")[:build_cap]
    )

    return JoinMatches(
        probe_idx=pid_c,
        build_idx=bid,
        match=match,
        probe_match_count=probe_match_count,
        build_matched=build_matched,
        total_candidates=total,
        overflow=overflow,
    )


def unique_join_lookup(
    bcols,
    bvalid: jnp.ndarray,
    perm: jnp.ndarray,
    pcols,
    pvalid: jnp.ndarray,
    lo: jnp.ndarray,
    hi: jnp.ndarray,
):
    """FK-join fast path: build keys are provably unique, so every
    probe row has <= 1 true match — no expansion, no output-capacity
    machinery; the output is the probe page itself plus gathered build
    columns (reference: LookupJoinOperator's unique-positions path).

    Only the FIRST candidate in the probe row's hash range is checked.
    A range wider than 1 means distinct unique keys collided in the
    u64 hash (~2^-64 per pair); ``collision`` flags it for the
    boosted-retry ladder, where eligibility falls back to the general
    expansion — wasted work, never wrong results.

    Returns (build_idx[int64, probe_cap], found[bool], collision)."""
    build_cap = bvalid.shape[0]
    pos = jnp.clip(lo.astype(jnp.int64), 0, build_cap - 1)
    bid = perm[pos].astype(jnp.int64)
    in_range = (hi - lo) >= 1
    found = in_range & pvalid & bvalid[bid]
    for bc, pc in zip(bcols, pcols):
        found = found & (bc[bid] == pc)
    collision = jnp.any(pvalid & ((hi - lo) > 1))
    return bid, found, collision


def semi_join_mask(
    build_cols: Sequence[jnp.ndarray],
    build_nulls: Sequence[Optional[jnp.ndarray]],
    build_valid: jnp.ndarray,
    probe_cols: Sequence[jnp.ndarray],
    probe_nulls: Sequence[Optional[jnp.ndarray]],
    probe_valid: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-probe-row (has_match, null_result) for IN / semi-join predicates.

    Reference: operator/HashSemiJoinOperator.java + SetBuilderOperator.
    null_result marks SQL three-valued unknown: probe key NULL, or no match
    while the build set contains a NULL (x IN (...NULL...) is NULL, not
    false).
    """
    bcols, b_null = _fold_nulls(build_cols, build_nulls, False)
    pcols, p_null = _fold_nulls(probe_cols, probe_nulls, False)
    bvalid = build_valid & ~b_null
    build_has_null = jnp.any(build_valid & b_null)

    none_nulls = [None] * len(bcols)
    bhash = H.hash_columns(bcols, none_nulls)
    phash = H.hash_columns(pcols, none_nulls)
    poisoned = jnp.where(bvalid, bhash, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    perm = jnp.argsort(poisoned)
    sorted_hash = poisoned[perm]
    lo = jnp.searchsorted(sorted_hash, phash, side="left", method="sort")
    hi = jnp.searchsorted(sorted_hash, phash, side="right", method="sort")

    # verify within a bounded window (hash collisions beyond window are
    # astronomically unlikely; window also bounds compile size)
    WINDOW = 4
    has_match = jnp.zeros(probe_valid.shape, dtype=jnp.bool_)
    build_cap = bvalid.shape[0]
    for w in range(WINDOW):
        pos = jnp.clip(lo + w, 0, build_cap - 1)
        bid = perm[pos]
        ok = (lo + w < hi) & bvalid[bid]
        for bc, pc in zip(bcols, pcols):
            ok = ok & (bc[bid] == pc)
        has_match = has_match | ok
    # fall back for pathological windows: any remaining candidates counted as
    # match only if hashes matched exactly (collision risk accepted 2^-64)
    has_match = has_match | ((hi - lo) > WINDOW)

    null_result = probe_valid & (
        p_null | (~has_match & build_has_null)
    )
    return probe_valid & has_match & ~p_null, null_result
