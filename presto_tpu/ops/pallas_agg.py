"""Pallas TPU segmented-reduction aggregation kernel (north-star:
"HashAggregationOperator as a segmented reduction", SURVEY §8.2.3).

The contract is shared with ops/agg._sorted_aggregate: rows arrive in
GROUP-SORTED order (GroupbyResult.sort_perm / gid_sorted from
compute_groups_sorted), invalid/non-contributing rows carry a group id
outside [0, num_groups) so they drop out of every reduction for free.
The kernel grid-blocks the sorted rows on the shapes ladder and
accumulates per-group partial sums into the SAME out_ref across
sequential grid steps (TPU grid iterations are sequential, so out_ref
is a legal accumulator; initialized at program_id == 0). Each step
reduces its block with one one-hot dot_general — the MXU-shaped
segmented reduction — instead of a scatter.

int64 exactness: TPU lanes are 32-bit and the dot accumulates int32,
so i64 values travel as (lo32, hi32) int32 words and are decomposed
in-kernel into 16 unsigned 4-bit limbs; per-limb group sums stay under
2^31 for any input up to 2^27 rows, and the host-side recombination
with wrapping u64 shifts reproduces the two's-complement int64 sum
exactly (same decomposition argument as ops/agg._mm_sum_int).

Lowering status: the kernel is written TPU-shaped (2-D iota, int8xint8
dot with int32 accumulation, block ladder), but like the radix join
probe it is interpret-verified only on this toolchain — the executor
engages it under pallas_join_enabled=true/force and always in
interpret mode (`agg_lowers_on_tpu()` is False until the in-kernel
one-hot dot is validated on hardware). jnp fallback: ops/agg.aggregate
computes identical results and stays the default everywhere.

Reference: presto-main operator/aggregation/* accumulate loops — the
per-group accumulation re-expressed as a blocked one-hot matmul.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.ops import agg as A
from presto_tpu.ops.pallas_join import _split64

# one grid step reduces this many sorted rows (8 sublanes x 128 lanes x
# 2 groups-of-lanes — small enough that the (B, G) one-hot stays well
# under VMEM at the group cap below)
BLOCK_ROWS = 2048
# group capacity ceiling: (BLOCK_ROWS x G) int8 one-hot + (16, G) int32
# accumulator must fit VMEM; 4096 matches ops/agg.MATMUL_AGG_MAX_GROUPS
# so the Pallas tier covers exactly the shapes the jnp MXU tier does
PALLAS_AGG_MAX_GROUPS = A.MATMUL_AGG_MAX_GROUPS

_N_LIMBS = 16  # 16 x 4-bit limbs cover the full u64 bit pattern

# kinds the segmented-reduction kernel computes; everything else falls
# back to ops/agg.aggregate (float SUM keeps the jnp path for
# accumulation-order stability, MIN/MAX/ANY are segment-gather shaped)
SUPPORTED_KINDS = (A.SUM, A.COUNT, A.COUNT_STAR, A.BOOL_OR, A.BOOL_AND)


def agg_lowers_on_tpu() -> bool:
    """Whether the segmented-reduction kernel lowers through Mosaic on
    the current toolchain. Not yet: the in-kernel broadcasted-iota
    one-hot + int8 dot_general is unvalidated on hardware, so the
    kernel runs interpret-only (the CPU test path), exactly like the
    radix join probe (ops/pallas_join.layout_lowers_on_tpu)."""
    return False


def _limb_kernel(ids_ref, vlo_ref, vhi_ref, out_ref, *,
                 num_groups: int):
    """One grid step: 16-limb decomposition of the block's (lo, hi)
    words, one int8 x int8 -> int32 dot against the block's one-hot,
    accumulated into the persistent (16, G) out_ref."""
    from jax.experimental import pallas as pl

    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    ids = ids_ref[:]
    b = ids.shape[0]
    onehot = (
        ids[:, None]
        == jax.lax.broadcasted_iota(jnp.int32, (b, num_groups), 1)
    ).astype(jnp.int8)
    lo = vlo_ref[:].astype(jnp.uint32)
    hi = vhi_ref[:].astype(jnp.uint32)
    limbs = jnp.concatenate(
        [
            jnp.stack(
                [((w >> jnp.uint32(4 * k)) & jnp.uint32(0xF)).astype(
                    jnp.int8) for k in range(8)]
            )
            for w in (lo, hi)
        ]
    )  # (16, B)
    acc = jax.lax.dot_general(
        limbs, onehot, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out_ref[:, :] += acc


def _segmented_limb_sums(
    ids: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray,
    num_groups: int, *, interpret: bool, block_rows: int = BLOCK_ROWS,
) -> jnp.ndarray:
    """(16, num_groups) int32 per-limb group sums over rows whose id
    lies in [0, num_groups); everything else contributes zero."""
    from jax.experimental import pallas as pl

    n = ids.shape[0]
    pad = (-n) % block_rows
    if pad:
        # pad rows route to the dropped id == num_groups
        ids = jnp.concatenate(
            [ids, jnp.full((pad,), num_groups, jnp.int32)]
        )
        zero = jnp.zeros((pad,), jnp.int32)
        lo = jnp.concatenate([lo, zero])
        hi = jnp.concatenate([hi, zero])
    nblocks = ids.shape[0] // block_rows
    blk = pl.BlockSpec((block_rows,), lambda j: (j,))
    kernel = functools.partial(_limb_kernel, num_groups=num_groups)
    return pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[blk, blk, blk],
        out_specs=pl.BlockSpec(
            (_N_LIMBS, num_groups), lambda j: (0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (_N_LIMBS, num_groups), jnp.int32
        ),
        interpret=interpret,
    )(ids, lo, hi)


def _recombine_i64(limb_sums: jnp.ndarray) -> jnp.ndarray:
    """Wrapping u64 recombination of (16, G) per-limb sums back into the
    exact two's-complement int64 group totals."""
    shifts = jnp.uint64(1) << (
        jnp.uint64(4) * jnp.arange(_N_LIMBS, dtype=jnp.uint64)
    )
    total = jnp.sum(
        limb_sums.astype(jnp.int64).astype(jnp.uint64) * shifts[:, None],
        axis=0, dtype=jnp.uint64,
    )
    return total.astype(jnp.int64)


def segmented_sum_i64(
    values: jnp.ndarray, ids: jnp.ndarray, num_groups: int, *,
    interpret: bool = True,
) -> jnp.ndarray:
    """Exact int64 per-group sum of `values` (any integer dtype) over
    group ids; rows with id outside [0, num_groups) contribute 0."""
    lo, hi = _split64(values.astype(jnp.int64))
    limbs = _segmented_limb_sums(
        ids.astype(jnp.int32), lo, hi, num_groups, interpret=interpret
    )
    return _recombine_i64(limbs)


def segmented_count(
    ids: jnp.ndarray, num_groups: int, *, interpret: bool = True
) -> jnp.ndarray:
    """int64 per-group row count (ids outside [0, num_groups) drop)."""
    ones = jnp.ones(ids.shape, jnp.int64)
    return segmented_sum_i64(ones, ids, num_groups,
                             interpret=interpret)


def supported(kind: str, num_groups: int, data) -> bool:
    """Whether this (kind, shape) runs on the segmented-reduction
    kernel; callers fall back to ops/agg.aggregate otherwise."""
    if kind not in SUPPORTED_KINDS or num_groups > PALLAS_AGG_MAX_GROUPS:
        return False
    if isinstance(data, tuple):  # long-decimal limb pairs
        return False
    if kind == A.SUM:
        return data is not None and jnp.issubdtype(
            data.dtype, jnp.integer
        )
    return True


def aggregate(
    groups,  # ops/agg.GroupbyResult
    kind: str,
    out_capacity: int,
    data: Optional[jnp.ndarray] = None,
    nulls: Optional[jnp.ndarray] = None,
    *,
    interpret: bool = True,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Drop-in for ops/agg.aggregate over the supported kinds: same SQL
    semantics (SUM over zero non-null inputs yields NULL, COUNT yields
    0), same (values[out_capacity], null_mask) shape, group totals from
    the Pallas kernel instead of segment ops. Unsupported kinds
    delegate to the jnp path so callers need no second dispatch."""
    if not supported(kind, out_capacity, data):
        return A.aggregate(groups, kind, out_capacity, data, nulls)
    contributing = groups.row_valid
    if nulls is not None:
        contributing = contributing & ~nulls
    cids = jnp.where(
        contributing, groups.group_ids, out_capacity
    ).astype(jnp.int32)
    if kind == A.COUNT_STAR:
        ids = jnp.where(
            groups.row_valid, groups.group_ids, out_capacity
        ).astype(jnp.int32)
        return segmented_count(ids, out_capacity,
                               interpret=interpret), None
    ncontrib = segmented_count(cids, out_capacity, interpret=interpret)
    empty = ncontrib == 0
    if kind == A.COUNT:
        return ncontrib, None
    if kind == A.SUM:
        totals = segmented_sum_i64(
            data, cids, out_capacity, interpret=interpret
        )
        return totals.astype(data.dtype), empty
    # BOOL_OR / BOOL_AND: count the true contributing rows
    trues = segmented_count(
        jnp.where(data.astype(jnp.bool_), cids,
                  jnp.int32(out_capacity)),
        out_capacity, interpret=interpret,
    )
    if kind == A.BOOL_OR:
        return (trues > 0), empty
    return (trues == ncontrib) & ~empty, empty
