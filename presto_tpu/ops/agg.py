"""Group-by as segmented reduction (the TPU replacement for the reference's
open-addressing hash tables).

Reference: presto-main operator/HashAggregationOperator.java drives
operator/GroupByHash.java (BigintGroupByHash fast path /
MultiChannelGroupByHash) with per-row probe/insert — pointer-chasing that maps
terribly to a vector unit. TPU-native design (BASELINE north-star: "hash
aggregation as segmented reduction"):

  - **sorted path** (general): lexsort rows by null-aware key encodings, mark
    group boundaries where adjacent keys differ, group id = prefix-sum of
    boundaries, then jax.ops.segment_* reductions with indices_are_sorted.
    O(n log n) but fully vectorized, no collisions, deterministic.
  - **dense path** (small key spaces, e.g. dictionary-coded flag columns):
    group id computed arithmetically from codes, direct segment reductions
    with a static group count — this is the Q1 fast path, analogous to the
    reference's BigintGroupByHash small-range optimization.

Output is fixed-capacity with a group validity mask plus an ``overflow`` flag
(true if real group count exceeded capacity) so drivers can re-run with a
larger capacity — the compiled-branch escape for dynamic cardinality
(SURVEY §8.2.1).

Partial/final split (reference: AggregationNode.Step PARTIAL/FINAL) is
expressed by running the same primitives over partial-state pages with merge
kinds (sum->sum, count->sum, min->min, max->max).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

# Primitive accumulator kinds. Compound SQL aggregates decompose into these
# (avg -> sum+count with a finalize divide; reference analog: the
# @AggregationFunction state/input/combine/output decomposition).
SUM = "sum"
COUNT = "count"  # counts non-null inputs
COUNT_STAR = "count_star"
MIN = "min"
MAX = "max"
ANY = "any"  # arbitrary non-null value (used for grouped key passthrough)
BOOL_OR = "bool_or"
BOOL_AND = "bool_and"
# HyperLogLog kinds: tuple-data states, handled by the executor kernels
# against ops/hll.py (not by aggregate() below)
HLL_INSERT = "hll_insert"
HLL_MERGE = "hll_merge"


@dataclasses.dataclass(frozen=True)
class AggInput:
    kind: str
    # data/nulls indices into the arrays passed alongside; COUNT_STAR has none
    has_input: bool = True


def _null_aware_sort_keys(
    key_cols: Sequence[jnp.ndarray],
    key_nulls: Sequence[Optional[jnp.ndarray]],
    valid: jnp.ndarray,
) -> List[jnp.ndarray]:
    """Sort keys: validity first (valid rows to front), then per key column a
    (null-flag, normalized-value) pair so SQL NULLs form their own group."""
    keys: List[jnp.ndarray] = [
        jnp.where(valid, jnp.uint64(0), jnp.uint64(1))
    ]
    for col, null in zip(key_cols, key_nulls):
        if null is None:
            keys.append(jnp.zeros(col.shape, dtype=jnp.uint64))
            keys.append(col)
        else:
            keys.append(jnp.where(null, jnp.uint64(1), jnp.uint64(0)))
            keys.append(jnp.where(null, jnp.uint64(0), col))
    return keys


def _lexsort(keys: List[jnp.ndarray]) -> jnp.ndarray:
    # jnp.lexsort: LAST key is primary; ours are listed primary-first.
    return jnp.lexsort(tuple(reversed(keys)))


@dataclasses.dataclass
class GroupbyResult:
    group_ids: jnp.ndarray  # int64[cap_in] group id per input row (clipped)
    row_valid: jnp.ndarray  # contributing rows (input valid)
    rep_index: jnp.ndarray  # int64[out_cap] representative input row per group
    group_valid: jnp.ndarray  # bool[out_cap]
    num_groups: jnp.ndarray  # traced scalar
    overflow: jnp.ndarray  # traced bool
    # dense path only: per-key-column code-space sizes. Group id is the
    # mixed-radix encoding of the key codes, so callers can synthesize
    # key columns arithmetically from arange(out_cap) instead of
    # gathering through rep_index — XLA then dead-code-eliminates the
    # rep scatter entirely.
    dense_sizes: Optional[Tuple[int, ...]] = None
    # sorted path only: the group-sort permutation and the per-SORTED-
    # row group id (invalid rows = out_cap, sorted to the tail). With
    # these, aggregate() computes SUM/COUNT via gather+cumsum+boundary
    # differences — no scatter at all (scatter: ~14M rows/s on TPU;
    # sort+cumsum: ~250M rows/s). The input-order group_ids scatter
    # above is then dead code XLA eliminates.
    sort_perm: Optional[jnp.ndarray] = None
    gid_sorted: Optional[jnp.ndarray] = None
    # group g occupies sorted positions [seg_start[g], seg_end[g]);
    # computed once per page with one scatter-min (group ids from the
    # sort are consecutive, so end[g] = start[g+1])
    seg_start: Optional[jnp.ndarray] = None
    seg_end: Optional[jnp.ndarray] = None


def compute_groups_sorted(
    key_cols: Sequence[jnp.ndarray],
    key_nulls: Sequence[Optional[jnp.ndarray]],
    valid: jnp.ndarray,
    out_capacity: int,
) -> GroupbyResult:
    """Assign group ids via sort; no aggregation yet.

    Reference analog: GroupByHash.getGroupIds(Page) — returns a group id per
    input position; aggregation happens against those ids.
    """
    from presto_tpu.ops import keys as K
    from presto_tpu.ops.sort import packed_argsort

    # bit-pack (validity, per-key null flag + word) and sort via LSD
    # chained single-word argsorts: one multi-operand lexsort compiles
    # for minutes on XLA:TPU, k two-operand argsorts compile in seconds
    parts = [(jnp.where(valid, jnp.uint64(0), jnp.uint64(1)), 1)]
    cmp_words: List[jnp.ndarray] = []
    for col, null in zip(key_cols, key_nulls):
        if null is not None:
            nw = null.astype(jnp.uint64)
            parts.append((nw, 1))
            cmp_words.append(nw)
            col = jnp.where(null, jnp.uint64(0), col)
        parts.append((col, 64))
        cmp_words.append(col)
    words = K.pack_sort_keys(parts)
    perm = packed_argsort(words, valid.shape[0])
    svalid = valid[perm]

    diff = jnp.zeros(valid.shape, dtype=jnp.bool_)
    for k in cmp_words:
        sk = k[perm]
        d = jnp.concatenate(
            [jnp.ones((1,), dtype=jnp.bool_), sk[1:] != sk[:-1]]
        )
        diff = diff | d
    boundary = svalid & diff
    gid_sorted = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    num_groups = jnp.sum(boundary.astype(jnp.int64))
    overflow = num_groups > out_capacity

    # scatter sorted-order group ids back to input order
    gids = jnp.zeros(valid.shape, dtype=jnp.int64)
    gids = gids.at[perm].set(jnp.clip(gid_sorted, 0, out_capacity - 1))

    # group g occupies sorted positions [start[g], end[g]). Group ids
    # from the sort are CONSECUTIVE (cumsum of boundaries), so one
    # scatter-min of boundary positions gives every start and
    # end[g] = start[g+1] (n_valid for the last group). This is the
    # only scatter the sorted path pays per page; every reduction then
    # runs scatter-free on [start, end) cumsum differences.
    gid_x = jnp.where(svalid, gid_sorted, out_capacity)
    n = valid.shape[0]
    idxs = jnp.arange(n, dtype=jnp.int64)
    n_valid = jnp.sum(svalid.astype(jnp.int64))
    start = (
        jnp.full((out_capacity + 1,), jnp.int64(n))
        .at[jnp.where(boundary & (gid_sorted < out_capacity),
                      gid_sorted, out_capacity)]
        .min(idxs, mode="drop")
    )
    start = jnp.minimum(start, n_valid)
    seg_start = start[:out_capacity]
    seg_end = jnp.concatenate(
        [start[1:out_capacity], n_valid[None]]
    )
    seg_end = jnp.maximum(seg_start, seg_end)
    rep = perm[jnp.clip(seg_start, 0, n - 1)].astype(jnp.int64)
    group_valid = jnp.arange(out_capacity, dtype=jnp.int64) < num_groups
    return GroupbyResult(
        group_ids=gids,
        row_valid=valid,
        rep_index=rep,
        group_valid=group_valid,
        num_groups=num_groups,
        overflow=overflow,
        sort_perm=perm,
        gid_sorted=gid_x,
        seg_start=seg_start,
        seg_end=seg_end,
    )


def compute_groups_dense(
    group_ids: jnp.ndarray,
    valid: jnp.ndarray,
    num_groups: int,
    out_capacity: Optional[int] = None,
    sizes: Optional[Tuple[int, ...]] = None,
) -> GroupbyResult:
    """Group ids already computed arithmetically (e.g. from dictionary codes:
    gid = code_a * |dict_b| + code_b). Static group count, no sort, no hash
    table — the Q1 fast path (reference analog: BigintGroupByHash's
    small-range optimization). Output arrays are padded to out_capacity
    (>= num_groups) so callers can mix this with the hashed path.
    """
    cap = out_capacity or num_groups
    assert cap >= num_groups
    # Segment ops (the rep scatter below) run over num_groups+1 segments,
    # NOT cap+1: segment count must match the true key space (6 for Q1),
    # never the caller's generic capacity.
    ids = jnp.where(valid, group_ids.astype(jnp.int64), num_groups)
    if _mm_backend_ok() and num_groups <= MATMUL_AGG_MAX_GROUPS:
        counts = _mm_count(ids, num_groups)
    else:
        counts = jax.ops.segment_sum(
            jnp.ones(valid.shape, dtype=jnp.int64),
            ids,
            num_segments=num_groups + 1,
        )[:num_groups]
    pad = cap - num_groups
    group_valid = jnp.pad(counts > 0, (0, pad))
    # representative row per group: min input index holding that gid
    idx = jnp.arange(valid.shape[0], dtype=jnp.int64)
    rep = jax.ops.segment_min(
        jnp.where(valid, idx, jnp.int64(2**62)),
        ids,
        num_segments=num_groups + 1,
    )[:num_groups]
    rep = jnp.pad(jnp.clip(rep, 0, valid.shape[0] - 1), (0, pad))
    return GroupbyResult(
        group_ids=jnp.clip(ids, 0, cap - 1),
        row_valid=valid,
        rep_index=rep,
        group_valid=group_valid,
        num_groups=jnp.sum(group_valid.astype(jnp.int64)),
        overflow=jnp.asarray(False),
        dense_sizes=sizes,
    )


def compute_groups_hashed(
    key_cols: Sequence[jnp.ndarray],
    key_nulls: Sequence[Optional[jnp.ndarray]],
    valid: jnp.ndarray,
    out_capacity: int,
    max_iters: int = 64,
) -> GroupbyResult:
    """Group assignment via a vectorized linear-probing hash table — the
    TPU-native GroupByHash (reference: operator/GroupByHash.java's
    open-addressing probe/insert, re-expressed as data-parallel rounds).

    Each round, every unsettled row claims its current slot with a
    scatter-min of its row index (deterministic winner), then checks whether
    the slot's owner carries an equal key; matching rows settle, losers probe
    the next slot. Equal-key rows start at the same hash slot and observe the
    same owners, so they advance in lockstep and can never split into two
    groups; scatter-min is commutative, so the whole procedure is
    deterministic. Compile cost is a handful of gather/scatter ops inside one
    while_loop body — versus a multi-operand u64 lexsort whose XLA:TPU
    comparator blows up exponentially in key count (measured: 17s -> 66s
    compile going from 1 to 2 u64 sort operands).

    Table capacity is 2x out_capacity (load factor <= 0.5 when the group
    count fits). Unresolved rows after max_iters or group count overflow set
    the overflow flag — callers retry with doubled capacity (SURVEY §8.2.1).
    """
    from presto_tpu.ops import hashing as H

    n = valid.shape[0]
    cols: List[jnp.ndarray] = []
    for c, nl in zip(key_cols, key_nulls):
        if nl is None:
            cols.append(c.astype(jnp.uint64))
        else:
            # fold the null flag in as its own word: NULL groups with NULL
            cols.append(jnp.where(nl, jnp.uint64(0), c.astype(jnp.uint64)))
            cols.append(nl.astype(jnp.uint64))
    h = H.hash_columns(cols, [None] * len(cols))

    cap = max(2 * out_capacity, 16)
    cap = 1 << (cap - 1).bit_length()  # pow2 for mask probing
    mask = jnp.int64(cap - 1)
    BIG = jnp.int64(n)
    row_idx = jnp.arange(n, dtype=jnp.int64)
    init_slot = (h & jnp.uint64(cap - 1)).astype(jnp.int64)

    def key_eq_owner(owner, slot):
        """settled mask: does the row's slot owner carry an equal key?"""
        win = owner[slot]
        winc = jnp.clip(win, 0, n - 1)
        ok = win < n
        for c in cols:
            ok = ok & (c[winc] == c)
        return valid & ok

    def cond(state):
        owner, slot, it = state
        unsettled = valid & ~key_eq_owner(owner, slot)
        return jnp.any(unsettled) & (it < max_iters)

    def body(state):
        owner, slot, it = state
        settled = key_eq_owner(owner, slot)
        claim = jnp.where(settled | ~valid, BIG, row_idx)
        owner = owner.at[slot].min(claim)
        settled2 = key_eq_owner(owner, slot)
        slot = jnp.where(settled2 | ~valid, slot, (slot + 1) & mask)
        return owner, slot, it + 1

    owner0 = jnp.full((cap,), BIG, dtype=jnp.int64)
    owner, slot, _ = jax.lax.while_loop(
        cond, body, (owner0, init_slot, jnp.int64(0))
    )

    settled = key_eq_owner(owner, slot)
    unresolved = jnp.any(valid & ~settled)
    # occupied slots = slots some row actually settled in (ghost claims from
    # rows that probed past are excluded by deriving occupancy from rows)
    used = (
        jnp.zeros((cap + 1,), dtype=jnp.bool_)
        .at[jnp.where(settled, slot, cap)]
        .set(True, mode="drop")[:cap]
    )
    gid_slot = jnp.cumsum(used.astype(jnp.int64)) - 1
    num_groups = jnp.sum(used.astype(jnp.int64))
    overflow = unresolved | (num_groups > out_capacity)

    gids = jnp.clip(gid_slot[slot], 0, out_capacity - 1)
    rep = (
        jnp.full((out_capacity + 1,), jnp.int64(2**62))
        .at[jnp.where(settled, gids, out_capacity)]
        .min(row_idx, mode="drop")[:out_capacity]
    )
    rep = jnp.clip(rep, 0, n - 1)
    group_valid = jnp.arange(out_capacity, dtype=jnp.int64) < num_groups
    return GroupbyResult(
        group_ids=gids,
        row_valid=valid & settled,
        rep_index=rep,
        group_valid=group_valid,
        num_groups=num_groups,
        overflow=overflow,
    )


# Above this group capacity the one-hot matmul aggregation falls back to
# XLA scatter. n x G int8 MACs are effectively free on the MXU up to here
# (measured: G=4096 over 256k rows adds < 1ms to a launch; scatter costs
# ~80ms per 1M rows regardless of G).
MATMUL_AGG_MAX_GROUPS = 4096


def _onehot(ids: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    """n x G int8 one-hot of group ids. Rows whose id is outside
    [0, num_groups) are all-zero — callers route invalid/null rows to
    id == num_groups so they drop out of every matmul for free. XLA
    fuses the compare into the dot; the n x G matrix never hits HBM."""
    return (
        ids[:, None] == jnp.arange(num_groups, dtype=ids.dtype)[None, :]
    ).astype(jnp.int8)


def _mm_count(ids: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    """Per-group row count as an MXU matmul: ones-vector x one-hot with
    int32 accumulation (exact for any page <= 2^31 rows)."""
    ones = jnp.ones((1, ids.shape[0]), dtype=jnp.int8)
    acc = jax.lax.dot_general(
        ones, _onehot(ids, num_groups), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )[0]
    return acc.astype(jnp.int64)


def _mm_sum_int(
    data: jnp.ndarray, ids: jnp.ndarray, num_groups: int
) -> jnp.ndarray:
    """Exact int64 per-group sum on the MXU (the scatter replacement
    that makes hash aggregation MXU-bound instead of scatter-bound).

    Decompose each value into 16 unsigned 4-bit limbs of its u64 bit
    pattern, matmul all limbs against the one-hot in one s8xs8->s32
    dot (per-limb group sums <= 15 * n < 2^31 for any n <= 2^27), then
    recombine with wrapping u64 shifts — addition mod 2^64 distributes
    over the limb decomposition, so the result equals the two's-
    complement int64 sum exactly, negatives included.

    int64<->uint64 moves use astype (two's-complement wrapping
    conversion: identical bits) rather than bitcast_convert_type — the
    axon compile service SIGSEGVs on 64-bit bitcasts (see
    exec/executor._collect_encode), and astype avoids the op class
    entirely."""
    u = data.astype(jnp.int64).astype(jnp.uint64)
    limbs = jnp.stack(
        [((u >> jnp.uint64(4 * k)) & jnp.uint64(0xF)).astype(jnp.int8)
         for k in range(16)]
    )  # (16, n)
    acc = jax.lax.dot_general(
        limbs, _onehot(ids, num_groups), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (16, G)
    shifts = (jnp.uint64(1) << (jnp.uint64(4)
                                * jnp.arange(16, dtype=jnp.uint64)))
    total = jnp.sum(
        acc.astype(jnp.uint64) * shifts[:, None], axis=0,
        dtype=jnp.uint64,
    )
    return total.astype(jnp.int64)


_MM_BACKEND: Optional[bool] = None


def _mm_backend_ok() -> bool:
    """One-hot matmul aggregation only where the compiler fuses the
    n x G one-hot into the dot (MXU path). XLA:CPU materializes it —
    gigabytes at bench shapes — so CPU (tests, oracle children) keeps
    the scatter path, which computes identical results.
    PRESTO_TPU_MM_AGG=1/0 overrides (CPU parity tests force it on
    tiny shapes)."""
    global _MM_BACKEND
    if _MM_BACKEND is None:
        import os

        v = os.environ.get("PRESTO_TPU_MM_AGG")
        if v is not None:
            _MM_BACKEND = v == "1"
        else:
            _MM_BACKEND = jax.default_backend() == "tpu"
    return _MM_BACKEND


def _mm_eligible(kind: str, num_groups: int, data) -> bool:
    if num_groups > MATMUL_AGG_MAX_GROUPS or not _mm_backend_ok():
        return False
    if kind in (COUNT, COUNT_STAR, BOOL_OR, BOOL_AND):
        return True
    return kind == SUM and data is not None and jnp.issubdtype(
        data.dtype, jnp.integer
    )


def _minmax_identity(dtype, is_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if is_min else -jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(is_min, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if is_min else info.min, dtype=dtype)


def _sorted_aggregate(
    groups: GroupbyResult,
    kind: str,
    out_capacity: int,
    data: Optional[jnp.ndarray],
    nulls: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Scatter-free segmented reduction over the sorted group layout:
    gather rows into group order, one cumulative sum, difference at
    group boundaries (start positions come from a method='sort'
    searchsorted over the sorted group ids). Exact for integers
    (prefix sums stay in-range: |page total| < 2^63); float SUM keeps
    the scatter path for accumulation-order stability."""
    perm, gidx = groups.sort_perm, groups.gid_sorted
    n = perm.shape[0]
    contributing_sorted = gidx < out_capacity
    if nulls is not None:
        contributing_sorted = contributing_sorted & ~nulls[perm]

    if kind in (SUM, BOOL_OR, BOOL_AND):
        assert data is not None
        ds = data[perm]
    if kind == SUM:
        x = jnp.where(contributing_sorted, ds,
                      jnp.zeros((), dtype=data.dtype))
    elif kind in (BOOL_OR, BOOL_AND):
        x = jnp.where(
            contributing_sorted & ds.astype(jnp.bool_),
            jnp.int64(1), jnp.int64(0),
        )
    else:  # COUNT / COUNT_STAR
        x = contributing_sorted.astype(jnp.int64)

    csum = jnp.cumsum(x)
    start, end = groups.seg_start, groups.seg_end
    pcs = jnp.concatenate([jnp.zeros((1,), dtype=csum.dtype), csum])
    totals = pcs[end] - pcs[start]

    if kind == COUNT_STAR:
        return totals, None
    ncontrib = (end - start).astype(jnp.int64)
    if nulls is not None:
        pcn = jnp.concatenate([
            jnp.zeros((1,), dtype=jnp.int64),
            jnp.cumsum(contributing_sorted.astype(jnp.int64)),
        ])
        ncontrib = pcn[end] - pcn[start]
    empty = ncontrib == 0
    if kind == COUNT:
        return ncontrib, None
    if kind == BOOL_OR:
        return (totals > 0), empty
    if kind == BOOL_AND:
        return (totals == ncontrib) & ~empty, empty
    return totals, empty  # SUM


def aggregate(
    groups: GroupbyResult,
    kind: str,
    out_capacity: int,
    data: Optional[jnp.ndarray] = None,
    nulls: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """One primitive aggregation over assigned group ids.

    Returns (values[out_capacity], null_mask or None). SQL semantics: SUM /
    MIN / MAX / ANY over zero non-null inputs yield NULL; COUNT yields 0.
    """
    ids = jnp.where(groups.row_valid, groups.group_ids, out_capacity)
    nseg = out_capacity + 1
    mm = _mm_eligible(kind, out_capacity, data)
    if (groups.sort_perm is not None and not mm
            and kind in (SUM, COUNT, COUNT_STAR, BOOL_OR, BOOL_AND)
            and (data is None or not isinstance(data, tuple))
            and (kind != SUM
                 or jnp.issubdtype(data.dtype, jnp.integer))):
        return _sorted_aggregate(groups, kind, out_capacity, data, nulls)

    if kind == COUNT_STAR:
        if mm:
            return _mm_count(ids, out_capacity), None
        ones = jnp.ones(groups.row_valid.shape, dtype=jnp.int64)
        out = jax.ops.segment_sum(ones, ids, num_segments=nseg)[:out_capacity]
        return out, None

    assert data is not None
    contributing = groups.row_valid
    if nulls is not None:
        contributing = contributing & ~nulls
    cids = jnp.where(contributing, groups.group_ids, out_capacity)
    if mm:
        ncontrib = _mm_count(cids, out_capacity)
    else:
        ncontrib = jax.ops.segment_sum(
            jnp.ones(contributing.shape, dtype=jnp.int64),
            cids,
            num_segments=nseg,
        )[:out_capacity]
    empty = ncontrib == 0

    if kind == COUNT:
        return ncontrib, None
    if kind == SUM:
        if mm:
            out = _mm_sum_int(data, cids, out_capacity)
            return out.astype(data.dtype), empty
        zero = jnp.zeros((), dtype=data.dtype)
        out = jax.ops.segment_sum(
            jnp.where(contributing, data, zero), cids, num_segments=nseg
        )[:out_capacity]
        return out, empty
    if kind == BOOL_OR and mm:
        trues = _mm_count(
            jnp.where(data.astype(jnp.bool_), cids, out_capacity),
            out_capacity,
        )
        return (trues > 0), empty
    if kind == BOOL_AND and mm:
        trues = _mm_count(
            jnp.where(data.astype(jnp.bool_), cids, out_capacity),
            out_capacity,
        )
        return (trues == ncontrib) & ~empty, empty
    if kind in (MIN, MAX):
        ident = _minmax_identity(data.dtype, kind == MIN)
        filled = jnp.where(contributing, data, ident)
        seg = jax.ops.segment_min if kind == MIN else jax.ops.segment_max
        out = seg(filled, cids, num_segments=nseg)[:out_capacity]
        out = jnp.where(empty, jnp.zeros((), dtype=data.dtype), out)
        return out, empty
    if kind == ANY:
        # value at min contributing row index
        idx = jnp.arange(data.shape[0], dtype=jnp.int64)
        first = jax.ops.segment_min(
            jnp.where(contributing, idx, jnp.int64(2**62)),
            cids,
            num_segments=nseg,
        )[:out_capacity]
        first = jnp.clip(first, 0, data.shape[0] - 1)
        return data[first], empty
    if kind == BOOL_OR:
        out = jax.ops.segment_max(
            jnp.where(contributing, data.astype(jnp.int32), 0),
            cids,
            num_segments=nseg,
        )[:out_capacity]
        return out.astype(jnp.bool_), empty
    if kind == BOOL_AND:
        out = jax.ops.segment_min(
            jnp.where(contributing, data.astype(jnp.int32), 1),
            cids,
            num_segments=nseg,
        )[:out_capacity]
        return out.astype(jnp.bool_), empty
    raise ValueError(f"unknown aggregation kind: {kind}")


def global_aggregate(
    kind: str,
    valid: jnp.ndarray,
    data: Optional[jnp.ndarray] = None,
    nulls: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Ungrouped aggregation (reference: operator/AggregationOperator.java).
    Returns (scalar value, scalar is_null). COUNT of empty input is 0, SUM is
    NULL — SQL global aggregates always produce exactly one row."""
    if kind == COUNT_STAR:
        return jnp.sum(valid.astype(jnp.int64)), jnp.asarray(False)
    assert data is not None
    contributing = valid
    if nulls is not None:
        contributing = contributing & ~nulls
    n = jnp.sum(contributing.astype(jnp.int64))
    empty = n == 0
    if kind == COUNT:
        return n, jnp.asarray(False)
    if kind == SUM:
        zero = jnp.zeros((), dtype=data.dtype)
        return jnp.sum(jnp.where(contributing, data, zero)), empty
    if kind in (MIN, MAX):
        ident = _minmax_identity(data.dtype, kind == MIN)
        filled = jnp.where(contributing, data, ident)
        val = jnp.min(filled) if kind == MIN else jnp.max(filled)
        return jnp.where(empty, jnp.zeros((), dtype=data.dtype), val), empty
    if kind == ANY:
        idx = jnp.arange(data.shape[0], dtype=jnp.int64)
        first = jnp.min(jnp.where(contributing, idx, jnp.int64(2**62)))
        first = jnp.clip(first, 0, data.shape[0] - 1)
        return data[first], empty
    if kind == BOOL_OR:
        return jnp.any(contributing & data.astype(jnp.bool_)), empty
    if kind == BOOL_AND:
        return (
            jnp.all(jnp.where(contributing, data.astype(jnp.bool_), True))
            & ~empty,
            empty,
        )
    raise ValueError(f"unknown aggregation kind: {kind}")


MERGE_KIND = {
    SUM: SUM,
    COUNT: SUM,
    COUNT_STAR: SUM,
    MIN: MIN,
    MAX: MAX,
    ANY: ANY,
    BOOL_OR: BOOL_OR,
    BOOL_AND: BOOL_AND,
}
