"""Hashing kernels: per-row hashes, hash combining, order-insensitive
checksums.

Reference:
  - presto-spi spi/type/AbstractLongType.java hashes a long with XxHash64;
  - presto-main operator/InterpretedHashGenerator.java combines channel hashes
    as ``h = h * 31 + channelHash`` (CombineHashFunction);
  - presto-verifier computes order-insensitive result checksums by summing
    row hashes.

We implement xxhash64 for single 8-byte values (bit-exact with the reference's
XxHash64.hash(long)) and use the same 31*h+x combiner, so row hashes and
checksums are comparable with a Java-side harness if one ever runs. All hash
math is uint64 with natural wraparound.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

# numpy scalars, NOT jnp: a module-level jnp constant is a device buffer
# that jit traces embed by reference, and on the axon TPU runtime any
# executable with an embedded device-buffer constant permanently degrades
# every subsequent kernel launch (~56ms floor, measured). numpy scalars
# fold to HLO literals at trace time instead.
import numpy as _np

_P1 = _np.uint64(0x9E3779B185EBCA87)
_P2 = _np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = _np.uint64(0x165667B19E3779F9)
_P4 = _np.uint64(0x85EBCA77C2B2AE63)
_P5 = _np.uint64(0x27D4EB2F165667C5)


def _rotl(x: jnp.ndarray, r: int) -> jnp.ndarray:
    return (x << jnp.uint64(r)) | (x >> jnp.uint64(64 - r))


def xxhash64_u64(value: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """xxhash64 of a single 8-byte little-endian value (vectorized).

    Bit-exact with io.airlift.slice.XxHash64.hash(long) used by the
    reference's type hashes.
    """
    v = value.astype(jnp.uint64)
    acc = jnp.uint64(seed) + _P5 + jnp.uint64(8)
    k1 = v * _P2
    k1 = _rotl(k1, 31)
    k1 = k1 * _P1
    acc = acc ^ k1
    acc = _rotl(acc, 27) * _P1 + _P4
    # avalanche
    acc = acc ^ (acc >> jnp.uint64(33))
    acc = acc * _P2
    acc = acc ^ (acc >> jnp.uint64(29))
    acc = acc * _P3
    acc = acc ^ (acc >> jnp.uint64(32))
    return acc


def combine_hash(h: jnp.ndarray, next_hash: jnp.ndarray) -> jnp.ndarray:
    """Reference: operator/scalar/CombineHashFunction.java: h * 31 + next."""
    return h.astype(jnp.uint64) * jnp.uint64(31) + next_hash.astype(jnp.uint64)


def hash_columns(
    cols_u64: Sequence[jnp.ndarray],
    nulls: Sequence[Optional[jnp.ndarray]],
) -> jnp.ndarray:
    """Row hash over equality-encoded uint64 key columns.

    NULL hashes to 0 (reference: TypeUtils.hashPosition returns NULL_HASH_CODE
    = 0 for nulls).
    """
    h = jnp.zeros(cols_u64[0].shape, dtype=jnp.uint64)
    for col, null in zip(cols_u64, nulls):
        ch = xxhash64_u64(col)
        if null is not None:
            ch = jnp.where(null, jnp.uint64(0), ch)
        h = combine_hash(h, ch)
    return h


def checksum(row_hashes: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Order-insensitive checksum: wrapping uint64 sum of selected row hashes
    (reference: presto-verifier checksum queries)."""
    return jnp.sum(
        jnp.where(valid, row_hashes, jnp.uint64(0)), dtype=jnp.uint64
    )
