"""Hashing kernels: per-row hashes, hash combining, order-insensitive
checksums.

Reference:
  - presto-spi spi/type/AbstractLongType.java hashes a long with XxHash64;
  - presto-main operator/InterpretedHashGenerator.java combines channel hashes
    as ``h = h * 31 + channelHash`` (CombineHashFunction);
  - presto-verifier computes order-insensitive result checksums by summing
    row hashes.

We implement xxhash64 for single 8-byte values (bit-exact with the reference's
XxHash64.hash(long)) and use the same 31*h+x combiner, so row hashes and
checksums are comparable with a Java-side harness if one ever runs. All hash
math is uint64 with natural wraparound.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

# numpy scalars, NOT jnp: a module-level jnp constant is a device buffer
# that jit traces embed by reference, and on the axon TPU runtime any
# executable with an embedded device-buffer constant permanently degrades
# every subsequent kernel launch (~56ms floor, measured). numpy scalars
# fold to HLO literals at trace time instead.
import numpy as _np

_P1 = _np.uint64(0x9E3779B185EBCA87)
_P2 = _np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = _np.uint64(0x165667B19E3779F9)
_P4 = _np.uint64(0x85EBCA77C2B2AE63)
_P5 = _np.uint64(0x27D4EB2F165667C5)


def _rotl(x: jnp.ndarray, r: int) -> jnp.ndarray:
    return (x << jnp.uint64(r)) | (x >> jnp.uint64(64 - r))


_M64 = (1 << 64) - 1


def xxhash64_host(data: bytes, seed: int = 0) -> int:
    """Full xxhash64 over a byte string (host-side scalar; the scalar
    xxhash64() function's implementation — reference:
    io.airlift.slice.XxHash64.hash(Slice))."""
    p1, p2, p3, p4, p5 = (int(_P1), int(_P2), int(_P3), int(_P4),
                          int(_P5))

    def rot(x, r):
        return ((x << r) | (x >> (64 - r))) & _M64

    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + p1 + p2) & _M64
        v2 = (seed + p2) & _M64
        v3 = seed & _M64
        v4 = (seed - p1) & _M64

        def rnd(acc, lane):
            acc = (acc + lane * p2) & _M64
            return (rot(acc, 31) * p1) & _M64

        while i + 32 <= n:
            v1 = rnd(v1, int.from_bytes(data[i:i + 8], "little"))
            v2 = rnd(v2, int.from_bytes(data[i + 8:i + 16], "little"))
            v3 = rnd(v3, int.from_bytes(data[i + 16:i + 24], "little"))
            v4 = rnd(v4, int.from_bytes(data[i + 24:i + 32], "little"))
            i += 32
        h = (rot(v1, 1) + rot(v2, 7) + rot(v3, 12) + rot(v4, 18)) & _M64

        def merge(h, v):
            h ^= rnd(0, v)
            return (h * p1 + p4) & _M64

        h = merge(h, v1)
        h = merge(h, v2)
        h = merge(h, v3)
        h = merge(h, v4)
    else:
        h = (seed + p5) & _M64
    h = (h + n) & _M64
    while i + 8 <= n:
        k = (int.from_bytes(data[i:i + 8], "little") * p2) & _M64
        k = (rot(k, 31) * p1) & _M64
        h = ((rot(h ^ k, 27) * p1) + p4) & _M64
        i += 8
    if i + 4 <= n:
        k = (int.from_bytes(data[i:i + 4], "little") * p1) & _M64
        h = ((rot(h ^ k, 23) * p2) + p3) & _M64
        i += 4
    while i < n:
        h = (rot(h ^ ((data[i] * p5) & _M64), 11) * p1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * p2) & _M64
    h ^= h >> 29
    h = (h * p3) & _M64
    h ^= h >> 32
    return h


def xxhash64_u64(value: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """xxhash64 of a single 8-byte little-endian value (vectorized).

    Bit-exact with io.airlift.slice.XxHash64.hash(long) used by the
    reference's type hashes.
    """
    v = value.astype(jnp.uint64)
    acc = jnp.uint64(seed) + _P5 + jnp.uint64(8)
    k1 = v * _P2
    k1 = _rotl(k1, 31)
    k1 = k1 * _P1
    acc = acc ^ k1
    acc = _rotl(acc, 27) * _P1 + _P4
    # avalanche
    acc = acc ^ (acc >> jnp.uint64(33))
    acc = acc * _P2
    acc = acc ^ (acc >> jnp.uint64(29))
    acc = acc * _P3
    acc = acc ^ (acc >> jnp.uint64(32))
    return acc


def combine_hash(h: jnp.ndarray, next_hash: jnp.ndarray) -> jnp.ndarray:
    """Reference: operator/scalar/CombineHashFunction.java: h * 31 + next."""
    return h.astype(jnp.uint64) * jnp.uint64(31) + next_hash.astype(jnp.uint64)


def hash_columns(
    cols_u64: Sequence[jnp.ndarray],
    nulls: Sequence[Optional[jnp.ndarray]],
) -> jnp.ndarray:
    """Row hash over equality-encoded uint64 key columns.

    NULL hashes to 0 (reference: TypeUtils.hashPosition returns NULL_HASH_CODE
    = 0 for nulls).
    """
    h = jnp.zeros(cols_u64[0].shape, dtype=jnp.uint64)
    for col, null in zip(cols_u64, nulls):
        ch = xxhash64_u64(col)
        if null is not None:
            ch = jnp.where(null, jnp.uint64(0), ch)
        h = combine_hash(h, ch)
    return h


def checksum(row_hashes: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Order-insensitive checksum: wrapping uint64 sum of selected row hashes
    (reference: presto-verifier checksum queries)."""
    return jnp.sum(
        jnp.where(valid, row_hashes, jnp.uint64(0)), dtype=jnp.uint64
    )
