"""Event listener SPI.

Reference: presto-spi spi/eventlistener/EventListener.java — plugins
receive QueryCreatedEvent / QueryCompletedEvent built by
presto-main event/QueryMonitor.java; the hook for warehouse-side query
logging (SURVEY §6.5). The TPU engine keeps the same shape: listeners
are registered on the server (or QueryManager) and receive immutable
event records; listener failures are swallowed so they can never fail a
query (reference behavior).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float  # unix seconds


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    state: str  # FINISHED | FAILED | CANCELED
    create_time: float
    end_time: float
    wall_ms: int
    row_count: int
    error_name: Optional[str] = None
    error_message: Optional[str] = None
    # the full QueryInfo/StageInfo/TaskInfo tree (obs/trace.to_info)
    # when the query was traced — the reference QueryCompletedEvent
    # carries QueryStats/StageStats the same way; None when tracing
    # was off for this query
    query_info: Optional[dict] = None


@dataclasses.dataclass(frozen=True)
class StageCompletedEvent:
    """One stage-DAG wave finished (dist/scheduler.py). wall_ms spans
    first dispatch to last task completion on the coordinator's
    monotonic clock (obs/trace.py timing rules)."""

    query_id: str
    stage_id: str
    task_count: int
    wall_ms: int
    retries: int
    spooled_pages: int


@dataclasses.dataclass(frozen=True)
class TaskCompletedEvent:
    """One logical task of a stage completed on its final placement.
    queue/run walls come from the worker's shipped spans (zero when
    the worker did not trace)."""

    query_id: str
    task_id: str
    stage_id: str
    uri: str
    state: str  # FINISHED | FAILED
    wall_ms: int
    queue_ms: int
    run_ms: int
    pages: int
    retries: int
    speculative: bool


@dataclasses.dataclass(frozen=True)
class TaskRetryEvent:
    """One fault-tolerant task re-dispatch (dist/dcn.py): the fragment
    placed on `from_uri` was lost (worker death / submit failure /
    exhausted fetch retries) and re-ran on `to_uri` with the same split
    assignment. Reference analog: Project Tardigrade's task-retry
    events in QueryMonitor."""

    query_id: str
    task_id: str
    from_uri: str
    to_uri: str
    attempt: int
    cause: str


class EventListener:
    """Subclass and override; register via PrestoTpuServer(
    event_listeners=[...]), QueryManager(listeners=[...]), or
    DcnRunner(listeners=[...])."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def task_retried(self, event: TaskRetryEvent) -> None:
        pass

    def stage_completed(self, event: StageCompletedEvent) -> None:
        pass

    def task_completed(self, event: TaskCompletedEvent) -> None:
        pass


def dispatch(listeners, method: str, event, on_error=None) -> None:
    """Deliver an event to every listener, swallowing listener errors
    (a misbehaving listener must never fail the query) — but COUNTING
    them: callers pass the owning executor's count_listener_error so
    every swallowed exception lands on the `listener_errors` registry
    counter (exec/counters.py) instead of vanishing."""
    for lst in listeners:
        try:
            getattr(lst, method)(event)
        except Exception:  # noqa: BLE001 - reference behavior, counted
            if on_error is not None:
                try:
                    on_error()
                except Exception:  # noqa: BLE001 - the counter sink
                    pass           # must never fail the query either
