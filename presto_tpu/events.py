"""Event listener SPI.

Reference: presto-spi spi/eventlistener/EventListener.java — plugins
receive QueryCreatedEvent / QueryCompletedEvent built by
presto-main event/QueryMonitor.java; the hook for warehouse-side query
logging (SURVEY §6.5). The TPU engine keeps the same shape: listeners
are registered on the server (or QueryManager) and receive immutable
event records; listener failures are swallowed so they can never fail a
query (reference behavior).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float  # unix seconds


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    state: str  # FINISHED | FAILED | CANCELED
    create_time: float
    end_time: float
    wall_ms: int
    row_count: int
    error_name: Optional[str] = None
    error_message: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class TaskRetryEvent:
    """One fault-tolerant task re-dispatch (dist/dcn.py): the fragment
    placed on `from_uri` was lost (worker death / submit failure /
    exhausted fetch retries) and re-ran on `to_uri` with the same split
    assignment. Reference analog: Project Tardigrade's task-retry
    events in QueryMonitor."""

    query_id: str
    task_id: str
    from_uri: str
    to_uri: str
    attempt: int
    cause: str


class EventListener:
    """Subclass and override; register via PrestoTpuServer(
    event_listeners=[...]), QueryManager(listeners=[...]), or
    DcnRunner(listeners=[...])."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def task_retried(self, event: TaskRetryEvent) -> None:
        pass


def dispatch(listeners, method: str, event) -> None:
    """Deliver an event to every listener, swallowing listener errors
    (a misbehaving listener must never fail the query)."""
    for lst in listeners:
        try:
            getattr(lst, method)(event)
        except Exception:  # noqa: BLE001 - reference behavior
            pass
