"""presto-tpu: a TPU-native distributed SQL query engine.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
engine (skyahead/presto, a prestodb/presto fork): coordinator-planned SQL over
columnar operator pipelines compiled to XLA stage programs on a device mesh.

Architecture (TPU-first, not a port):
  - Columnar batches are fixed-capacity ``Page``s of ``Block``s registered as
    JAX pytrees (reference: presto-spi spi/Page.java, spi/block/*), with
    validity masks instead of dynamic row counts so every operator is a
    statically-shaped XLA program.
  - Expressions lower from a RowExpression-style IR straight to jax.jit
    (reference: presto-main sql/gen/ExpressionCompiler.java generates JVM
    bytecode; XLA is our bytecode).
  - Group-by/join/sort are vectorized array programs (segmented reductions,
    sort + searchsorted probes, lax.top_k) rather than pointer-chasing hash
    tables (reference: presto-main operator/GroupByHash.java, JoinHash).
  - Distribution is SPMD over a jax.sharding.Mesh: hash repartition is
    lax.all_to_all over ICI, broadcast joins are all_gather, final gathers are
    psum/gather (reference: HTTP shuffle via operator/ExchangeClient.java).

x64 note: SQL BIGINT/DOUBLE semantics require 64-bit; we enable jax x64 at
import. Hot paths downcast to i32/bf16 where value ranges allow.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

if not hasattr(_jax, "shard_map"):
    # jax 0.4.x compat: the engine targets the jax.shard_map API
    # (check_vma=); route through jax.experimental.shard_map, whose
    # equivalent knob is check_rep=.
    from jax.experimental.shard_map import shard_map as _esm_shard_map

    def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                          check_vma=True, **kw):
        return _esm_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=bool(check_vma), **kw,
        )

    _jax.shard_map = _shard_map_compat

from presto_tpu.types import (  # noqa: E402
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    REAL,
    SMALLINT,
    TINYINT,
    UNKNOWN,
    VARBINARY,
    CharType,
    DecimalType,
    SqlType,
    VarcharType,
)
from presto_tpu.page import Block, Dictionary, Page  # noqa: E402

__version__ = "0.1.0"

__all__ = [
    "BIGINT",
    "BOOLEAN",
    "DATE",
    "DOUBLE",
    "INTEGER",
    "REAL",
    "SMALLINT",
    "TINYINT",
    "UNKNOWN",
    "VARBINARY",
    "Block",
    "CharType",
    "DecimalType",
    "Dictionary",
    "Page",
    "SqlType",
    "VarcharType",
]
