"""Typed session properties + per-query session state.

Reference: presto-main SystemSessionProperties.java (typed, defaulted,
per-query overrides settable via SET SESSION / X-Presto-Session headers)
and Session.java (user, catalog, property map). The north-star's
`tpu_offload_enabled` gate lives here: it decides whether query kernels
run as compiled XLA programs on the accelerator path or fall back to
op-by-op eager evaluation (the row-oracle fallback, BASELINE.json).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    """Reference: spi/session/PropertyMetadata.java."""

    name: str
    description: str
    type: type  # bool | int | str
    default: Any
    validate: Optional[Callable[[Any], bool]] = None


def _parse_value(prop: PropertyMetadata, value: Any) -> Any:
    if prop.type is str:
        # tri-state and enum properties: accept python bools and any
        # casing ("SET SESSION x = TRUE" arrives as a string either way)
        if isinstance(value, bool):
            return "true" if value else "false"
        value = str(value).strip()
        # normalize case only for enum-domain properties (those with a
        # validator); free-form string values keep their casing
        return value.lower() if prop.validate is not None else value
    if isinstance(value, str) and prop.type is bool:
        low = value.strip().lower()
        if low in ("true", "1", "on"):
            return True
        if low in ("false", "0", "off"):
            return False
        raise ValueError(f"{prop.name}: expected boolean, got {value!r}")
    if isinstance(value, str) and prop.type is int:
        return int(value)
    if not isinstance(value, prop.type):
        try:
            return prop.type(value)
        except Exception:
            raise ValueError(
                f"{prop.name}: expected {prop.type.__name__}, "
                f"got {value!r}"
            )
    return value


SYSTEM_SESSION_PROPERTIES: Dict[str, PropertyMetadata] = {
    p.name: p
    for p in [
        PropertyMetadata(
            "tpu_offload_enabled",
            "compile operator pipelines to XLA and run them on the "
            "accelerator; false falls back to eager op-by-op execution",
            bool, True,
        ),
        PropertyMetadata(
            "join_distribution_type",
            "auto | broadcast | partitioned (reference: "
            "join_distribution_type)",
            str, "auto",
            validate=lambda v: v in ("auto", "broadcast", "partitioned"),
        ),
        PropertyMetadata(
            "broadcast_join_rows",
            "build sides up to this many estimated rows replicate to "
            "every mesh device instead of repartitioning",
            int, 1 << 21,
        ),
        PropertyMetadata(
            "agg_gather_capacity",
            "grouped aggregations up to this capacity gather partial "
            "states to one stream; larger ones repartition by group key",
            int, 1 << 17,
        ),
        PropertyMetadata(
            "page_rows",
            "target rows per page (split granularity)",
            int, 1 << 18,
        ),
        PropertyMetadata(
            "array_agg_max_elements",
            "per-group value-slot bound for array_agg/map_agg/"
            "approx_percentile collect state; a group exceeding it "
            "fails with a clear error (raise and re-run)",
            int, 1024,
        ),
        PropertyMetadata(
            "query_max_memory_bytes",
            "fail queries whose largest page footprint exceeds this many "
            "bytes (0 = unlimited; reference: query.max-memory)",
            int, 0,
        ),
        PropertyMetadata(
            "hash_partition_count",
            "devices used for repartitioned stages (0 = whole mesh)",
            int, 0,
        ),
        PropertyMetadata(
            "pallas_join_enabled",
            "use the Pallas join kernels (radix-partitioned general "
            "join + unique-key fast path) for eligible joins; auto = "
            "on when running on TPU, off elsewhere (the interpreted "
            "kernels exist for CPU testing, not speed)",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "mesh_exchange_mode",
            "lower a repartition exchange to an in-program "
            "lax.all_to_all when its producer spools and consumer "
            "readers are co-resident on one process mesh (ISSUE 18); "
            "auto = co-resident stages only, false = always the "
            "spooled HTTP plane (the authoritative path for "
            "DCN-remote consumers and replay recovery)",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "spill_threshold_bytes",
            "joins/aggregations whose state estimate exceeds this many "
            "bytes run in hash-partition passes (grace-style spill; 0 = "
            "disabled; reference: spill-enabled + revocable memory)",
            int, 0,
        ),
        PropertyMetadata(
            "generated_join_enabled",
            "allow the build-free generated join (closed-form key "
            "inverse + generate-at-index) for eligible joins over "
            "generator-connector tables; off forces the materialized "
            "build paths (hash/sort/Pallas/partitioned)",
            bool, True,
        ),
        PropertyMetadata(
            "agg_optimistic_rows",
            "optimistic group-capacity clamp for blocking aggregations: "
            "state buffers start at min(planner estimate, this) and grow "
            "on the overflow-retry ladder; sorts/scatters in the grouped "
            "path scale with capacity, so a tight start is much faster "
            "when the planner over-estimates (0 = trust the estimate)",
            int, 1 << 18,
        ),
        PropertyMetadata(
            "agg_compact_enabled",
            "when an aggregation consumes a join's output, densify the "
            "input stream through a rolling compacted accumulator of "
            "agg_optimistic_rows capacity first (join outputs are "
            "capacity-sparse; blocking-op cost scales with slots, not "
            "valid rows). Rows beyond the accumulator ride the "
            "overflow-retry ladder",
            bool, True,
        ),
        PropertyMetadata(
            "max_join_build_rows",
            "partition a join whenever the build-side row estimate "
            "exceeds this many rows, regardless of the byte threshold "
            "(kernel-size ceiling for runtimes that fault on huge "
            "buffers; 0 = disabled)",
            int, 0,
        ),
        PropertyMetadata(
            "host_spill_bytes",
            "materialized intermediates (multi-pass operator sources) "
            "estimated above this many bytes stage to host RAM instead "
            "of staying HBM-resident (0 = always device-resident; "
            "reference: spiller/FileSingleStreamSpiller). Default 4GB "
            "keeps huge intermediates from pinning device memory",
            int, 1 << 32,
        ),
        PropertyMetadata(
            "disk_spill_bytes",
            "materialized intermediates estimated above this many "
            "bytes stage to DISK files instead of host RAM (0 = "
            "disabled; the third spill tier — SF100 partitioned state "
            "can exceed host RAM per SURVEY §6.4's sizing). Default "
            "64GB engages only when host RAM would be at risk",
            int, 1 << 36,
        ),
        PropertyMetadata(
            "spill_path",
            "directory for disk-spill files (empty = the system temp "
            "dir; reference: spiller-spill-path config)",
            str, "",
        ),
        PropertyMetadata(
            "late_materialization_enabled",
            "join chains defer carried build columns as row-id "
            "indirections and gather values ONCE at the first consumer "
            "that needs them (reference: DictionaryBlock outputs of "
            "LookupJoinOperator); off gathers every carried column at "
            "every join. auto = on when running on TPU (the win is "
            "HBM gather bandwidth, ~25M rows/s per carried column), "
            "off elsewhere (extra per-join programs cost CPU compile "
            "time). Observability: gathers_deferred / "
            "gathers_materialized counters in EXPLAIN ANALYZE",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "fused_partial_agg_enabled",
            "compile scan->filter->project->partial-aggregation chains "
            "to ONE XLA program per split (extends whole-pipeline "
            "fusion through the partial agg step; fused_partial_aggs "
            "counter in EXPLAIN ANALYZE). Grouped aggregations fuse in "
            "the dense/MXU regime only. auto = on when running on TPU "
            "(the win is per-launch tunnel overhead), off elsewhere "
            "(bigger fused programs cost real CPU compile time)",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "split_batch_size",
            "fold up to this many splits of a fused scan pipeline "
            "into ONE XLA program launch (a lax.scan over split "
            "indices with the partial-aggregation state as carry for "
            "scan->filter->project->partial-agg chains; a vmapped "
            "[B, page] stacked batch emitted as one page for "
            "page-emitting chains). auto = on when running on TPU "
            "with the default max batch (the win is the per-launch "
            "tunnel tax, which CPU doesn't pay — the "
            "pallas_join_enabled policy); false = per-split launches. "
            "Observability: program_launches / splits_per_launch "
            "counters in EXPLAIN ANALYZE",
            str, "auto",
            validate=lambda v: v in ("auto", "false", "off")
            or v.isdigit(),
        ),
        PropertyMetadata(
            "compile_cache_dir",
            "directory for jax's persistent compilation cache: programs "
            "compile once per canonical shape per MACHINE, not per "
            "process (empty = in-process caching only; see "
            "presto_tpu/compilecache.py). Observability: "
            "programs_compiled / program_cache_hits / compile_wall_s "
            "counters in EXPLAIN ANALYZE",
            str, "",
        ),
        PropertyMetadata(
            "device_memory_budget",
            "device-memory budget in bytes for the HBM governor "
            "(exec/membudget.py): pipelines whose planned peak device "
            "footprint exceeds their budget share rewrite into "
            "chunked/streaming form (grace-partition join passes, "
            "probe-side position chunking, generation-chunked scans, "
            "partitioned aggregation, PageStore host/disk overflow) "
            "before anything launches. 0 = auto: real HBM minus "
            "headroom on TPU, a generous cap on CPU. Observability: "
            "peak_device_bytes / memory_chunked_pipelines counters in "
            "EXPLAIN ANALYZE",
            int, 0,
        ),
        PropertyMetadata(
            "task_retry_attempts",
            "fault-tolerant execution (reference: Project Tardigrade's "
            "task-level retry): re-dispatch a lost DCN task to a "
            "surviving ALIVE worker up to this many times — the "
            "fragment re-generates its split share deterministically "
            "at the scan and already-consumed pages dedupe by fetch "
            "token, so delivery stays effectively exactly-once. Also "
            "bounds the executor's device-OOM re-entries (each under a "
            "halved device-memory budget). 0 pins the classic "
            "fail-query-cleanly model",
            int, 2,
        ),
        PropertyMetadata(
            "retry_backoff_ms",
            "base delay for the exponential-backoff-with-jitter ladder "
            "between DCN fetch/submit retries (reference: "
            "HttpPageBufferClient backoff)",
            int, 100,
        ),
        PropertyMetadata(
            "query_max_run_time",
            "wall-clock deadline in milliseconds for a query "
            "(0 = unlimited; reference: query.max-run-time). Enforced "
            "in QueryManager, at executor page boundaries, and in the "
            "DCN fetch loop — expiry surfaces as FAILED with a "
            "QueryDeadlineExceeded cause instead of hanging",
            int, 0,
        ),
        PropertyMetadata(
            "plan_check",
            "pre-compile plan verification (exec/plan_check.py): "
            "schema-consistent operator/fragment edges, ladder-"
            "quantized capacities under the device fault line, "
            "canonical jit-cache key material, deterministic split "
            "assignment fields. auto = on under pytest and bench "
            "--prewarm, off on the hot serving path; true/false "
            "force. Violations fail the query BEFORE compile with a "
            "pointed PlanCheckError",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "stage_scheduler",
            "general fragment-DAG scheduling for DCN queries "
            "(dist/scheduler.py): cut ANY plan into a stage DAG with "
            "gather/broadcast/hash-repartition exchanges and dispatch "
            "it task-by-task across the worker pool, every inter-stage "
            "exchange spooled through PageStore tiers on the producing "
            "worker so lost non-leaf tasks replay instead of failing "
            "the query. auto = engage when the special-cased shapes "
            "(agg-cut / union-cut / hash-fanout) do not apply; true "
            "forces DAG scheduling first; false disables it. "
            "Observability: stages_scheduled / spooled_exchange_pages "
            "/ nonleaf_replays counters in EXPLAIN ANALYZE",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "speculation_enabled",
            "straggler speculation as a stage-scheduler policy "
            "(reference: Project Tardigrade speculative execution): "
            "race a re-dispatched copy of a stage's slowest running "
            "task on another worker and take whichever placement "
            "finishes first (deterministic fragments make the outputs "
            "byte-identical, so the loser is simply cancelled). "
            "Counters: speculative_tasks_won / speculative_tasks_lost",
            bool, False,
        ),
        PropertyMetadata(
            "spool_exchange_bytes",
            "per-task resident-byte budget for spooled-exchange "
            "partitions on a worker: serialized exchange pages beyond "
            "it spill to disk-tier PageStore files instead of host "
            "RAM (0 = never spill to disk; the spooled shuffle tier "
            "that makes non-leaf task replay and mid-query rejoin "
            "scheduler policies). On the device-exchange tier the "
            "same budget bounds device-RESIDENT spool bytes — a page "
            "past it materializes to host eagerly",
            int, 1 << 30,
        ),
        PropertyMetadata(
            "device_exchange_enabled",
            "partition spooled-exchange pages ON DEVICE "
            "(dist/spool.device_partition_pages: jitted splitmix64 "
            "radix partition + ladder-bucket compaction) and spool "
            "device Pages that materialize to host bytes lazily — "
            "mesh-local exchanges then complete with zero h2d/d2h; "
            "auto = on when running on TPU, off elsewhere (the "
            "partition programs cost real CPU compile time for "
            "copies the CPU backend barely pays)",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "buffer_donation_enabled",
            "thread donate_argnums through the jit wrapper for the "
            "fold/topn merge accumulator programs so chained merges "
            "and the overflow-retry ladder reuse HBM in place "
            "instead of reallocating per step (buffers_donated "
            "counter); auto = on when running on TPU, off elsewhere",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "query_trace_enabled",
            "record a query-lifecycle span trace (presto_tpu/obs/): "
            "query -> stage -> task -> attempt -> operator spans on "
            "one monotonic clock with one wall anchor, served live as "
            "the /v1/query/{id} QueryInfo tree and "
            "system.runtime_tasks. Off = zero recording cost "
            "(trace_spans counter pins 0). The HTTP server enables "
            "this by default for its queries",
            bool, False,
        ),
        PropertyMetadata(
            "query_trace_dir",
            "directory for per-query Chrome-trace (Perfetto-loadable) "
            "JSON exports; setting it also enables tracing (empty = "
            "no files). Each query writes "
            "<query-id>.trace.json on completion",
            str, "",
        ),
        PropertyMetadata(
            "stats_profile_dir",
            "directory for persisted observed-stats profiles "
            "(presto_tpu/obs/profile.py), keyed by (canonical plan "
            "fingerprint, connector snapshot): settled capacity "
            "bucket + observed cardinalities. Repeated queries seed "
            "their starting capacity from the profile and skip the "
            "overflow-retry ladder (capacity_boost_retries -> 0); "
            "empty = disabled",
            str, "",
        ),
        PropertyMetadata(
            "result_cache_enabled",
            "serve repeated work from the two-level result cache "
            "(presto_tpu/cache/): cacheable plan subtrees replay "
            "their pages (skipping compile+launch) and identical "
            "full statements return the finished row set, keyed by "
            "(canonical plan/statement fingerprint, connector "
            "snapshot versions) so a write to any scanned table "
            "structurally invalidates. The store is process-shared "
            "across concurrent queries. Observability: "
            "result_cache_hits / result_cache_misses / "
            "result_cache_evictions / result_cache_invalidations "
            "counters in EXPLAIN ANALYZE",
            bool, False,
        ),
        PropertyMetadata(
            "result_cache_bytes",
            "host-resident byte budget for the result cache: LRU "
            "page entries past it demote to disk-tier PageStore "
            "spill files, and total bytes past 4x the budget evict "
            "outright (result_cache_evictions counts both reclaim "
            "paths). An entry larger than the whole budget is never "
            "admitted",
            int, 1 << 28,
        ),
        PropertyMetadata(
            "result_cache_ttl_ms",
            "age bound for result-cache entries in milliseconds: an "
            "entry older than this reads as a miss and is reclaimed "
            "(0 = no age bound; snapshot-version keying already "
            "handles write staleness — TTL exists for wall-clock "
            "freshness policies on slowly-polled dashboards)",
            int, 0,
        ),
        PropertyMetadata(
            "result_cache_persist_dir",
            "directory for the persistent warm-start tier of the "
            "result cache (cache/persist.py): completed fragment "
            "entries publish a wire-serde payload file plus a row in "
            "an atomically-renamed versioned manifest (entry key, "
            "snapshot tokens, stream watermark, serde fingerprint), "
            "and the first enabled session after a process boot "
            "warm-loads every entry whose snapshot tokens still "
            "match the live connectors (cache_warm_loads counter); "
            "stale/corrupt/mismatched entries drop loudly "
            "(cache_manifest_drops). Empty = memory-only (the PR-10 "
            "behavior)",
            str, "",
        ),
        PropertyMetadata(
            "result_cache_remote_probe",
            "let the DCN coordinator probe fleet members' fragment "
            "caches before dispatching a leaf task "
            "(dist/cacheprobe.py): any worker's cached fragment "
            "short-circuits the task (cache_remote_hits) and its "
            "pages replay over the existing pooled spool-fetch "
            "plane; probes are gated by bloom-style summaries "
            "refreshed with heartbeats, so the common miss costs "
            "nothing on the wire",
            bool, True,
        ),
        PropertyMetadata(
            "result_cache_subsumption",
            "serve a fragment whose single-column range/IN filter is "
            "CONTAINED by an already-cached sibling (same scan + "
            "projection chain) by re-filtering the cached pages "
            "(cache/rules.py descriptor containment): WHERE d < 5 "
            "replays the cached WHERE d < 10 pages through a "
            "residual filter instead of rescanning "
            "(cache_subsumed_hits); anything beyond single-column "
            "range/IN stays exact-match",
            bool, False,
        ),
        PropertyMetadata(
            "checkpoint_enabled",
            "journal coordinator query state durably at natural "
            "barriers (dist/checkpoint.py): admission, every "
            "spooled-stage boundary (placements + spool tokens + "
            "page digests), final-stage supplier registration, and "
            "client-protocol token advances — so a restarted "
            "coordinator re-attaches RUNNING queries whose producer "
            "spools still answer instead of losing them "
            "(coordinator_reattaches / checkpoints_written). "
            "Effective only when a journal directory is configured "
            "(checkpoint_dir session prop or the server's "
            "checkpoint.dir etc key); false disables journaling "
            "even when a directory is set",
            bool, True,
        ),
        PropertyMetadata(
            "checkpoint_dir",
            "directory for the durable coordinator journal "
            "(dist/checkpoint.py): one generation-numbered manifest "
            "(shared cache/persist.py ManifestStore discipline — "
            "atomic tmp+rename publishes, O(1) appends, compaction "
            "past a record threshold) holding one record per "
            "in-flight query; on restart the server replays the "
            "journal and re-attaches or loudly fails each pending "
            "query (never a hang, never duplicate or missing rows). "
            "Empty = checkpointing off (the pre-restart behavior)",
            str, "",
        ),
        PropertyMetadata(
            "ivm_enabled",
            "maintain registered materialized views incrementally "
            "(streaming/ivm.py): a refresh folds ONLY the pages "
            "appended since the view's offset watermark through the "
            "partial-aggregation kernels into persisted settled "
            "state — O(new rows) instead of a full recompute. false "
            "forces full recomputes (counted loudly on "
            "ivm_full_recomputes; results identical either way). "
            "Non-IVM-safe view shapes always recompute in full",
            bool, True,
        ),
        PropertyMetadata(
            "stream_tail_enabled",
            "turn /v1/statement into a TAILING cursor for queries "
            "over append-only stream tables (connectors/stream.py): "
            "nextUri never terminates — each poll long-polls the log "
            "and emits only rows derived from new offsets, riding "
            "the incremental-view-maintenance path when the "
            "statement matches a registered view's shape. Set per "
            "request via the X-Presto-Session header (the protocol's "
            "per-request flag) or session-wide via SET SESSION; "
            "DELETE the statement to stop tailing",
            bool, False,
        ),
        PropertyMetadata(
            "stream_poll_ms",
            "long-poll interval in milliseconds for tailing "
            "/v1/statement cursors: a poll with no new offsets "
            "returns an empty page (with a fresh nextUri) after this "
            "long; an append wakes waiting pollers immediately",
            int, 1000,
        ),
        PropertyMetadata(
            "adaptive_execution",
            "runtime re-planning at spooled-exchange stage "
            "boundaries (presto_tpu/adaptive/): when a stage's "
            "spools finish, the not-yet-dispatched DAG suffix "
            "re-optimizes from EXACT observed row/byte counts — "
            "broadcast-vs-partitioned flips, join build re-orders, "
            "capacity re-buckets onto the shapes ladder, skew "
            "pre-engagement — re-verified by plan_check.verify_dag "
            "before dispatch (a failed re-verify falls back to the "
            "static plan, counted on adaptive_replan_rejected). "
            "auto = on under the stage scheduler; false disables. "
            "Counters: adaptive_replans / adaptive_dist_flips / "
            "adaptive_capacity_seeds / adaptive_replan_rejected / "
            "skew_preempted in EXPLAIN ANALYZE",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "adaptive_max_replans",
            "per-query bound on adaptive re-plans applied at stage "
            "boundaries (each re-plan re-verifies the mutated DAG; "
            "the bound keeps re-verification wall off long DAGs "
            "once the plan has settled). 0 observes stats but never "
            "mutates",
            int, 4,
        ),
        PropertyMetadata(
            "join_skew_rebalance",
            "on boosted retries, rebalance hot grace-join partitions "
            "by chunking build rows by position (buffers stay at the "
            "unboosted size; one probe pass per chunk) instead of "
            "growing every buffer — a genuinely hot key cannot be "
            "split by hash (SURVEY §6.7 per-partition rebalancing)",
            bool, True,
        ),
        PropertyMetadata(
            "cross_query_batching",
            "gang compatible fused-pipeline launches from CONCURRENT "
            "queries into one shared vmapped device step with "
            "in-program per-query demux (server/launch_batcher.py) — "
            "the PR-3 split-batching amortization applied across "
            "queries, the batching-inference-server shape. auto = on "
            "under the concurrent server path only (raw Executors "
            "and the serial path never batch); false forces solo "
            "launches. Counters: cross_query_batches / "
            "cross_query_batched_queries / batch_gather_wait_ms / "
            "queries_per_launch in EXPLAIN ANALYZE",
            str, "auto",
            validate=lambda v: v in ("auto", "true", "false"),
        ),
        PropertyMetadata(
            "cross_query_batch_wait_ms",
            "bounded gather window in milliseconds for cross-query "
            "launch batching: the first compatible launch (the group "
            "leader) waits at most this long for peers before "
            "dispatching (extended while a same-key step is already "
            "executing — continuous batching), so a lone query never "
            "stalls past the window; the window is only ever paid "
            "when >= 2 queries are running server-wide; 0 batches "
            "only launches already pending at submit time",
            int, 25,
        ),
    ]
}


class Session:
    """Reference: Session.java — user + catalog + property overrides."""

    def __init__(
        self,
        user: str = "presto",
        catalog: Optional[str] = None,
        schema: str = "default",
        properties: Optional[Dict[str, Any]] = None,
    ):
        self.user = user
        self.catalog = catalog
        self.schema = schema
        self._values: Dict[str, Any] = {}
        for k, v in (properties or {}).items():
            self.set(k, v)

    def set(self, name: str, value: Any) -> None:
        prop = SYSTEM_SESSION_PROPERTIES.get(name)
        if prop is None:
            raise KeyError(f"unknown session property: {name}")
        parsed = _parse_value(prop, value)
        if prop.validate and not prop.validate(parsed):
            raise ValueError(
                f"invalid value for {name}: {value!r}"
            )
        self._values[name] = parsed

    def get(self, name: str) -> Any:
        prop = SYSTEM_SESSION_PROPERTIES.get(name)
        if prop is None:
            raise KeyError(f"unknown session property: {name}")
        return self._values.get(name, prop.default)

    def unset(self, name: str) -> None:
        """Remove an override so the default shows again (reference:
        RESET SESSION)."""
        self._values.pop(name, None)

    def is_set(self, name: str) -> bool:
        """True when the property was explicitly set (SET SESSION /
        header / set()) rather than defaulting — consumers that must
        distinguish an override from the default (e.g. page_rows vs a
        constructor argument) check this, never _values directly."""
        return name in self._values

    def rows(self) -> List[tuple]:
        """SHOW SESSION rows: (name, value, default, type, description)."""
        out = []
        for name, p in sorted(SYSTEM_SESSION_PROPERTIES.items()):
            out.append((
                name,
                str(self._values.get(name, p.default)).lower()
                if p.type is bool else str(self._values.get(name, p.default)),
                str(p.default).lower() if p.type is bool else str(p.default),
                p.type.__name__,
                p.description,
            ))
        return out
