"""Function registry breadth: the remaining reference scalar families.

Reference: presto-main operator/scalar/* — RegexpFunctions (the full
regexp_* set), StringFunctions (translate/levenshtein/hamming/soundex),
VarbinaryFunctions (to_utf8/crc32/xxhash64/sha512/hmac_*/big-endian),
BitwiseFunctions (shifts), UrlFunctions (component extractors),
ArrayFunctions (set algebra/zip), MapFunctions (concat/from_entries).

All string/array/map work rides the dictionary pattern of
functions.py/functions_ext.py: host-side transforms over the DISTINCT
value table plus an on-device code remap, so per-row device work stays
O(1) gathers regardless of string length. Binary (column, column)
string/array ops go through a bounded pair universe: the cross product
of both dictionaries' values is enumerated host-side when small enough
and refused (clear error) when it would explode — the engine's honest
version of per-row host work it cannot vectorize.

Varbinary stays host-side (types.py: "VARBINARY -> host-side payloads")
— varbinary values are python `bytes` living in dictionaries, and the
hash/codec functions return them as first-class values.
"""

from __future__ import annotations

import re
import zlib
from typing import List, Optional

import numpy as np

from presto_tpu import types as T
from presto_tpu.expr.functions import (
    Ctx,
    _dict_int,
    _dict_map,
    _dict_map_nullable,
    register,
)
from presto_tpu.expr.functions_ext import (
    _array_resolve_same,
    _dict_of,
    _elem_result_val,
    _lam_of,
    _require_const,
    _run_lambda,
    _str_resolve,
    _varchar_results,
)
from presto_tpu.expr.values import Val, union_nulls
from presto_tpu.page import Dictionary

_PAIR_LIMIT = 1 << 16


def _strcol(val: Val) -> Val:
    """Constant string/varbinary inputs become one-entry-dictionary
    columns so every dictionary-based helper (including functions.py's
    const-rejecting ones) applies uniformly."""
    if (val.dictionary is None and val.is_const
            and val.py_value is not None):
        return Val(val.data, val.nulls, val.type,
                   Dictionary([val.py_value]), py_value=val.py_value)
    return val


def _pair_map(ctx: Ctx, a: Val, b: Val, fn, rt) -> Val:
    """Binary op over two dictionary-coded columns via the bounded
    cross-product universe: result[i] = fn(a_val[i], b_val[i]) computed
    per distinct (a, b) PAIR, with codes pair_code = a*len(db) + b."""
    a, b = _strcol(a), _strcol(b)
    da, db = _dict_of(a), _dict_of(b)
    if len(da) * max(len(db), 1) > _PAIR_LIMIT:
        raise TypeError(
            "dictionary pair universe too large for host evaluation "
            f"({len(da)}x{len(db)}); reduce distinct values or make "
            "one side a constant"
        )
    results = [fn(x, y) for x in da.values for y in db.values]
    xp = ctx.xp
    ca = xp.clip(a.data, 0, max(len(da) - 1, 0)).astype(np.int64)
    cb = xp.clip(b.data, 0, max(len(db) - 1, 0)).astype(np.int64)
    pair = Val(
        ca * max(len(db), 1) + cb,
        union_nulls(xp, a.nulls, b.nulls),
        a.type,
        Dictionary(list(range(len(results)))),  # placeholder universe
    )
    return _elem_result_val(ctx, pair, results, rt)


# ------------------------------------------------------------------ regexp


def _const_pat(vals: List[Val], idx: int = 1) -> str:
    return str(_require_const(vals[idx], "regexp pattern"))


def _impl_regexp_extract_all(ctx: Ctx, rt, vals: List[Val]) -> Val:
    rx = re.compile(_const_pat(vals))
    group = int(_require_const(vals[2], "regexp group")) \
        if len(vals) > 2 else 0

    def one(v):
        return tuple(
            m.group(group) for m in rx.finditer(str(v))
        )

    return _elem_result_val(ctx, _strcol(vals[0]), [one(v) for v in _dict_of(_strcol(vals[0])).values],
        T.ArrayType(T.VARCHAR),
    )


register("regexp_extract_all", lambda a: T.ArrayType(T.VARCHAR),
         _impl_regexp_extract_all)


def _impl_regexp_split(ctx: Ctx, rt, vals: List[Val]) -> Val:
    rx = re.compile(_const_pat(vals))
    return _elem_result_val(ctx, _strcol(vals[0]),
        [tuple(rx.split(str(v))) for v in _dict_of(_strcol(vals[0])).values],
        T.ArrayType(T.VARCHAR),
    )


register("regexp_split", lambda a: T.ArrayType(T.VARCHAR),
         _impl_regexp_split)

register("regexp_count", lambda a: T.BIGINT,
         lambda ctx, rt, vals: _dict_int(ctx, _strcol(vals[0]),
             lambda v, rx=re.compile(_const_pat(vals)):
             sum(1 for _ in rx.finditer(str(v)))))
register("regexp_position", lambda a: T.BIGINT,
         lambda ctx, rt, vals: _dict_int(ctx, _strcol(vals[0]),
             lambda v, rx=re.compile(_const_pat(vals)):
             (lambda m: m.start() + 1 if m else -1)(rx.search(str(v)))))


# ------------------------------------------------------------------ string


def _impl_translate(ctx: Ctx, rt, vals: List[Val]) -> Val:
    src = str(_require_const(vals[1], "translate from"))
    dst = str(_require_const(vals[2], "translate to"))
    table = {}
    for i, ch in enumerate(src):
        table.setdefault(ord(ch), dst[i] if i < len(dst) else None)
    tbl = {k: v for k, v in table.items()}
    return _dict_map(ctx, _strcol(vals[0]),
        lambda v: "".join(
            tbl.get(ord(c), c) for c in str(v)
            if tbl.get(ord(c), c) is not None
        ),
        T.VARCHAR,
    )


register("translate", lambda a: T.VARCHAR, _impl_translate)


def _soundex(s: str) -> str:
    codes = {**dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
             **dict.fromkeys("DT", "3"), "L": "4",
             **dict.fromkeys("MN", "5"), "R": "6"}
    s = "".join(c for c in str(s).upper() if c.isalpha())
    if not s:
        return ""
    out, prev = s[0], codes.get(s[0], "")
    for c in s[1:]:
        code = codes.get(c, "")
        if code and code != prev:
            out += code
        if c not in "HW":
            prev = code
    return (out + "000")[:4]


register("soundex", lambda a: T.VARCHAR,
         lambda ctx, rt, vals: _dict_map(ctx, _strcol(vals[0]), _soundex,
                                         T.VARCHAR))


def _levenshtein(a: str, b: str) -> int:
    a, b = str(a), str(b)
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def _impl_levenshtein(ctx: Ctx, rt, vals: List[Val]) -> Val:
    if vals[1].is_const:
        w = str(vals[1].py_value)
        return _dict_int(ctx, _strcol(vals[0]),
                         lambda v: _levenshtein(str(v), w))
    return _pair_map(ctx, vals[0], vals[1],
                     lambda x, y: _levenshtein(str(x), str(y)),
                     T.BIGINT)


register("levenshtein_distance", lambda a: T.BIGINT, _impl_levenshtein)


def _hamming(a: str, b: str) -> Optional[int]:
    a, b = str(a), str(b)
    if len(a) != len(b):
        return None  # reference raises; masked-eval policy -> NULL
    return sum(1 for x, y in zip(a, b) if x != y)


def _impl_hamming(ctx: Ctx, rt, vals: List[Val]) -> Val:
    if vals[1].is_const:
        w = str(vals[1].py_value)
        return _dict_map_nullable(ctx, _strcol(vals[0]), lambda v: _hamming(str(v), w), T.BIGINT)
    return _pair_map(ctx, vals[0], vals[1], _hamming, T.BIGINT)


register("hamming_distance", lambda a: T.BIGINT, _impl_hamming)


def _luhn(s: str) -> bool:
    digits = str(s)
    if not digits.isdigit():
        return False
    total = 0
    for i, ch in enumerate(reversed(digits)):
        d = int(ch)
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        total += d
    return total % 10 == 0


register("luhn_check", lambda a: T.BOOLEAN,
         lambda ctx, rt, vals: _dict_map(ctx, _strcol(vals[0]), _luhn, T.BOOLEAN))


# --------------------------------------------------------------- varbinary
# varbinary values are python bytes living in dictionaries (types.py)


def _as_bytes(v) -> bytes:
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    return str(v).encode("utf-8")


register("to_utf8", lambda a: T.VARBINARY,
         lambda ctx, rt, vals: _elem_result_val(ctx, _strcol(vals[0]),
             [_as_bytes(v) for v in _dict_of(_strcol(vals[0])).values],
             T.VARBINARY))
register("from_utf8", lambda a: T.VARCHAR,
         lambda ctx, rt, vals: _varchar_results(ctx, _strcol(vals[0]),
             [_as_bytes(v).decode("utf-8", "replace")
              for v in _dict_of(_strcol(vals[0])).values]))
register("crc32", lambda a: T.BIGINT,
         lambda ctx, rt, vals: _dict_int(ctx, _strcol(vals[0]),
             lambda v: zlib.crc32(_as_bytes(v)) & 0xFFFFFFFF))


def _xxhash64_bytes(b: bytes) -> int:
    from presto_tpu.ops.hashing import xxhash64_host

    return xxhash64_host(b)


register("xxhash64", lambda a: T.VARBINARY,
         lambda ctx, rt, vals: _elem_result_val(ctx, _strcol(vals[0]),
             [(_xxhash64_bytes(_as_bytes(v)) & (2**64 - 1)
               ).to_bytes(8, "big")
              for v in _dict_of(_strcol(vals[0])).values],
             T.VARBINARY))


def _impl_hashfn_bytes(algo):
    import hashlib

    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        return _elem_result_val(ctx, _strcol(vals[0]),
            [hashlib.new(algo, _as_bytes(v)).digest()
             for v in _dict_of(_strcol(vals[0])).values],
            T.VARBINARY,
        )

    return impl


register("sha512", lambda a: T.VARBINARY, _impl_hashfn_bytes("sha512"))


def _const_value(val: Val, what: str):
    """A constant py_value OR the single entry of a one-entry
    dictionary (a constant that went through a function, e.g.
    to_utf8('key'))."""
    if val.is_const:
        return val.py_value
    if val.dictionary is not None and len(val.dictionary) == 1:
        return val.dictionary.values[0]
    raise TypeError(f"{what} must be a constant")


def _impl_hmac(algo):
    import hashlib
    import hmac as hmac_mod

    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        key = _as_bytes(_const_value(vals[1], "hmac key"))
        return _elem_result_val(ctx, _strcol(vals[0]),
            [hmac_mod.new(key, _as_bytes(v), algo).digest()
             for v in _dict_of(_strcol(vals[0])).values],
            T.VARBINARY,
        )

    return impl


register("hmac_md5", lambda a: T.VARBINARY, _impl_hmac("md5"))
register("hmac_sha1", lambda a: T.VARBINARY, _impl_hmac("sha1"))
register("hmac_sha256", lambda a: T.VARBINARY, _impl_hmac("sha256"))
register("hmac_sha512", lambda a: T.VARBINARY, _impl_hmac("sha512"))

register("from_big_endian_64", lambda a: T.BIGINT,
         lambda ctx, rt, vals: _dict_int(ctx, _strcol(vals[0]),
             lambda v: int.from_bytes(
                 _as_bytes(v)[:8], "big", signed=True)))
register("from_big_endian_32", lambda a: T.INTEGER,
         lambda ctx, rt, vals: _dict_int(ctx, _strcol(vals[0]),
             lambda v: int.from_bytes(
                 _as_bytes(v)[:4], "big", signed=True)))


# ---------------------------------------------------------------- bitwise


def _impl_shift(kind):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        xp = ctx.xp
        x = vals[0].data.astype(np.int64)
        n = vals[1].data.astype(np.int64)
        nc = xp.clip(n, 0, 63)
        if kind == "left":
            out = xp.where(n >= 64, np.int64(0), x << nc)
        elif kind == "arith":
            # >=64 saturates to the sign fill, which clip-to-63 gives
            out = x >> nc
        else:  # logical right: >=64 shifts everything out
            out = xp.where(
                n >= 64, np.int64(0),
                (x.astype(np.uint64) >> nc.astype(np.uint64))
                .astype(np.int64),
            )
        return Val(out, union_nulls(xp, vals[0].nulls, vals[1].nulls),
                   T.BIGINT)

    return impl


register("bitwise_left_shift", lambda a: T.BIGINT, _impl_shift("left"),
         propagate_nulls=False)
register("bitwise_right_shift", lambda a: T.BIGINT,
         _impl_shift("logical"), propagate_nulls=False)
register("bitwise_right_shift_arithmetic", lambda a: T.BIGINT,
         _impl_shift("arith"), propagate_nulls=False)
register("bit_length", lambda a: T.BIGINT,
         lambda ctx, rt, vals: _dict_int(ctx, _strcol(vals[0]), lambda v: len(_as_bytes(v)) * 8))


# -------------------------------------------------------------------- url


def _impl_url_part(part):
    from urllib.parse import urlparse

    def one(v):
        try:
            u = urlparse(str(v))
        except Exception:  # noqa: BLE001 - url functions yield NULL
            return None    # on malformed input (reference semantics)
        got = {
            "host": u.hostname, "path": u.path or "",
            "protocol": u.scheme, "query": u.query,
            "fragment": u.fragment,
        }[part]
        return None if got is None else str(got)

    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        return _dict_map_nullable(ctx, _strcol(vals[0]), one, T.VARCHAR)

    return impl


for _p in ("host", "path", "protocol", "query", "fragment"):
    register(f"url_extract_{_p}", _str_resolve, _impl_url_part(_p))


def _impl_url_port(ctx: Ctx, rt, vals: List[Val]) -> Val:
    from urllib.parse import urlparse

    def one(v):
        try:
            p = urlparse(str(v)).port
        except Exception:  # noqa: BLE001 - url functions yield NULL
            return None    # on malformed input (reference semantics)
        return p

    d = _dict_of(_strcol(vals[0]))
    return _elem_result_val(ctx, _strcol(vals[0]), [one(v) for v in d.values], T.BIGINT
    )


register("url_extract_port", lambda a: T.BIGINT, _impl_url_port)

# url_encode / url_decode already live in functions_ext.py


# ------------------------------------------------------------- array sets


def _impl_array_setop(op):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        def fn(x, y):
            x, y = tuple(x), tuple(y)
            if op == "union":
                return tuple(dict.fromkeys(x + y))
            if op == "intersect":
                ys = set(y)
                return tuple(dict.fromkeys(v for v in x if v in ys))
            ys = set(y)  # except
            return tuple(dict.fromkeys(v for v in x if v not in ys))

        return _pair_map(ctx, vals[0], vals[1], fn, rt)

    return impl


register("array_union", _array_resolve_same, _impl_array_setop("union"))
register("array_intersect", _array_resolve_same,
         _impl_array_setop("intersect"))
register("array_except", _array_resolve_same, _impl_array_setop("except"))
register("arrays_overlap", lambda a: T.BOOLEAN,
         lambda ctx, rt, vals: _pair_map(
             ctx, vals[0], vals[1],
             lambda x, y: bool(set(x) & set(y)), T.BOOLEAN))


def _impl_zip(ctx: Ctx, rt, vals: List[Val]) -> Val:
    t0, t1 = vals[0].type, vals[1].type
    rt2 = T.ArrayType(T.RowType((
        t0.element if isinstance(t0, T.ArrayType) else T.UNKNOWN,
        t1.element if isinstance(t1, T.ArrayType) else T.UNKNOWN,
    )))
    return _pair_map(
        ctx, vals[0], vals[1],
        lambda x, y: tuple(
            (x[i] if i < len(x) else None,
             y[i] if i < len(y) else None)
            for i in range(max(len(x), len(y)))
        ),
        rt2,
    )


register(
    "zip",
    lambda a: T.ArrayType(T.RowType((
        a[0].element if isinstance(a[0], T.ArrayType) else T.UNKNOWN,
        a[1].element if isinstance(a[1], T.ArrayType) else T.UNKNOWN,
    ))),
    _impl_zip,
)


def _impl_zip_with(ctx: Ctx, rt, vals: List) -> Val:
    a, b, lam = vals[0], vals[1], _lam_of(vals, 2)
    ta = a.type.element if isinstance(a.type, T.ArrayType) else T.UNKNOWN
    tb = b.type.element if isinstance(b.type, T.ArrayType) else T.UNKNOWN

    def fn(x, y):
        x, y = tuple(x), tuple(y)
        n = max(len(x), len(y))
        xs = [x[i] if i < len(x) else None for i in range(n)]
        ys = [y[i] if i < len(y) else None for i in range(n)]
        return tuple(_run_lambda(lam, [xs, ys], [ta, tb]))

    return _pair_map(ctx, a, b, fn, rt)


register(
    "zip_with",
    # args = (array, array, lambda-body type) — result element type is
    # the lambda's
    lambda a: T.ArrayType(a[2] if len(a) > 2 else T.UNKNOWN),
    _impl_zip_with,
)


# -------------------------------------------------------------------- maps


def _impl_map_concat(ctx: Ctx, rt, vals: List[Val]) -> Val:
    def fn(x, y):
        out = dict(tuple(kv) for kv in x)
        out.update(dict(tuple(kv) for kv in y))
        return tuple(out.items())

    return _pair_map(ctx, vals[0], vals[1], fn, vals[0].type)


register(
    "map_concat",
    lambda a: a[0] if isinstance(a[0], T.MapType) else T.UNKNOWN,
    _impl_map_concat,
)


def _impl_map_from_entries(ctx: Ctx, rt, vals: List[Val]) -> Val:
    t = vals[0].type
    elem = t.element if isinstance(t, T.ArrayType) else None
    kt = elem.fields[0] if isinstance(elem, T.RowType) else T.UNKNOWN
    vt = elem.fields[1] if isinstance(elem, T.RowType) else T.UNKNOWN
    return _elem_result_val(ctx, _strcol(vals[0]),
        [tuple(dict(tuple(kv) for kv in v).items())
         for v in _dict_of(_strcol(vals[0])).values],
        T.MapType(kt, vt),
    )


register(
    "map_from_entries",
    lambda a: T.MapType(
        a[0].element.fields[0], a[0].element.fields[1]
    ) if (isinstance(a[0], T.ArrayType)
          and isinstance(a[0].element, T.RowType)) else T.UNKNOWN,
    _impl_map_from_entries,
)


def _impl_split_to_map(ctx: Ctx, rt, vals: List[Val]) -> Val:
    entry_d = str(_require_const(vals[1], "entry delimiter"))
    kv_d = str(_require_const(vals[2], "key/value delimiter"))

    def one(v):
        out = {}
        s = str(v)
        if not s:
            return ()
        for part in s.split(entry_d):
            k, _, val = part.partition(kv_d)
            out[k] = val
        return tuple(out.items())

    return _elem_result_val(ctx, _strcol(vals[0]), [one(v) for v in _dict_of(_strcol(vals[0])).values],
        T.MapType(T.VARCHAR, T.VARCHAR),
    )


register("split_to_map", lambda a: T.MapType(T.VARCHAR, T.VARCHAR),
         _impl_split_to_map)
