"""Expression layer: RowExpression-style IR lowered to jax array programs.

Reference: presto-main sql/relational/RowExpression.java (the IR) and
sql/gen/ExpressionCompiler.java (JVM bytecode codegen). Our "bytecode" is XLA:
an expression tree evaluates to a statically-shaped array program over a Page,
and ``jax.jit`` compiles it. The dual-eval testing pattern (reference:
operator/scalar/FunctionAssertions evaluating interpreted vs compiled) becomes
evaluating with the numpy backend vs the jitted jax backend.
"""

from presto_tpu.expr.ir import (  # noqa: F401
    Call,
    Constant,
    InputRef,
    RowExpression,
    SpecialForm,
)
from presto_tpu.expr.eval import Val, evaluate, evaluate_filter  # noqa: F401
