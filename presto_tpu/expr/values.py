"""Evaluated-value representation and backend-generic helpers.

The expression evaluator is written against an array-namespace parameter
``xp`` that is either ``jax.numpy`` (compiled path) or ``numpy`` (oracle
path), enabling the dual-eval testing pattern (reference:
operator/scalar/FunctionAssertions runs expressions both interpreted and
bytecode-compiled and compares).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from presto_tpu import types as T
from presto_tpu.page import Dictionary

NOT_CONST = object()


@dataclasses.dataclass
class Val:
    """A vectorized value: one array (or limb pair) per page position.

    nulls is None (no nulls) or a bool array, True = SQL NULL.
    dictionary carries the host-side Dictionary for string-typed values.
    py_value is the Python literal when this Val came from a Constant —
    needed to translate string literals into dictionary codes at trace time.
    """

    data: Any
    nulls: Any
    type: T.SqlType
    dictionary: Optional[Dictionary] = None
    py_value: Any = NOT_CONST

    @property
    def is_const(self) -> bool:
        return self.py_value is not NOT_CONST


def union_nulls(xp, *nulls):
    out = None
    for n in nulls:
        if n is None:
            continue
        out = n if out is None else (out | n)
    return out


def nulls_or_false(xp, val: Val, cap: int):
    if val.nulls is None:
        return xp.zeros((cap,), dtype=bool)
    return broadcast_arr(xp, val.nulls, cap)


def broadcast_arr(xp, arr, cap: int):
    arr = xp.asarray(arr)
    if arr.ndim == 0:
        return xp.broadcast_to(arr, (cap,))
    return arr


def broadcast_val(xp, val: Val, cap: int) -> Val:
    data = val.data
    if isinstance(data, tuple):
        data = tuple(broadcast_arr(xp, d, cap) for d in data)
    else:
        data = broadcast_arr(xp, data, cap)
    nulls = None if val.nulls is None else broadcast_arr(xp, val.nulls, cap)
    return Val(data, nulls, val.type, val.dictionary, val.py_value)


# ------------------------------------------------------------------ casting

_INT_ORDER = [T.TinyintType, T.SmallintType, T.IntegerType, T.BigintType]


def pow10(xp, k: int):
    return xp.asarray(np.int64(10**k))


def rescale_decimal(xp, data, from_scale: int, to_scale: int):
    """Exact rescale of unscaled i64 decimal values; scale-down rounds
    half-up away from zero (reference: spi/type/Decimals rescale)."""
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * np.int64(10 ** (to_scale - from_scale))
    d = np.int64(10 ** (from_scale - to_scale))
    return _div_round_half_up(xp, data, xp.asarray(d))


def _div_round_half_up(xp, num, den):
    """Sign-aware round-half-up integer division (den > 0 elementwise safe
    after zero-masking by the caller)."""
    sign = xp.where(num >= 0, np.int64(1), np.int64(-1))
    mag = xp.abs(num)
    q = (mag + den // np.int64(2)) // den
    return sign * q


def div_round_half_up(xp, num, den):
    """Round-half-up division handling signs on both operands; den must be
    nonzero (caller masks zeros)."""
    sgn = xp.where((num >= 0) == (den >= 0), np.int64(1), np.int64(-1))
    q = (xp.abs(num) + xp.abs(den) // np.int64(2)) // xp.abs(den)
    return sgn * q


def cast_data(xp, val: Val, to: T.SqlType, cap: int):
    """Cast a Val's data/nulls to another type. Returns (data, nulls).

    Reference: presto-main operator cast functions resolved via
    FunctionRegistry ("operator CAST"). Unsupported value-dependent failures
    (e.g. overflow on narrow) follow the masked-eval policy: no runtime
    errors, values wrap like the hardware does (documented divergence from
    the reference's checked casts).
    """
    src = val.type
    data = val.data
    nulls = val.nulls
    if src == to:
        return data, nulls
    if isinstance(src, T.UnknownType):  # typed NULL literal
        z = xp.zeros((cap,), dtype=np.dtype(to.numpy_dtype))
        return z, xp.ones((cap,), dtype=bool)
    if isinstance(data, tuple):
        # long-decimal limbs (base-2^64 two's complement). Lossless only
        # into double below 2^53 of unscaled magnitude — the planner casts
        # long-decimal aggregate outputs to double before further
        # arithmetic (documented divergence from the reference's exact
        # decimal(38) math).
        hi, lo = data
        if T.is_floating(to):
            f = (
                hi.astype(np.float64) * float(2**64)
                + xp.where(lo >= 0, lo.astype(np.float64),
                           lo.astype(np.float64) + float(2**64))
            )
            if isinstance(src, T.DecimalType):
                f = f / float(10**src.scale)
            return f.astype(np.dtype(to.numpy_dtype)), nulls
        raise TypeError(f"unsupported cast from long decimal to {to}")

    if isinstance(to, T.DecimalType):
        if isinstance(src, T.DecimalType):
            return (
                rescale_decimal(xp, data, src.scale, to.scale),
                nulls,
            )
        if T.is_integral(src):
            return (
                data.astype(np.int64) * np.int64(10**to.scale),
                nulls,
            )
        if T.is_floating(src):
            scaled = data.astype(np.float64) * float(10**to.scale)
            rounded = xp.where(
                scaled >= 0.0, xp.floor(scaled + 0.5), xp.ceil(scaled - 0.5)
            )
            return rounded.astype(np.int64), nulls
    if isinstance(src, T.DecimalType):
        if T.is_floating(to):
            out = data.astype(np.float64) / float(10**src.scale)
            return out.astype(np.dtype(to.numpy_dtype)), nulls
        if T.is_integral(to):
            unscaled = rescale_decimal(xp, data, src.scale, 0)
            return unscaled.astype(np.dtype(to.numpy_dtype)), nulls
        if isinstance(to, T.BooleanType):
            return data != 0, nulls
    if T.is_integral(src) or isinstance(src, T.BooleanType):
        if T.is_integral(to) or T.is_floating(to):
            return data.astype(np.dtype(to.numpy_dtype)), nulls
        if isinstance(to, T.BooleanType):
            return data != 0, nulls
    if T.is_floating(src):
        if T.is_floating(to):
            return data.astype(np.dtype(to.numpy_dtype)), nulls
        if T.is_integral(to):
            # SQL cast rounds half up (reference: DoubleOperators.castToLong)
            r = xp.where(
                data >= 0, xp.floor(data + 0.5), xp.ceil(data - 0.5)
            )
            return r.astype(np.dtype(to.numpy_dtype)), nulls
        if isinstance(to, T.BooleanType):
            return data != 0.0, nulls
    if isinstance(src, T.DateType) and isinstance(to, T.TimestampType):
        return data.astype(np.int64) * np.int64(86_400_000_000), nulls
    if isinstance(src, T.TimestampType) and isinstance(to, T.DateType):
        micros_per_day = np.int64(86_400_000_000)
        return (data // micros_per_day).astype(np.int32), nulls
    raise TypeError(f"unsupported cast: {src} -> {to}")


# ----------------------------------------------------- civil date arithmetic
# Branch-free Gregorian conversions (public-domain algorithms, Howard
# Hinnant's chrono date paper), vectorized over int arrays with
# floor-division semantics (python/numpy/jax // all floor for ints).


def civil_from_days(xp, z):
    """days-since-1970 -> (year, month, day) int arrays."""
    z = z.astype(np.int64) + np.int64(719_468)
    era = z // np.int64(146_097)
    doe = z - era * np.int64(146_097)
    yoe = (
        doe - doe // np.int64(1460) + doe // np.int64(36_524)
        - doe // np.int64(146_096)
    ) // np.int64(365)
    y = yoe + era * np.int64(400)
    doy = doe - (
        np.int64(365) * yoe + yoe // np.int64(4) - yoe // np.int64(100)
    )
    mp = (np.int64(5) * doy + np.int64(2)) // np.int64(153)
    d = doy - (np.int64(153) * mp + np.int64(2)) // np.int64(5) + np.int64(1)
    m = xp.where(mp < 10, mp + np.int64(3), mp - np.int64(9))
    y = xp.where(m <= 2, y + np.int64(1), y)
    return y, m, d


def days_from_civil(xp, y, m, d):
    """(year, month, day) -> days-since-1970, int64."""
    y = y.astype(np.int64)
    m = m.astype(np.int64)
    d = d.astype(np.int64)
    yadj = xp.where(m <= 2, y - np.int64(1), y)
    era = yadj // np.int64(400)
    yoe = yadj - era * np.int64(400)
    mp = (m + np.int64(9)) % np.int64(12)
    doy = (np.int64(153) * mp + np.int64(2)) // np.int64(5) + d - np.int64(1)
    doe = (
        np.int64(365) * yoe + yoe // np.int64(4) - yoe // np.int64(100) + doy
    )
    return era * np.int64(146_097) + doe - np.int64(719_468)


def add_months_to_days(xp, days, months):
    """date + INTERVAL YEAR TO MONTH with end-of-month clamping (reference:
    DateTimeOperators/joda addMonths semantics: Jan 31 + 1 month = Feb 28)."""
    y, m, d = civil_from_days(xp, days)
    m0 = m - np.int64(1) + months.astype(np.int64)
    y2 = y + m0 // np.int64(12)
    m2 = m0 % np.int64(12) + np.int64(1)
    last = days_in_month(xp, y2, m2)
    d2 = xp.minimum(d, last)
    return days_from_civil(xp, y2, m2, d2).astype(np.int32)


def days_in_month(xp, y, m):
    lengths = xp.asarray(
        np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31], np.int64)
    )
    base = lengths[m - np.int64(1)]
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return xp.where((m == 2) & leap, np.int64(29), base)
