"""RowExpression-equivalent IR.

Reference: presto-main sql/relational/RowExpression.java and subclasses
CallExpression / ConstantExpression / InputReferenceExpression /
SpecialFormExpression (AND, OR, IF, COALESCE, SWITCH, IN, IS_NULL, ...).
Planner-produced trees of these nodes are what the reference compiles to
bytecode; ours lower to jax (presto_tpu/expr/eval.py).

Nodes are frozen/hashable so whole trees can ride in jit static aux data —
the jit cache key plays the role of the reference's compiled-expression LRU
(sql/gen/ExpressionCompiler cache).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

from presto_tpu import types as T


class RowExpression:
    """Base class. ``type`` is the SQL result type of the node."""

    type: T.SqlType

    def children(self) -> Tuple["RowExpression", ...]:
        return ()


@dataclasses.dataclass(frozen=True)
class InputRef(RowExpression):
    """Reference to a Page channel (reference: InputReferenceExpression)."""

    channel: int
    type: T.SqlType = dataclasses.field(default_factory=T.UnknownType)

    def __repr__(self) -> str:
        return f"#{self.channel}:{self.type}"


@dataclasses.dataclass(frozen=True)
class Constant(RowExpression):
    """Literal (reference: ConstantExpression). value=None means NULL.

    Values are host Python scalars: int for integral/date/interval types,
    int (unscaled) for decimals, float for double/real, bool, str for
    varchar/char.
    """

    value: Any
    type: T.SqlType = dataclasses.field(default_factory=T.UnknownType)

    def __repr__(self) -> str:
        return f"{self.value!r}:{self.type}"


@dataclasses.dataclass(frozen=True)
class Call(RowExpression):
    """Function/operator call bound by name against the function registry
    (reference: CallExpression resolved against FunctionRegistry)."""

    name: str
    args: Tuple[RowExpression, ...]
    type: T.SqlType

    def children(self) -> Tuple[RowExpression, ...]:
        return self.args

    def __repr__(self) -> str:
        inner = ", ".join(repr(a) for a in self.args)
        return f"{self.name}({inner})"


@dataclasses.dataclass(frozen=True)
class ParamRef(RowExpression):
    """Lambda parameter reference (reference: sql/relational
    VariableReferenceExpression inside LambdaDefinitionExpression).
    Distinct from InputRef so plan-level channel rewrites (pruning,
    pushdown) can never confuse a lambda parameter with a page
    channel."""

    index: int
    type: T.SqlType = dataclasses.field(default_factory=T.UnknownType)

    def __repr__(self) -> str:
        return f"$lambda{self.index}:{self.type}"


@dataclasses.dataclass(frozen=True)
class Lambda(RowExpression):
    """Lambda argument to a higher-order function (reference:
    LambdaDefinitionExpression). Parameters appear in the body as
    ParamRef(0..n_params-1); ``type`` is the body's result type. The
    body must be capture-free (enforced at planning) so it can be
    evaluated per distinct dictionary value on the host."""

    n_params: int
    body: RowExpression
    type: T.SqlType = dataclasses.field(default_factory=T.UnknownType)

    def children(self) -> Tuple[RowExpression, ...]:
        return (self.body,)

    def __repr__(self) -> str:
        ps = ", ".join(f"$lambda{i}" for i in range(self.n_params))
        return f"({ps}) -> {self.body!r}"


# SpecialForm kinds (reference: SpecialFormExpression.Form)
AND = "and"
OR = "or"
IF = "if"  # args: (condition, then, else)
COALESCE = "coalesce"
SWITCH = "switch"  # searched CASE: (when1, then1, ..., whenN, thenN, else)
IN = "in"  # args: (value, candidate1, ..., candidateN)
IS_NULL = "is_null"
BETWEEN = "between"  # args: (value, low, high)
DEREFERENCE = "dereference"  # row field access (v1: unsupported at eval)


@dataclasses.dataclass(frozen=True)
class SpecialForm(RowExpression):
    """Short-circuit / variadic forms with non-function null semantics.

    The reference evaluates these lazily (bytecode branches); XLA evaluates
    both sides eagerly and selects with where() — the documented semantic
    difference (SURVEY §4.4): erroring branches must be masked by their
    guards, which the function implementations here do (e.g. divide by zero
    yields NULL rather than raising).
    """

    form: str
    args: Tuple[RowExpression, ...]
    type: T.SqlType

    def children(self) -> Tuple[RowExpression, ...]:
        return self.args

    def __repr__(self) -> str:
        inner = ", ".join(repr(a) for a in self.args)
        return f"{self.form.upper()}({inner})"


# ----------------------------------------------------------------- builders
# Convenience constructors that resolve result types via the registry, for
# hand-built plans and tests (the SQL analyzer builds nodes directly).


def _registry():
    from presto_tpu.expr import functions

    return functions


def input_ref(channel: int, typ: T.SqlType) -> InputRef:
    return InputRef(channel, typ)


def const(value: Any, typ: T.SqlType) -> Constant:
    return Constant(value, typ)


def null(typ: T.SqlType = T.UNKNOWN) -> Constant:
    return Constant(None, typ)


def call(name: str, *args: RowExpression) -> Call:
    typ = _registry().resolve_type(name, [a.type for a in args])
    return Call(name, tuple(args), typ)


def and_(*args: RowExpression) -> SpecialForm:
    return SpecialForm(AND, tuple(args), T.BOOLEAN)


def or_(*args: RowExpression) -> SpecialForm:
    return SpecialForm(OR, tuple(args), T.BOOLEAN)


def not_(arg: RowExpression) -> Call:
    return call("not", arg)


def if_(cond, then, else_) -> SpecialForm:
    typ = T.common_super_type(then.type, else_.type)
    if typ is None:
        raise TypeError(f"IF branches disagree: {then.type} vs {else_.type}")
    return SpecialForm(IF, (cond, then, else_), typ)


def is_null(arg: RowExpression) -> SpecialForm:
    return SpecialForm(IS_NULL, (arg,), T.BOOLEAN)


def coalesce(*args: RowExpression) -> SpecialForm:
    typ = args[0].type
    for a in args[1:]:
        nxt = T.common_super_type(typ, a.type)
        if nxt is None:
            raise TypeError(f"COALESCE branches disagree: {typ} vs {a.type}")
        typ = nxt
    return SpecialForm(COALESCE, tuple(args), typ)


def between(value, low, high) -> SpecialForm:
    return SpecialForm(BETWEEN, (value, low, high), T.BOOLEAN)


def in_(value, *candidates: RowExpression) -> SpecialForm:
    return SpecialForm(IN, (value,) + tuple(candidates), T.BOOLEAN)


def switch(*args: RowExpression) -> SpecialForm:
    """Searched CASE: switch(when1, then1, ..., whenN, thenN, default)."""
    if len(args) < 3 or len(args) % 2 == 0:
        raise TypeError("switch needs whenN/thenN pairs plus a default")
    thens = list(args[1::2]) + [args[-1]]
    typ = thens[0].type
    for t in thens[1:]:
        nxt = T.common_super_type(typ, t.type)
        if nxt is None:
            raise TypeError(f"CASE branches disagree: {typ} vs {t.type}")
        typ = nxt
    return SpecialForm(SWITCH, tuple(args), typ)


def cast(arg: RowExpression, to: T.SqlType) -> Call:
    return Call("cast", (arg,), to)
