"""Expression evaluator: lower a RowExpression tree over a Page.

Reference: presto-main sql/gen/ExpressionCompiler.java compiles RowExpression
trees to JVM bytecode producing a PageProcessor; here the "compiled" form is
the jax trace of this evaluator — running it under ``jax.jit`` specializes on
the (static, hashable) expression tree and page schema, exactly the role of
the reference's compiled-expression cache.

Null semantics follow the reference: scalar functions propagate NULL; AND/OR
use SQL three-valued logic (sql/gen/ AndCodeGenerator/OrCodeGenerator);
IF/CASE treat NULL conditions as false; COALESCE picks the first non-null.
Lazy short-circuit evaluation becomes eager evaluate-both + select — value
errors in untaken branches are masked inside the function implementations
(presto_tpu/expr/functions.py docstring).
"""

from __future__ import annotations

from typing import List

import numpy as np

from presto_tpu import types as T
from presto_tpu.expr import ir
from presto_tpu.expr.values import (
    NOT_CONST,
    Val,
    broadcast_val,
    cast_data,
    union_nulls,
)
from presto_tpu.page import Page


def _const_val(ctx, node: ir.Constant) -> Val:
    t = node.type
    if node.value is None:
        dt = np.dtype(t.numpy_dtype)
        return Val(
            ctx.xp.zeros((), dtype=dt),
            ctx.xp.ones((ctx.capacity,), dtype=bool),
            t,
            py_value=None,
        )
    if T.is_string(t):
        # dictionary code resolution happens at the consuming function
        # (comparison/LIKE) where the column dictionary is known
        return Val(
            ctx.xp.zeros((), dtype=np.int32),
            None,
            t,
            py_value=node.value,
        )
    if t.is_dictionary_encoded:
        # complex constants (array/map/row literals): a one-entry
        # dictionary makes the value first-class — projection, decode,
        # UNNEST, and the _dict_* function helpers all apply
        from presto_tpu.page import Dictionary

        return Val(
            ctx.xp.zeros((), dtype=np.int32),
            None,
            t,
            Dictionary([node.value]),
            py_value=node.value,
        )
    dt = np.dtype(t.numpy_dtype)
    return Val(
        ctx.xp.asarray(np.asarray(node.value, dtype=dt)),
        None,
        t,
        py_value=node.value,
    )


def evaluate(node: ir.RowExpression, page: Page, xp) -> Val:
    """Evaluate an expression over every position of a page (the selection
    mask does not gate evaluation — masked lanes compute garbage safely and
    are dropped downstream, the standard SPMD predication discipline)."""
    from presto_tpu.expr import functions as F

    ctx = F.Ctx(xp=xp, capacity=page.capacity)
    return _eval(ctx, node, page)


def _eval(ctx, node: ir.RowExpression, page: Page) -> Val:
    from presto_tpu.expr import functions as F

    xp = ctx.xp
    if isinstance(node, ir.InputRef):
        blk = page.block(node.channel)
        return Val(blk.data, blk.nulls, blk.type, blk.dictionary)
    if isinstance(node, ir.ParamRef):
        # lambda parameter over the synthetic element page a higher-
        # order function builds per distinct collection value
        blk = page.block(node.index)
        return Val(blk.data, blk.nulls, blk.type, blk.dictionary)
    if isinstance(node, ir.Constant):
        return _const_val(ctx, node)
    if isinstance(node, ir.Call):
        # lambda arguments pass through unevaluated; the higher-order
        # function's impl evaluates the body per element universe
        vals = [
            a if isinstance(a, ir.Lambda) else _eval(ctx, a, page)
            for a in node.args
        ]
        return F.eval_call(ctx, node.name, node.type, vals)
    if isinstance(node, ir.SpecialForm):
        return _eval_special(ctx, node, page)
    raise TypeError(f"unknown expression node: {node!r}")


def _as_bool3(ctx, val: Val):
    """(value, is_null) pair for three-valued logic."""
    xp = ctx.xp
    v = broadcast_val(xp, val, ctx.capacity)
    data = v.data.astype(bool)
    if v.nulls is None:
        return data, xp.zeros((ctx.capacity,), dtype=bool)
    return data & ~v.nulls, v.nulls


def _unify_string_vals(ctx, vals):
    """Remap string-typed branch Vals onto ONE merged dictionary.

    IF/SWITCH/COALESCE select raw codes across branches with where(); if the
    branches carry different dictionaries (two different columns, a transform
    output, a string literal), the selected codes must decode through a single
    shared universe — keeping one branch's dictionary silently decodes the
    other branch's codes through the wrong value table. Merging at trace time
    is a compile-time constant gather, same trick as
    functions._string_codes_for_compare.
    """
    from presto_tpu.page import Dictionary

    xp = ctx.xp
    dicts = [v.dictionary for v in vals]
    real = {id(d): d for d in dicts if d is not None}
    nondict_consts = [
        v for v in vals
        if v.dictionary is None and v.is_const and v.py_value is not None
    ]
    if not real and not nondict_consts:
        return vals  # all NULL literals: nothing to decode
    if (
        len({d for d in dicts if d is not None}) == 1
        and not nondict_consts
        and all(d is not None for d in dicts)
    ):
        return vals  # one shared dictionary already
    universe: dict = {}
    for v in vals:
        if v.dictionary is not None:
            for x in v.dictionary.values:
                universe.setdefault(x, len(universe))
        elif v.is_const and v.py_value is not None:
            universe.setdefault(v.py_value, len(universe))
    merged = Dictionary(list(universe))
    out = []
    for v in vals:
        if v.dictionary is not None and len(v.dictionary):
            lut = np.array(
                [universe[x] for x in v.dictionary.values], np.int32
            )
            codes = xp.clip(v.data, 0, len(v.dictionary) - 1)
            data = xp.asarray(lut)[codes]
        elif v.is_const and v.py_value is not None:
            data = xp.broadcast_to(
                xp.asarray(np.int32(universe[v.py_value])), (ctx.capacity,)
            )
        else:  # NULL literal or empty dictionary: code value is never read
            data = xp.zeros((ctx.capacity,), dtype=np.int32)
        out.append(Val(data, v.nulls, v.type, merged, v.py_value))
    return out


def _eval_special(ctx, node: ir.SpecialForm, page: Page) -> Val:
    from presto_tpu.expr import functions as F

    xp = ctx.xp
    form = node.form

    if form == ir.AND:
        # SQL 3VL: FALSE dominates NULL
        vals = [_as_bool3(ctx, _eval(ctx, a, page)) for a in node.args]
        any_false = None
        any_null = None
        acc = None
        for v, n in vals:
            acc = v if acc is None else (acc & v)
            f = ~v & ~n
            any_false = f if any_false is None else (any_false | f)
            any_null = n if any_null is None else (any_null | n)
        out_null = any_null & ~any_false
        return Val(acc & ~out_null, out_null, T.BOOLEAN)

    if form == ir.OR:
        # TRUE dominates NULL
        vals = [_as_bool3(ctx, _eval(ctx, a, page)) for a in node.args]
        any_true = None
        any_null = None
        acc = None
        for v, n in vals:
            acc = v if acc is None else (acc | v)
            any_true = v if any_true is None else (any_true | v)
            any_null = n if any_null is None else (any_null | n)
        out_null = any_null & ~any_true
        return Val(acc & ~out_null, out_null, T.BOOLEAN)

    if form == ir.IS_NULL:
        v = broadcast_val(xp, _eval(ctx, node.args[0], page), ctx.capacity)
        if v.nulls is None:
            return Val(xp.zeros((ctx.capacity,), dtype=bool), None, T.BOOLEAN)
        return Val(v.nulls, None, T.BOOLEAN)

    if form == ir.IF:
        cond, _ = _as_bool3(ctx, _eval(ctx, node.args[0], page))
        t = _coerced(ctx, node.args[1], page, node.type)
        f = _coerced(ctx, node.args[2], page, node.type)
        if T.is_string(node.type):
            t, f = _unify_string_vals(ctx, [t, f])
        data = _select(xp, cond, t.data, f.data)
        tn = t.nulls if t.nulls is not None else xp.zeros(
            (ctx.capacity,), dtype=bool)
        fn_ = f.nulls if f.nulls is not None else xp.zeros(
            (ctx.capacity,), dtype=bool)
        nulls = xp.where(cond, tn, fn_)
        return Val(data, nulls, node.type, t.dictionary or f.dictionary)

    if form == ir.COALESCE:
        branches = [_coerced(ctx, a, page, node.type) for a in node.args]
        if T.is_string(node.type):
            branches = _unify_string_vals(ctx, branches)
        out = None
        for v in branches:
            vn = v.nulls if v.nulls is not None else xp.zeros(
                (ctx.capacity,), dtype=bool)
            if out is None:
                out = (v.data, vn, v.dictionary)
            else:
                data, nulls, dic = out
                take_new = nulls & ~vn
                out = (
                    _select(xp, take_new, v.data, data),
                    nulls & vn,
                    dic or v.dictionary,
                )
        data, nulls, dic = out
        return Val(data, nulls, node.type, dic)

    if form == ir.BETWEEN:
        v, lo, hi = node.args
        expanded = ir.and_(
            ir.Call("ge", (v, lo), T.BOOLEAN),
            ir.Call("le", (v, hi), T.BOOLEAN),
        )
        return _eval_special(ctx, expanded, page)

    if form == ir.IN:
        value = node.args[0]
        clauses = tuple(
            ir.Call("eq", (value, c), T.BOOLEAN) for c in node.args[1:]
        )
        return _eval_special(
            ctx, ir.SpecialForm(ir.OR, clauses, T.BOOLEAN), page
        )

    if form == ir.SWITCH:
        *pairs, default = node.args
        whens = pairs[0::2]
        thens = pairs[1::2]
        out = _coerced(ctx, default, page, node.type)
        branch_vals = [
            _coerced(ctx, t_, page, node.type) for t_ in thens
        ]
        if T.is_string(node.type):
            unified = _unify_string_vals(ctx, [out] + branch_vals)
            out, branch_vals = unified[0], unified[1:]
        data = out.data
        nulls = out.nulls if out.nulls is not None else xp.zeros(
            (ctx.capacity,), dtype=bool)
        dic = out.dictionary
        # later WHENs must not override earlier ones: fold right-to-left
        for when, t in reversed(list(zip(whens, branch_vals))):
            c, _ = _as_bool3(ctx, _eval(ctx, when, page))
            tn = t.nulls if t.nulls is not None else xp.zeros(
                (ctx.capacity,), dtype=bool)
            data = _select(xp, c, t.data, data)
            nulls = xp.where(c, tn, nulls)
            dic = t.dictionary or dic
        return Val(data, nulls, node.type, dic)

    raise TypeError(f"unknown special form: {form}")


def _select(xp, cond, a, b):
    if isinstance(a, tuple):
        return tuple(xp.where(cond, x, y) for x, y in zip(a, b))
    return xp.where(cond, a, b)


def _coerced(ctx, node: ir.RowExpression, page: Page, to: T.SqlType) -> Val:
    v = broadcast_val(ctx.xp, _eval(ctx, node, page), ctx.capacity)
    if v.type == to or T.is_string(to):
        return v
    data, nulls = cast_data(ctx.xp, v, to, ctx.capacity)
    return Val(data, nulls, to, v.dictionary)


def evaluate_filter(node: ir.RowExpression, page: Page, xp) -> Page:
    """FilterNode semantics: keep rows where the predicate is TRUE (NULL and
    FALSE both drop — reference: FilterAndProjectOperator)."""
    from presto_tpu.expr import functions as F

    ctx = F.Ctx(xp=xp, capacity=page.capacity)
    v = _eval(ctx, node, page)
    cond, nulls = _as_bool3(ctx, v)
    return page.with_valid(page.valid & cond & ~nulls)
