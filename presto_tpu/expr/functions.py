"""Function registry: scalar builtins resolved by name.

Reference: presto-main metadata/FunctionRegistry.java registering hundreds of
@ScalarFunction builtins plus arithmetic/comparison "operators"; the bytecode
compiler binds calls to MethodHandles. Here each function is (type resolution,
array implementation over an xp namespace); the evaluator applies generic
null propagation (result NULL if any argument NULL) unless the function opts
out — the same convention as the reference's default null-convention scalars.

Value-dependent errors (division by zero, overflow) cannot raise inside a
compiled XLA program, so they follow the masked-eval policy: the offending
positions produce NULL (divide/modulus by zero) or wrap (overflow). This is
the documented divergence from the reference's checked semantics (SURVEY
§4.4: lazy guards become input masking).

String functions operate on dictionary codes: value-level work happens once
per distinct dictionary entry on the host at trace time (a compile-time
constant), then a vectorized gather applies it to every row — the TPU
translation of per-row string processing.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, List, Optional, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.expr.values import (
    NOT_CONST,
    Val,
    broadcast_val,
    cast_data,
    civil_from_days,
    days_from_civil,
    add_months_to_days,
    div_round_half_up,
    rescale_decimal,
    union_nulls,
)
from presto_tpu.page import Dictionary


@dataclasses.dataclass
class Ctx:
    xp: object
    capacity: int


@dataclasses.dataclass
class FunctionDef:
    name: str
    resolve: Callable[[List[T.SqlType]], T.SqlType]
    impl: Callable  # impl(ctx, result_type, vals) -> Val
    propagate_nulls: bool = True


_REGISTRY: dict = {}


def register(name: str, resolve, impl, propagate_nulls: bool = True):
    _REGISTRY[name] = FunctionDef(name, resolve, impl, propagate_nulls)


def registered_names() -> list:
    """All installed scalar functions (system.functions backing;
    reference: FunctionRegistry.list())."""
    return list(_REGISTRY)


def lookup(name: str) -> FunctionDef:
    fn = _REGISTRY.get(name)
    if fn is None:
        raise KeyError(f"unknown function: {name}")
    return fn


def resolve_type(name: str, arg_types: Sequence[T.SqlType]) -> T.SqlType:
    return lookup(name).resolve(list(arg_types))


def eval_call(ctx: Ctx, name: str, result_type: T.SqlType, vals: List[Val]):
    fn = lookup(name)
    vals = [
        v if not isinstance(v, Val) else broadcast_val(
            ctx.xp, v, ctx.capacity)
        for v in vals  # non-Val args are ir.Lambda nodes, passed as-is
    ]
    out = fn.impl(ctx, result_type, vals)
    if fn.propagate_nulls:
        extra = union_nulls(
            ctx.xp, *(v.nulls for v in vals if isinstance(v, Val))
        )
        out = Val(
            out.data,
            union_nulls(ctx.xp, out.nulls, extra),
            out.type,
            out.dictionary,
            py_value=out.py_value,
        )
    return out


# ------------------------------------------------------------ type helpers

_INT_RANK = {T.TinyintType: 0, T.SmallintType: 1, T.IntegerType: 2,
             T.BigintType: 3}


def _short_decimal(p: int, s: int) -> T.DecimalType:
    """Computed decimals are physically scaled i64 (presto_tpu/expr design:
    TPU-side decimal arithmetic never widens to limbs; only aggregate sums
    produce long-decimal limb blocks). Declare the honest physical
    precision — capped at 18 — so downstream layers can tell i64 decimals
    from limb decimals by type. Reference divergence: the reference widens
    to decimal(38) and raises on overflow; we wrap at i64 (SURVEY §8.2.4:
    TPC-H money stays far below 2^63)."""
    if s > 18:
        raise TypeError(f"decimal scale {s} beyond i64 arithmetic range")
    return T.DecimalType(max(min(p, 18), s, 1), s)


def _numeric_result(a: T.SqlType, b: T.SqlType, op: str) -> T.SqlType:
    if isinstance(a, T.DoubleType) or isinstance(b, T.DoubleType):
        return T.DOUBLE
    if isinstance(a, T.RealType) or isinstance(b, T.RealType):
        if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
            return T.REAL
        return T.REAL
    if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
        da, db = T._to_decimal(a), T._to_decimal(b)
        # Reference: spi/type/DecimalType + DecimalOperators result rules,
        # with precision capped to the i64 physical representation
        if op in ("add", "subtract"):
            s = max(da.scale, db.scale)
            p = max(da.precision - da.scale, db.precision - db.scale) + s + 1
            return _short_decimal(p, s)
        if op == "multiply":
            return _short_decimal(da.precision + db.precision,
                                  da.scale + db.scale)
        if op == "divide":
            s = max(da.scale, db.scale)
            p = da.precision + db.scale + max(0, db.scale - da.scale)
            return _short_decimal(max(p, s + 1), s)
        if op == "modulus":
            s = max(da.scale, db.scale)
            p = min(da.precision - da.scale, db.precision - db.scale) + s
            return _short_decimal(max(p, s + 1), s)
    if type(a) in _INT_RANK and type(b) in _INT_RANK:
        return a if _INT_RANK[type(a)] >= _INT_RANK[type(b)] else b
    raise TypeError(f"no numeric result for {a} {op} {b}")


def _arith_resolve(op: str):
    def resolve(args: List[T.SqlType]) -> T.SqlType:
        a, b = args
        # date/timestamp +- interval
        if isinstance(a, (T.DateType, T.TimestampType)) and isinstance(
            b, (T.IntervalDayTimeType, T.IntervalYearMonthType)
        ):
            if op in ("add", "subtract"):
                return a
        if isinstance(b, (T.DateType, T.TimestampType)) and isinstance(
            a, (T.IntervalDayTimeType, T.IntervalYearMonthType)
        ):
            if op == "add":
                return b
        if isinstance(a, T.DateType) and isinstance(b, T.DateType):
            if op == "subtract":  # date - date -> days (bigint)
                return T.BIGINT
        if isinstance(a, T.IntervalDayTimeType) and isinstance(
            b, T.IntervalDayTimeType
        ):
            return a
        if isinstance(a, T.IntervalYearMonthType) and isinstance(
            b, T.IntervalYearMonthType
        ):
            return a
        return _numeric_result(a, b, op)

    return resolve


def _to_common(ctx: Ctx, val: Val, target: T.SqlType):
    data, nulls = cast_data(ctx.xp, val, target, ctx.capacity)
    return Val(data, nulls, target, val.dictionary, val.py_value)


def _decimal_scale(t: T.SqlType) -> int:
    return t.scale if isinstance(t, T.DecimalType) else 0


def _impl_arith(op: str):
    def impl(ctx: Ctx, rt: T.SqlType, vals: List[Val]) -> Val:
        xp = ctx.xp
        a, b = vals
        ta, tb = a.type, b.type

        # ---- temporal arithmetic
        if isinstance(ta, (T.IntervalDayTimeType, T.IntervalYearMonthType)) \
                and isinstance(rt, (T.DateType, T.TimestampType)):
            a, b = b, a  # normalize: temporal op interval
            ta, tb = a.type, b.type
        if isinstance(ta, (T.DateType, T.TimestampType)) and isinstance(
            tb, (T.IntervalDayTimeType, T.IntervalYearMonthType)
        ):
            amt = b.data.astype(np.int64)
            if op == "subtract":
                amt = -amt
            if isinstance(tb, T.IntervalYearMonthType):
                if isinstance(ta, T.DateType):
                    out = add_months_to_days(xp, a.data, amt)
                else:
                    micros_day = np.int64(86_400_000_000)
                    days = (a.data // micros_day).astype(np.int32)
                    rem = a.data % micros_day
                    nd = add_months_to_days(xp, days, amt)
                    out = nd.astype(np.int64) * micros_day + rem
            else:
                if isinstance(ta, T.DateType):
                    out = (
                        a.data.astype(np.int64)
                        + amt // np.int64(86_400_000_000)
                    ).astype(np.int32)
                else:
                    out = a.data + amt
            return Val(out, None, rt)
        if isinstance(ta, T.DateType) and isinstance(tb, T.DateType) \
                and op == "subtract":
            out = a.data.astype(np.int64) - b.data.astype(np.int64)
            return Val(out, None, rt)
        if isinstance(ta, (T.IntervalDayTimeType, T.IntervalYearMonthType)):
            x = a.data.astype(np.int64)
            y = b.data.astype(np.int64)
            out = x + y if op == "add" else x - y
            return Val(out.astype(np.dtype(rt.numpy_dtype)), None, rt)

        # ---- numeric
        if isinstance(rt, T.DecimalType):
            sa, sb = _decimal_scale(ta), _decimal_scale(tb)
            xa = _to_common(ctx, a, T.DecimalType(38, sa)
                            if isinstance(ta, T.DecimalType)
                            else T.DecimalType(38, 0)).data
            xb = _to_common(ctx, b, T.DecimalType(38, sb)
                            if isinstance(tb, T.DecimalType)
                            else T.DecimalType(38, 0)).data
            if op in ("add", "subtract"):
                xa = rescale_decimal(xp, xa, sa, rt.scale)
                xb = rescale_decimal(xp, xb, sb, rt.scale)
                out = xa + xb if op == "add" else xa - xb
                return Val(out, None, rt)
            if op == "multiply":
                out = rescale_decimal(xp, xa * xb, sa + sb, rt.scale)
                return Val(out, None, rt)
            if op == "divide":
                zero = xb == 0
                safe = xp.where(zero, np.int64(1), xb)
                # scale numerator so quotient lands on rt.scale
                k = rt.scale - sa + sb
                num = xa * np.int64(10**k) if k >= 0 else rescale_decimal(
                    xp, xa, -k, 0
                )
                q = div_round_half_up(xp, num, safe)
                return Val(xp.where(zero, np.int64(0), q), zero, rt)
            if op == "modulus":
                zero = xb == 0
                safe = xp.where(zero, np.int64(1), xb)
                s = rt.scale
                ra = rescale_decimal(xp, xa, sa, s)
                rb = rescale_decimal(xp, xb, sb, s)
                safe = xp.where(zero, np.int64(1), rb)
                # SQL mod keeps dividend sign (fmod), unlike floor-mod
                q = (xp.abs(ra) % xp.abs(safe))
                out = xp.where(ra >= 0, q, -q)
                return Val(xp.where(zero, np.int64(0), out), zero, rt)
        if T.is_floating(rt):
            xa = _to_common(ctx, a, rt).data
            xb = _to_common(ctx, b, rt).data
            if op == "add":
                return Val(xa + xb, None, rt)
            if op == "subtract":
                return Val(xa - xb, None, rt)
            if op == "multiply":
                return Val(xa * xb, None, rt)
            if op == "divide":
                zero = xb == 0.0
                safe = xp.where(zero, xp.ones_like(xb), xb)
                return Val(xp.where(zero, xp.zeros_like(xa), xa / safe),
                           zero, rt)
            if op == "modulus":
                zero = xb == 0.0
                safe = xp.where(zero, xp.ones_like(xb), xb)
                q = xp.abs(xa) % xp.abs(safe)
                out = xp.where(xa >= 0, q, -q)
                return Val(xp.where(zero, xp.zeros_like(xa), out), zero, rt)
        # integral
        xa = _to_common(ctx, a, rt).data
        xb = _to_common(ctx, b, rt).data
        if op == "add":
            return Val(xa + xb, None, rt)
        if op == "subtract":
            return Val(xa - xb, None, rt)
        if op == "multiply":
            return Val(xa * xb, None, rt)
        zero = xb == 0
        safe = xp.where(zero, xp.ones_like(xb), xb)
        if op == "divide":
            # SQL integer division truncates toward zero
            q = xp.abs(xa) // xp.abs(safe)
            sgn = xp.where((xa >= 0) == (safe >= 0), 1, -1).astype(xa.dtype)
            return Val(xp.where(zero, xp.zeros_like(xa), sgn * q), zero, rt)
        if op == "modulus":
            q = xp.abs(xa) % xp.abs(safe)
            out = xp.where(xa >= 0, q, -q)
            return Val(xp.where(zero, xp.zeros_like(xa), out), zero, rt)
        raise ValueError(op)

    return impl


for _op in ("add", "subtract", "multiply", "divide", "modulus"):
    register(_op, _arith_resolve(_op), _impl_arith(_op))


def _impl_negate(ctx, rt, vals):
    v = vals[0]
    return Val(-v.data, None, rt)


register("negate", lambda a: a[0], _impl_negate)


def _impl_abs(ctx, rt, vals):
    return Val(ctx.xp.abs(vals[0].data), None, rt)


register("abs", lambda a: a[0], _impl_abs)


# ------------------------------------------------------------- comparisons

def _cmp_resolve(args: List[T.SqlType]) -> T.SqlType:
    a, b = args
    if T.common_super_type(a, b) is None:
        raise TypeError(f"cannot compare {a} and {b}")
    return T.BOOLEAN


def _string_codes_for_compare(ctx: Ctx, a: Val, b: Val, ordered: bool):
    """Map two string Vals onto integer arrays whose = and < agree with SQL
    string semantics.

    Both operands are remapped through one merged *distinct sorted* value
    universe computed on the host at trace time (a compile-time constant
    gather). Canonicalizing through the set handles dictionaries that carry
    duplicate values (e.g. those produced by substr()'s dictionary_map) and
    makes order comparison exact without per-byte work on device.
    """
    xp = ctx.xp

    def col_values(v: Val):
        if v.is_const:
            return {v.py_value}
        if v.dictionary is None:
            raise TypeError("string comparison requires dictionary coding")
        return set(v.dictionary.values)

    universe = sorted(col_values(a) | col_values(b))
    pos = {v: i for i, v in enumerate(universe)}

    def canon(v: Val):
        if v.is_const:
            return xp.broadcast_to(
                xp.asarray(np.int64(pos[v.py_value])), (ctx.capacity,)
            )
        lut = np.array(
            [pos[x] for x in v.dictionary.values] or [0], np.int64
        )
        codes = xp.clip(v.data, 0, max(len(v.dictionary) - 1, 0))
        return xp.asarray(lut)[codes]

    return canon(a), canon(b)


def _impl_cmp(op: str):
    def impl(ctx: Ctx, rt: T.SqlType, vals: List[Val]) -> Val:
        xp = ctx.xp
        a, b = vals
        if T.is_string(a.type) or T.is_string(b.type):
            ordered = op not in ("eq", "ne")
            xa, xb = _string_codes_for_compare(ctx, a, b, ordered)
        else:
            ct = T.common_super_type(a.type, b.type)
            if isinstance(ct, T.DecimalType):
                # compare at common scale without precision loss
                s = max(_decimal_scale(a.type), _decimal_scale(b.type))
                ct = T.DecimalType(38, s)
            xa = _to_common(ctx, a, ct).data
            xb = _to_common(ctx, b, ct).data
        if op == "eq":
            out = xa == xb
        elif op == "ne":
            out = xa != xb
        elif op == "lt":
            out = xa < xb
        elif op == "le":
            out = xa <= xb
        elif op == "gt":
            out = xa > xb
        else:
            out = xa >= xb
        return Val(out, None, T.BOOLEAN)

    return impl


for _op in ("eq", "ne", "lt", "le", "gt", "ge"):
    register(_op, _cmp_resolve, _impl_cmp(_op))


def _impl_not(ctx, rt, vals):
    return Val(~vals[0].data.astype(bool), None, T.BOOLEAN)


register("not", lambda a: T.BOOLEAN, _impl_not)


# ------------------------------------------------------------------- casts

def _impl_cast(ctx: Ctx, rt: T.SqlType, vals: List[Val]) -> Val:
    v = vals[0]
    data, nulls = cast_data(ctx.xp, v, rt, ctx.capacity)
    if T.is_string(rt) and T.is_string(v.type):
        # varchar(n) <-> varchar keeps the dictionary codes; dropping
        # the dictionary (or a constant's py_value) here would decode
        # the codes as bare integers downstream
        return Val(data, nulls, rt, v.dictionary, py_value=v.py_value)
    return Val(data, nulls, rt)


register("cast", lambda a: a[0], _impl_cast)


# ----------------------------------------------------------------- temporal

def _impl_date_part(part: str):
    def impl(ctx: Ctx, rt: T.SqlType, vals: List[Val]) -> Val:
        xp = ctx.xp
        v = vals[0]
        days = v.data
        if isinstance(v.type, T.TimestampType):
            days = (days // np.int64(86_400_000_000)).astype(np.int32)
        y, m, d = civil_from_days(xp, days)
        if part == "year":
            out = y
        elif part == "month":
            out = m
        elif part == "day":
            out = d
        elif part == "quarter":
            out = (m - 1) // np.int64(3) + np.int64(1)
        elif part == "week":
            # ISO week number
            doy_monday = (days.astype(np.int64) + np.int64(3)) % np.int64(7)
            thursday = days.astype(np.int64) + (np.int64(3) - doy_monday)
            ty, _, _ = civil_from_days(xp, thursday)
            jan1 = days_from_civil(
                xp, ty, xp.ones_like(ty), xp.ones_like(ty)
            )
            out = (thursday - jan1) // np.int64(7) + np.int64(1)
        elif part == "day_of_week":
            out = (days.astype(np.int64) + np.int64(3)) % np.int64(7) + 1
        elif part == "day_of_year":
            jan1 = days_from_civil(xp, y, xp.ones_like(y), xp.ones_like(y))
            out = days.astype(np.int64) - jan1 + np.int64(1)
        else:
            raise ValueError(part)
        return Val(out.astype(np.int64), None, T.BIGINT)

    return impl


def _temporal_resolve(args):
    if not isinstance(args[0], (T.DateType, T.TimestampType)):
        raise TypeError(f"temporal function over {args[0]}")
    return T.BIGINT


for _part in ("year", "month", "day", "quarter", "week", "day_of_week",
              "day_of_year"):
    register(_part, _temporal_resolve, _impl_date_part(_part))


# --------------------------------------------------------- string functions

def _dict_of(val: Val) -> Dictionary:
    if val.dictionary is None:
        raise TypeError("string function requires a dictionary-coded value")
    return val.dictionary


def _dict_map(ctx: Ctx, val: Val, fn, rt: T.SqlType) -> Val:
    """Apply a per-value host transform over the dictionary once; codes are
    unchanged. The new dictionary may contain duplicates by value — harmless
    for projection; equality comparisons re-canonicalize via merge."""
    d = _dict_of(val)
    new = Dictionary([fn(v) for v in d.values])
    return Val(val.data, val.nulls, rt, new)


def _dict_predicate(ctx: Ctx, val: Val, pred) -> Val:
    d = _dict_of(val)
    lut = np.array([bool(pred(v)) for v in d.values] or [False], bool)
    codes = ctx.xp.clip(val.data, 0, max(len(d) - 1, 0))
    return Val(ctx.xp.asarray(lut)[codes], None, T.BOOLEAN)


def _dict_int(ctx: Ctx, val: Val, fn) -> Val:
    d = _dict_of(val)
    lut = np.array([int(fn(v)) for v in d.values] or [0], np.int64)
    codes = ctx.xp.clip(val.data, 0, max(len(d) - 1, 0))
    return Val(ctx.xp.asarray(lut)[codes], None, T.BIGINT)


def like_pattern_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    """Translate a SQL LIKE pattern to an anchored Python regex (reference:
    joni-based LikeFunctions.likePattern)."""
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "^" + "".join(out) + "$"


def _impl_like(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col, pat = vals[0], vals[1]
    if not pat.is_const:
        raise TypeError("LIKE pattern must be a constant")
    esc = None
    if len(vals) == 3:
        if not vals[2].is_const:
            raise TypeError("LIKE escape must be a constant")
        esc = vals[2].py_value
    rx = re.compile(like_pattern_to_regex(pat.py_value, esc), re.DOTALL)
    return _dict_predicate(ctx, col, lambda v: rx.match(str(v)) is not None)


register("like", lambda a: T.BOOLEAN, _impl_like)


def _substr(value: str, start: int, length: Optional[int] = None) -> str:
    # SQL substr is 1-based; negative start counts from the end
    s = str(value)
    if start > 0:
        begin = start - 1
    elif start < 0:
        begin = max(len(s) + start, 0)
    else:
        return ""
    end = len(s) if length is None else min(begin + max(length, 0), len(s))
    return s[begin:end]


def _impl_substr(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    if not all(v.is_const for v in vals[1:]):
        raise TypeError("substr start/length must be constants")
    start = int(vals[1].py_value)
    length = int(vals[2].py_value) if len(vals) == 3 else None
    return _dict_map(ctx, col, lambda v: _substr(v, start, length), rt)


register("substr", lambda a: T.VARCHAR, _impl_substr)
register("substring", lambda a: T.VARCHAR, _impl_substr)


def _impl_strfn(fn):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        return _dict_map(ctx, vals[0], fn, rt)

    return impl


register("lower", lambda a: a[0], _impl_strfn(lambda v: str(v).lower()))
register("upper", lambda a: a[0], _impl_strfn(lambda v: str(v).upper()))
register("trim", lambda a: a[0], _impl_strfn(lambda v: str(v).strip()))
register("ltrim", lambda a: a[0], _impl_strfn(lambda v: str(v).lstrip()))
register("rtrim", lambda a: a[0], _impl_strfn(lambda v: str(v).rstrip()))
register(
    "length",
    lambda a: T.BIGINT,
    lambda ctx, rt, vals: _dict_int(ctx, vals[0], lambda v: len(str(v))),
)


def _impl_concat(ctx: Ctx, rt, vals: List[Val]) -> Val:
    # column-with-constants concat; column∘column concat requires the cross
    # dictionary product and is deferred until a workload needs it
    cols = [v for v in vals if not v.is_const]
    if len(cols) != 1:
        raise TypeError("concat supports one column plus constants (v1)")
    col = cols[0]
    parts = [
        (None if not v.is_const else str(v.py_value)) for v in vals
    ]

    def fn(value):
        return "".join(p if p is not None else str(value) for p in parts)

    return _dict_map(ctx, col, fn, rt)


register("concat", lambda a: T.VARCHAR, _impl_concat)


# --------------------------------------------------------------- math misc

def _impl_round(ctx: Ctx, rt, vals: List[Val]) -> Val:
    xp = ctx.xp
    v = vals[0]
    n = int(vals[1].py_value) if len(vals) == 2 else 0
    if isinstance(v.type, T.DecimalType):
        out = rescale_decimal(xp, v.data, v.type.scale, min(n, v.type.scale))
        out = rescale_decimal(xp, out, min(n, v.type.scale), rt.scale)
        return Val(out, None, rt)
    scale = float(10**n)
    x = v.data * scale
    r = xp.where(x >= 0, xp.floor(x + 0.5), xp.ceil(x - 0.5))
    return Val((r / scale).astype(v.data.dtype), None, rt)


def _round_resolve(args):
    return args[0]


register("round", _round_resolve, _impl_round)


def _impl_floorceil(which):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        xp = ctx.xp
        v = vals[0]
        if isinstance(v.type, T.DecimalType):
            s = np.int64(10**v.type.scale)
            if which == "floor":
                out = v.data // s
            else:
                out = -((-v.data) // s)
            return Val(out * np.int64(10**rt.scale)
                       if isinstance(rt, T.DecimalType) else out, None, rt)
        f = xp.floor if which == "floor" else xp.ceil
        return Val(f(v.data), None, rt)

    return impl


def _floorceil_resolve(args):
    t = args[0]
    if isinstance(t, T.DecimalType):
        return T.DecimalType(min(38, t.precision - t.scale + 1), 0)
    return t


register("floor", _floorceil_resolve, _impl_floorceil("floor"))
register("ceil", _floorceil_resolve, _impl_floorceil("ceil"))
register("ceiling", _floorceil_resolve, _impl_floorceil("ceil"))


def _impl_double_fn(fn_name):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        xp = ctx.xp
        x = _to_common(ctx, vals[0], T.DOUBLE).data
        if fn_name == "sqrt":
            bad = x < 0
            out = xp.sqrt(xp.where(bad, 0.0, x))
            return Val(xp.where(bad, xp.asarray(np.nan), out), None, T.DOUBLE)
        if fn_name == "ln":
            bad = x <= 0
            out = xp.log(xp.where(bad, 1.0, x))
            return Val(xp.where(bad, xp.asarray(np.nan), out), None, T.DOUBLE)
        if fn_name == "exp":
            return Val(xp.exp(x), None, T.DOUBLE)
        raise ValueError(fn_name)

    return impl


for _f in ("sqrt", "ln", "exp"):
    register(_f, lambda a: T.DOUBLE, _impl_double_fn(_f))


def _impl_power(ctx: Ctx, rt, vals: List[Val]) -> Val:
    xp = ctx.xp
    x = _to_common(ctx, vals[0], T.DOUBLE).data
    y = _to_common(ctx, vals[1], T.DOUBLE).data
    out = xp.power(xp.abs(x), y) * xp.where(
        (x < 0) & (y % 2 == 1), -1.0, 1.0)
    # Java Math.pow: FINITE negative base with non-integer exponent -> NaN
    # (pow(-inf, 0.5) = +inf, pow(-inf, -0.5) = +0.0 — keep those)
    out = xp.where(
        (x < 0) & xp.isfinite(x) & (y != xp.floor(y)),
        xp.float64(xp.nan), out,
    )
    return Val(out, None, T.DOUBLE)


register("power", lambda a: T.DOUBLE, _impl_power)
register("pow", lambda a: T.DOUBLE, _impl_power)


def _impl_greatest_least(which):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        xp = ctx.xp
        acc = _to_common(ctx, vals[0], rt).data
        for v in vals[1:]:
            x = _to_common(ctx, v, rt).data
            acc = xp.maximum(acc, x) if which == "greatest" else xp.minimum(
                acc, x)
        return Val(acc, None, rt)

    return impl


def _var_numeric_resolve(args):
    t = args[0]
    for a in args[1:]:
        nxt = T.common_super_type(t, a)
        if nxt is None:
            raise TypeError(f"incompatible args: {t} vs {a}")
        t = nxt
    return t


register("greatest", _var_numeric_resolve, _impl_greatest_least("greatest"))
register("least", _var_numeric_resolve, _impl_greatest_least("least"))


# ----------------------------------------------- round-3 breadth batch
# Reference: presto-main metadata/FunctionRegistry.java registrations for
# MathFunctions, StringFunctions, DateTimeFunctions, JoniRegexpFunctions,
# ConditionalFunctions — the most-used subset, TPU-idiomatic: numeric
# work stays vectorized on device; string/regex work happens once per
# distinct dictionary entry on the host at trace time.


def _impl_simple_double(fn):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        xp = ctx.xp
        x = _to_common(ctx, vals[0], T.DOUBLE).data
        return Val(fn(xp, x), None, T.DOUBLE)

    return impl


for _name, _fn in [
    ("log2", lambda xp, x: xp.log2(xp.where(x <= 0, np.nan, x))),
    ("log10", lambda xp, x: xp.log10(xp.where(x <= 0, np.nan, x))),
    ("cbrt", lambda xp, x: xp.sign(x) * xp.power(xp.abs(x), 1.0 / 3.0)),
    ("sin", lambda xp, x: xp.sin(x)),
    ("cos", lambda xp, x: xp.cos(x)),
    ("tan", lambda xp, x: xp.tan(x)),
    ("asin", lambda xp, x: xp.arcsin(x)),
    ("acos", lambda xp, x: xp.arccos(x)),
    ("atan", lambda xp, x: xp.arctan(x)),
    ("sinh", lambda xp, x: xp.sinh(x)),
    ("cosh", lambda xp, x: xp.cosh(x)),
    ("tanh", lambda xp, x: xp.tanh(x)),
    ("degrees", lambda xp, x: x * (180.0 / np.pi)),
    ("radians", lambda xp, x: x * (np.pi / 180.0)),
    ("truncate", lambda xp, x: xp.trunc(x)),
]:
    register(_name, lambda a: T.DOUBLE, _impl_simple_double(_fn))


def _impl_atan2(ctx: Ctx, rt, vals: List[Val]) -> Val:
    xp = ctx.xp
    y = _to_common(ctx, vals[0], T.DOUBLE).data
    x = _to_common(ctx, vals[1], T.DOUBLE).data
    return Val(xp.arctan2(y, x), None, T.DOUBLE)


register("atan2", lambda a: T.DOUBLE, _impl_atan2)


def _impl_log_base(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """log(b, x) = ln(x)/ln(b) (reference: MathFunctions.log)."""
    xp = ctx.xp
    b = _to_common(ctx, vals[0], T.DOUBLE).data
    x = _to_common(ctx, vals[1], T.DOUBLE).data
    return Val(
        xp.log(xp.where(x <= 0, np.nan, x))
        / xp.log(xp.where(b <= 0, np.nan, b)),
        None, T.DOUBLE,
    )


register("log", lambda a: T.DOUBLE, _impl_log_base)


def _impl_mod(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """mod(a, b) with Java remainder semantics (sign follows the
    dividend); b == 0 -> NULL (masked-eval policy, module docstring)."""
    xp = ctx.xp
    ct = _var_numeric_resolve([vals[0].type, vals[1].type])
    a = _to_common(ctx, vals[0], ct).data
    b = _to_common(ctx, vals[1], ct).data
    zero = b == 0
    safe_b = xp.where(zero, 1, b)
    if T.is_floating(ct):
        out = a - xp.trunc(a / safe_b) * safe_b
    else:
        # truncation remainder: a - trunc(a/b)*b, via abs-quotient
        q = xp.abs(a) // xp.abs(safe_b)
        out = a - xp.sign(a) * q * xp.abs(safe_b)
    return Val(xp.where(zero, 0, out), zero, ct)


register("mod", lambda a: _var_numeric_resolve(a), _impl_mod)


def _impl_sign(ctx: Ctx, rt, vals: List[Val]) -> Val:
    xp = ctx.xp
    v = vals[0]
    return Val(xp.sign(v.data).astype(v.data.dtype), None, v.type)


register("sign", lambda a: a[0], _impl_sign)


def _impl_zero_arg(value):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        return Val(ctx.xp.asarray(np.float64(value)), None, T.DOUBLE)

    return impl


register("pi", lambda a: T.DOUBLE, _impl_zero_arg(np.pi))
register("e", lambda a: T.DOUBLE, _impl_zero_arg(np.e))
register("infinity", lambda a: T.DOUBLE, _impl_zero_arg(np.inf))
register("nan", lambda a: T.DOUBLE, _impl_zero_arg(np.nan))


def _impl_float_pred(fn):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        xp = ctx.xp
        x = _to_common(ctx, vals[0], T.DOUBLE).data
        return Val(fn(xp, x), None, T.BOOLEAN)

    return impl


register("is_nan", lambda a: T.BOOLEAN,
         _impl_float_pred(lambda xp, x: xp.isnan(x)))
register("is_finite", lambda a: T.BOOLEAN,
         _impl_float_pred(lambda xp, x: xp.isfinite(x)))
register("is_infinite", lambda a: T.BOOLEAN,
         _impl_float_pred(lambda xp, x: xp.isinf(x)))


def _impl_width_bucket(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """width_bucket(x, lo, hi, n) (reference: MathFunctions)."""
    xp = ctx.xp
    x = _to_common(ctx, vals[0], T.DOUBLE).data
    lo = _to_common(ctx, vals[1], T.DOUBLE).data
    hi = _to_common(ctx, vals[2], T.DOUBLE).data
    n = _to_common(ctx, vals[3], T.BIGINT).data
    width = (hi - lo) / xp.maximum(n, 1).astype(xp.float64)
    raw = xp.floor((x - lo) / xp.where(width == 0, 1.0, width)) + 1
    out = xp.clip(raw, 0, (n + 1).astype(xp.float64)).astype(np.int64)
    return Val(out, None, T.BIGINT)


register("width_bucket", lambda a: T.BIGINT, _impl_width_bucket)


# ------------------------------------------------------------ conditional

def _impl_nullif(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """NULLIF(a, b): NULL where a = b, else a. Null semantics: a NULL ->
    NULL; b NULL -> a (equality unknown keeps a). Reuses the comparison
    kernel so string/dictionary/decimal coercions match `=` exactly."""
    a, b = vals
    eq = _impl_cmp("eq")(ctx, T.BOOLEAN, [a, b])
    xp = ctx.xp
    b_null = b.nulls if b.nulls is not None else None
    is_eq = eq.data
    if b_null is not None:
        is_eq = is_eq & ~b_null
    nulls = union_nulls(xp, a.nulls, is_eq)
    return Val(a.data, nulls, a.type, a.dictionary)


register("nullif", lambda a: a[0], _impl_nullif, propagate_nulls=False)


# ----------------------------------------------------------------- regexp

def _const_pattern(vals: List[Val], idx: int) -> str:
    p = vals[idx]
    if not p.is_const:
        raise TypeError("regexp pattern must be a constant")
    return str(p.py_value)


def _impl_regexp_like(ctx: Ctx, rt, vals: List[Val]) -> Val:
    rx = re.compile(_const_pattern(vals, 1))
    return _dict_predicate(
        ctx, vals[0], lambda v: rx.search(str(v)) is not None
    )


register("regexp_like", lambda a: T.BOOLEAN, _impl_regexp_like)


def _dict_map_nullable(ctx: Ctx, val: Val, fn, rt: T.SqlType) -> Val:
    """_dict_map variant where fn may return None (SQL NULL): the
    per-distinct-value null flags gather into a row null mask."""
    d = _dict_of(val)
    results = [fn(v) for v in d.values]
    new = Dictionary(["" if r is None else r for r in results])
    isnull = np.array([r is None for r in results] or [False], bool)
    codes = ctx.xp.clip(val.data, 0, max(len(d) - 1, 0))
    nulls = ctx.xp.asarray(isnull)[codes]
    return Val(val.data, union_nulls(ctx.xp, val.nulls, nulls), rt, new)


def _impl_regexp_extract(ctx: Ctx, rt, vals: List[Val]) -> Val:
    rx = re.compile(_const_pattern(vals, 1))
    group = int(vals[2].py_value) if len(vals) > 2 else 0

    def ext(v):
        m = rx.search(str(v))
        return m.group(group) if m else None  # no match -> NULL

    return _dict_map_nullable(ctx, vals[0], ext, T.VARCHAR)


register("regexp_extract", lambda a: T.VARCHAR, _impl_regexp_extract)


def _impl_regexp_replace(ctx: Ctx, rt, vals: List[Val]) -> Val:
    rx = re.compile(_const_pattern(vals, 1))
    repl = ""
    if len(vals) > 2:
        if not vals[2].is_const:
            raise TypeError("regexp replacement must be a constant")
        # Presto uses $1 group refs; Python uses \1
        repl = re.sub(r"\$(\d+)", r"\\\1", str(vals[2].py_value))
    return _dict_map(
        ctx, vals[0], lambda v: rx.sub(repl, str(v)), T.VARCHAR
    )


register("regexp_replace", lambda a: T.VARCHAR, _impl_regexp_replace)


# ----------------------------------------------------------------- string

register("length", lambda a: T.BIGINT,
         lambda ctx, rt, vals: _dict_int(ctx, vals[0],
                                         lambda v: len(str(v))))
register("codepoint", lambda a: T.BIGINT,
         lambda ctx, rt, vals: _dict_int(
             ctx, vals[0], lambda v: ord(str(v)[0]) if str(v) else 0))
register("reverse", lambda a: T.VARCHAR,
         lambda ctx, rt, vals: _dict_map(ctx, vals[0],
                                         lambda v: str(v)[::-1], rt))


def _impl_strpos(ctx: Ctx, rt, vals: List[Val]) -> Val:
    sub = vals[1]
    if not sub.is_const:
        raise TypeError("strpos substring must be a constant")
    s = str(sub.py_value)
    return _dict_int(ctx, vals[0], lambda v: str(v).find(s) + 1)


register("strpos", lambda a: T.BIGINT, _impl_strpos)
register("position", lambda a: T.BIGINT, _impl_strpos)


def _impl_replace(ctx: Ctx, rt, vals: List[Val]) -> Val:
    if not (vals[1].is_const and (len(vals) < 3 or vals[2].is_const)):
        raise TypeError("replace search/replacement must be constants")
    find = str(vals[1].py_value)
    repl = str(vals[2].py_value) if len(vals) > 2 else ""
    return _dict_map(
        ctx, vals[0], lambda v: str(v).replace(find, repl), T.VARCHAR
    )


register("replace", lambda a: T.VARCHAR, _impl_replace)


def _impl_pad(side):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        if not (vals[1].is_const and vals[2].is_const):
            raise TypeError("lpad/rpad size and padstring must be constants")
        n = int(vals[1].py_value)
        pad = str(vals[2].py_value) or " "

        def do(v):
            s = str(v)
            if len(s) >= n:
                return s[:n]
            fill = (pad * n)[: n - len(s)]
            return fill + s if side == "l" else s + fill

        return _dict_map(ctx, vals[0], do, T.VARCHAR)

    return impl


register("lpad", lambda a: T.VARCHAR, _impl_pad("l"))
register("rpad", lambda a: T.VARCHAR, _impl_pad("r"))


def _impl_split_part(ctx: Ctx, rt, vals: List[Val]) -> Val:
    if not (vals[1].is_const and vals[2].is_const):
        raise TypeError("split_part delimiter/index must be constants")
    delim = str(vals[1].py_value)
    idx = int(vals[2].py_value)

    def do(v):
        parts = str(v).split(delim)
        return parts[idx - 1] if 1 <= idx <= len(parts) else ""

    return _dict_map(ctx, vals[0], do, T.VARCHAR)


register("split_part", lambda a: T.VARCHAR, _impl_split_part)


# --------------------------------------------------------------- temporal

_US = np.int64(1_000_000)
_US_DAY = np.int64(86_400_000_000)


def _days_and_us(v: Val):
    """(days, intraday microseconds, is_timestamp) from a date/ts Val."""
    if isinstance(v.type, T.TimestampType):
        days = (v.data // _US_DAY).astype(np.int32)
        return days, v.data - days.astype(np.int64) * _US_DAY, True
    return v.data, None, False


def _impl_date_trunc(ctx: Ctx, rt, vals: List[Val]) -> Val:
    if not vals[0].is_const:
        raise TypeError("date_trunc unit must be a constant")
    unit = str(vals[0].py_value).lower()
    xp = ctx.xp
    v = vals[1]
    days, us, is_ts = _days_and_us(v)
    if unit in ("hour", "minute", "second", "millisecond"):
        if not is_ts:
            return Val(v.data, None, v.type, v.dictionary)
        q = {"hour": np.int64(3_600_000_000),
             "minute": np.int64(60_000_000),
             "second": _US,
             "millisecond": np.int64(1000)}[unit]
        return Val(v.data - (v.data % q), None, v.type)
    y, m, _d = civil_from_days(xp, days)
    one = xp.ones_like(y)
    if unit == "day":
        out_days = days.astype(np.int64)
    elif unit == "week":
        out_days = days.astype(np.int64) - (
            (days.astype(np.int64) + np.int64(3)) % np.int64(7)
        )
    elif unit == "month":
        out_days = days_from_civil(xp, y, m, one)
    elif unit == "quarter":
        qm = ((m - 1) // np.int64(3)) * np.int64(3) + np.int64(1)
        out_days = days_from_civil(xp, y, qm, one)
    elif unit == "year":
        out_days = days_from_civil(xp, y, one, one)
    else:
        raise ValueError(f"date_trunc unit {unit!r}")
    if is_ts:
        return Val(out_days * _US_DAY, None, v.type)
    return Val(out_days.astype(v.data.dtype), None, v.type)


register("date_trunc", lambda a: a[1], _impl_date_trunc)


def _impl_date_add(ctx: Ctx, rt, vals: List[Val]) -> Val:
    if not vals[0].is_const:
        raise TypeError("date_add unit must be a constant")
    unit = str(vals[0].py_value).lower()
    xp = ctx.xp
    n = _to_common(ctx, vals[1], T.BIGINT).data
    v = vals[2]
    days, us, is_ts = _days_and_us(v)
    if unit in ("hour", "minute", "second", "millisecond"):
        if not is_ts:
            raise TypeError(f"date_add({unit}) over DATE")
        q = {"hour": np.int64(3_600_000_000),
             "minute": np.int64(60_000_000),
             "second": _US,
             "millisecond": np.int64(1000)}[unit]
        return Val(v.data + n * q, None, v.type)
    if unit in ("day", "week"):
        k = np.int64(7) if unit == "week" else np.int64(1)
        out_days = days.astype(np.int64) + n * k
    elif unit in ("month", "quarter", "year"):
        k = {"month": 1, "quarter": 3, "year": 12}[unit]
        out_days = add_months_to_days(
            xp, days.astype(np.int64), n * np.int64(k)
        )
    else:
        raise ValueError(f"date_add unit {unit!r}")
    if is_ts:
        return Val(out_days * _US_DAY + us, None, v.type)
    return Val(out_days.astype(v.data.dtype), None, v.type)


register("date_add", lambda a: a[2], _impl_date_add)


def _impl_date_diff(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """date_diff(unit, a, b) = complete units from a to b (reference:
    DateTimeFunctions via Joda *.between — counts whole periods)."""
    if not vals[0].is_const:
        raise TypeError("date_diff unit must be a constant")
    unit = str(vals[0].py_value).lower()
    xp = ctx.xp
    a, b = vals[1], vals[2]
    da, ua, a_ts = _days_and_us(a)
    db, ub, b_ts = _days_and_us(b)
    usa = da.astype(np.int64) * _US_DAY + (ua if ua is not None else 0)
    usb = db.astype(np.int64) * _US_DAY + (ub if ub is not None else 0)
    if unit in ("hour", "minute", "second", "millisecond", "day", "week"):
        # complete elapsed units, truncated toward zero (Joda *.between):
        # day/week over timestamps count whole 24h/168h periods, not
        # calendar-day boundaries
        q = {"hour": np.int64(3_600_000_000),
             "minute": np.int64(60_000_000),
             "second": _US,
             "millisecond": np.int64(1000),
             "day": _US_DAY,
             "week": _US_DAY * np.int64(7)}[unit]
        delta = usb - usa
        out = xp.sign(delta) * (xp.abs(delta) // q)
        return Val(out, None, T.BIGINT)
    if unit in ("month", "quarter", "year"):
        ya, ma, dda = civil_from_days(xp, da)
        yb, mb, ddb = civil_from_days(xp, db)
        months = (yb.astype(np.int64) - ya.astype(np.int64)) * 12 + (
            mb.astype(np.int64) - ma.astype(np.int64)
        )
        # incomplete final month doesn't count (Joda monthsBetween)
        incomplete = xp.where(
            months > 0, ddb < dda, xp.where(months < 0, ddb > dda, False)
        )
        months = months - xp.where(
            incomplete, xp.sign(months), np.int64(0)
        )
        k = {"month": 1, "quarter": 3, "year": 12}[unit]
        return Val(months // np.int64(k) if k == 1 else
                   xp.sign(months) * (xp.abs(months) // np.int64(k)),
                   None, T.BIGINT)
    raise ValueError(f"date_diff unit {unit!r}")


register("date_diff", lambda a: T.BIGINT, _impl_date_diff)


def _impl_from_unixtime(ctx: Ctx, rt, vals: List[Val]) -> Val:
    x = _to_common(ctx, vals[0], T.DOUBLE).data
    return Val((x * 1e6).astype(np.int64), None, T.TIMESTAMP)


def _impl_to_unixtime(ctx: Ctx, rt, vals: List[Val]) -> Val:
    return Val(vals[0].data.astype(np.float64) / 1e6, None, T.DOUBLE)


register("from_unixtime", lambda a: T.TIMESTAMP, _impl_from_unixtime)
register("to_unixtime", lambda a: T.DOUBLE, _impl_to_unixtime)


for _part in ("hour", "minute", "second", "millisecond"):
    def _impl_ts_part(part=_part):
        def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
            v = vals[0]
            if not isinstance(v.type, T.TimestampType):
                raise TypeError(f"{part}() over {v.type}")
            q = {"hour": (np.int64(3_600_000_000), np.int64(24)),
                 "minute": (np.int64(60_000_000), np.int64(60)),
                 "second": (_US, np.int64(60)),
                 "millisecond": (np.int64(1000), np.int64(1000))}[part]
            return Val((v.data // q[0]) % q[1], None, T.BIGINT)

        return impl

    register(_part, lambda a: T.BIGINT, _impl_ts_part())


# ------------------------------------------------- complex types (v1)
# ARRAY/MAP/ROW values are dictionary-coded (host tuples, i32 codes) —
# per-distinct-value work at trace time, vectorized gathers per row
# (same scheme as strings; reference: spi/block/{Array,Map,Row}Block +
# operator/scalar/{Array,Map}Functions).


def _impl_cardinality(ctx: Ctx, rt, vals: List[Val]) -> Val:
    return _dict_int(ctx, vals[0], lambda v: len(v))


def _cardinality_resolve(args):
    if not isinstance(args[0], (T.ArrayType, T.MapType)):
        raise TypeError(f"cardinality over {args[0]}")
    return T.BIGINT


register("cardinality", _cardinality_resolve, _impl_cardinality)


def _elem_result_val(ctx: Ctx, col: Val, results, elem_t: T.SqlType) -> Val:
    """Per-distinct-value results (may contain None) -> a typed Val:
    dictionary-coded element types build a new dictionary; numeric
    element types gather from a typed lut."""
    codes = ctx.xp.clip(col.data, 0, max(len(results) - 1, 0))
    isnull = np.array([r is None for r in results] or [True], bool)
    nulls = union_nulls(
        ctx.xp, col.nulls, ctx.xp.asarray(isnull)[codes]
    )
    if elem_t.is_dictionary_encoded:
        d = Dictionary(["" if r is None else r for r in results])
        return Val(col.data, nulls, elem_t, d)
    lut = np.zeros((max(len(results), 1),),
                   np.dtype(elem_t.numpy_dtype))
    for i, r in enumerate(results):
        if r is not None:
            lut[i] = r
    return Val(ctx.xp.asarray(lut)[codes], nulls, elem_t)


def _impl_element_at(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """element_at(array, index) / element_at(map, key) / element_at(row,
    ordinal). Array/row indices are 1-based; out-of-range and missing
    map keys yield NULL (reference: MapFunctions.elementAt /
    ArrayFunctions)."""
    col, key = vals[0], vals[1]
    if not key.is_const:
        raise TypeError("element_at key/index must be a constant")
    k = key.py_value
    d = _dict_of(col)
    t = col.type
    if isinstance(t, T.MapType):
        def get(v):
            for mk, mv in v:
                if mk == k:
                    return mv
            return None

        return _elem_result_val(
            ctx, col, [get(v) for v in d.values], t.value
        )
    # array / row: 1-based ordinal
    idx = int(k)

    def at(v):
        return v[idx - 1] if 1 <= idx <= len(v) else None

    elem_t = (t.element if isinstance(t, T.ArrayType)
              else (t.fields[idx - 1] if isinstance(t, T.RowType)
                    and 1 <= idx <= len(t.fields) else T.UNKNOWN))
    return _elem_result_val(
        ctx, col, [at(v) for v in d.values], elem_t
    )


def _element_at_resolve(args):
    t = args[0]
    if isinstance(t, T.ArrayType):
        return t.element
    if isinstance(t, T.MapType):
        return t.value
    if isinstance(t, T.RowType):
        return T.UNKNOWN  # refined at eval; constants resolve later
    raise TypeError(f"element_at over {t}")


register("element_at", _element_at_resolve, _impl_element_at)


def _impl_contains(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col, needle = vals[0], vals[1]
    if not needle.is_const:
        raise TypeError("contains() value must be a constant")
    k = needle.py_value
    return _dict_predicate(ctx, col, lambda v: k in v)


register("contains", lambda a: T.BOOLEAN, _impl_contains)


def _impl_array_minmax(which):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        col = vals[0]
        d = _dict_of(col)
        f = min if which == "min" else max

        def get(v):
            xs = [x for x in v if x is not None]
            return f(xs) if xs else None

        return _elem_result_val(
            ctx, col, [get(v) for v in d.values], col.type.element
        )

    return impl


def _array_elem_resolve(args):
    if not isinstance(args[0], T.ArrayType):
        raise TypeError(f"array function over {args[0]}")
    return args[0].element


register("array_min", _array_elem_resolve, _impl_array_minmax("min"))
register("array_max", _array_elem_resolve, _impl_array_minmax("max"))


def _impl_map_keys_values(which):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        col = vals[0]
        t = col.type
        d = _dict_of(col)
        i = 0 if which == "keys" else 1
        results = [tuple(pair[i] for pair in v) for v in d.values]
        elem = t.key if which == "keys" else t.value
        new = Dictionary(results)
        return Val(col.data, col.nulls, T.ArrayType(elem), new)

    return impl


def _map_arr_resolve(which):
    def resolve(args):
        t = args[0]
        if not isinstance(t, T.MapType):
            raise TypeError(f"map function over {t}")
        return T.ArrayType(t.key if which == "keys" else t.value)

    return resolve


register("map_keys", _map_arr_resolve("keys"),
         _impl_map_keys_values("keys"))
register("map_values", _map_arr_resolve("values"),
         _impl_map_keys_values("values"))


def _impl_map_ctor(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """map(key_array, value_array) over constant arrays."""
    ka, va = vals[0], vals[1]
    if not (ka.is_const and va.is_const):
        raise TypeError("map() arguments must be constant arrays")
    pairs = tuple(zip(ka.py_value, va.py_value))
    t = T.MapType(ka.type.element, va.type.element)
    return Val(ctx.xp.zeros((), dtype=np.int32), None, t,
               Dictionary([pairs]), py_value=pairs)


def _map_ctor_resolve(args):
    if len(args) != 2 or not all(
        isinstance(a, T.ArrayType) for a in args
    ):
        raise TypeError("map() takes two array arguments")
    return T.MapType(args[0].element, args[1].element)


register("map", _map_ctor_resolve, _impl_map_ctor)


def _impl_row_ctor(ctx: Ctx, rt, vals: List[Val]) -> Val:
    if not all(v.is_const for v in vals):
        raise TypeError("row() arguments must be constants")
    tup = tuple(v.py_value for v in vals)
    t = T.RowType(tuple(v.type for v in vals))
    return Val(ctx.xp.zeros((), dtype=np.int32), None, t,
               Dictionary([tup]), py_value=tup)


register("row", lambda a: T.RowType(tuple(a)), _impl_row_ctor)


# extended builtin families (JSON, TRY/TRY_CAST, bitwise, URL, array/map
# utilities) register themselves on import — see functions_ext.py
from presto_tpu.expr import functions_ext  # noqa: E402,F401  isort:skip
from presto_tpu.expr import functions_more  # noqa: E402,F401  isort:skip
