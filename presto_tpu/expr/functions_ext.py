"""Extended scalar builtins: JSON, TRY/TRY_CAST, bitwise, URL, array/map
utilities, and misc string/date functions.

Reference: presto-main operator/scalar/* — JsonFunctions + JsonExtract,
TryCastFunction / the TRY special form, BitwiseFunctions, UrlFunctions,
ArrayFunctions (array_distinct/array_sort/array_join/slice/sequence...),
MapFunctions. Same evaluation model as presto_tpu/expr/functions.py:
value-level work happens once per distinct dictionary entry on the host
at trace time, vectorized gathers apply it per row.

Divergences (documented):
- JSON is canonicalized varchar, not a distinct type: json_parse
  validates + canonicalizes; json functions accept any varchar JSON.
- TRY is an identity pass-through: this engine already follows the
  masked-eval policy (value-dependent errors produce NULL instead of
  raising — see functions.py module docstring), so TRY(x) == x. It is
  registered so reference SQL runs unchanged.
- CAST from varchar parses per distinct value; unparsable values yield
  NULL under both cast and try_cast (the reference raises for cast).
"""

from __future__ import annotations

import json
import re
import urllib.parse
from typing import List, Optional

import numpy as np

from presto_tpu import types as T
from presto_tpu.exec import xfer as XF
from presto_tpu.expr.functions import (
    Ctx,
    _elem_result_val,
    lookup,
    register,
)
from presto_tpu.expr.functions import _dict_of as _base_dict_of
from presto_tpu.expr.values import Val, rescale_decimal, union_nulls
from presto_tpu.page import Dictionary


def _dict_of(val: Val) -> Dictionary:
    """Like functions._dict_of, but accepts string constants too: a
    literal becomes a one-entry dictionary (its broadcast codes are
    zeros, which index entry 0)."""
    if (val.dictionary is None and val.is_const
            and val.py_value is not None):
        return Dictionary([val.py_value])
    return _base_dict_of(val)


def _codes(ctx: Ctx, col: Val, n: int):
    return ctx.xp.clip(col.data, 0, max(n - 1, 0))


def _varchar_results(ctx: Ctx, col: Val, results: List[Optional[str]],
                     rt=T.VARCHAR) -> Val:
    """Per-distinct string-or-None results -> varchar Val (new
    dictionary + null lut)."""
    return _elem_result_val(ctx, col, results, rt)


def _require_const(val: Val, what: str):
    if not val.is_const:
        raise TypeError(f"{what} must be a constant")
    return val.py_value


# ------------------------------------------------------------------ JSON

def _json_canon(v) -> str:
    return json.dumps(v, separators=(",", ":"), ensure_ascii=False)


def _parse_json(s):
    try:
        return json.loads(s)
    except Exception:  # noqa: BLE001 - malformed JSON is a value
        return _JSON_BAD  # (SQL json functions return NULL), not an error


_JSON_BAD = object()


_JSON_PATH_RE = re.compile(
    r"""\.(?P<key>[A-Za-z_][A-Za-z0-9_]*)  # .key
      | \[\s*(?P<index>\d+)\s*\]           # [0]
      | \[\s*"(?P<qkey>[^"]*)"\s*\]        # ["key"]
      | \[\s*'(?P<sqkey>[^']*)'\s*\]       # ['key']
    """,
    re.VERBOSE,
)


def _json_path_steps(path: str):
    """Parse the $.a[0].b JSONPath subset (reference: JsonExtract's
    non-script paths). Returns None for unsupported paths."""
    if not path.startswith("$"):
        return None
    pos, steps = 1, []
    while pos < len(path):
        m = _JSON_PATH_RE.match(path, pos)
        if m is None:
            return None
        if m.group("key") is not None:
            steps.append(m.group("key"))
        elif m.group("index") is not None:
            steps.append(int(m.group("index")))
        else:
            steps.append(m.group("qkey") or m.group("sqkey") or "")
        pos = m.end()
    return steps


def _json_walk(doc, steps):
    cur = doc
    for s in steps:
        if isinstance(s, int):
            if not isinstance(cur, list) or s >= len(cur):
                return _JSON_BAD
            cur = cur[s]
        else:
            if not isinstance(cur, dict) or s not in cur:
                return _JSON_BAD
            cur = cur[s]
    return cur


def _json_extract_impl(scalar_only: bool):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        col = vals[0]
        path = _require_const(vals[1], "json path")
        steps = _json_path_steps(str(path))
        d = _dict_of(col)

        def one(v):
            if steps is None:
                return None
            doc = _parse_json(str(v))
            if doc is _JSON_BAD:
                return None
            out = _json_walk(doc, steps)
            if out is _JSON_BAD:
                return None
            if scalar_only:
                if isinstance(out, (dict, list)):
                    return None
                if out is None:
                    return None
                if isinstance(out, bool):
                    return "true" if out else "false"
                return str(out)
            return _json_canon(out)

        return _varchar_results(ctx, col, [one(v) for v in d.values])

    return impl


def _str_resolve(args):
    if not T.is_string(args[0]):
        raise TypeError(f"expected varchar, got {args[0]}")
    return T.VARCHAR


register("json_extract", _str_resolve, _json_extract_impl(False))
register("json_extract_scalar", _str_resolve, _json_extract_impl(True))


def _impl_json_parse(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    d = _dict_of(col)

    def one(v):
        doc = _parse_json(str(v))
        return None if doc is _JSON_BAD else _json_canon(doc)

    return _varchar_results(ctx, col, [one(v) for v in d.values])


register("json_parse", _str_resolve, _impl_json_parse)
# json_format(json) renders the canonical text — identity over our
# canonicalized-varchar JSON representation
register("json_format", _str_resolve,
         lambda ctx, rt, vals: vals[0])


def _impl_json_array_length(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    d = _dict_of(col)

    def one(v):
        doc = _parse_json(str(v))
        return len(doc) if isinstance(doc, list) else None

    return _elem_result_val(ctx, col, [one(v) for v in d.values],
                            T.BIGINT)


register("json_array_length", lambda a: T.BIGINT,
         _impl_json_array_length)


def _impl_json_size(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    path = _require_const(vals[1], "json path")
    steps = _json_path_steps(str(path))
    d = _dict_of(col)

    def one(v):
        if steps is None:
            return None
        doc = _parse_json(str(v))
        if doc is _JSON_BAD:
            return None
        out = _json_walk(doc, steps)
        if out is _JSON_BAD:
            return None
        return len(out) if isinstance(out, (dict, list)) else 0

    return _elem_result_val(ctx, col, [one(v) for v in d.values],
                            T.BIGINT)


register("json_size", lambda a: T.BIGINT, _impl_json_size)


def _impl_json_array_contains(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    want = _require_const(vals[1], "json_array_contains value")
    d = _dict_of(col)

    def one(v):
        doc = _parse_json(str(v))
        if not isinstance(doc, list):
            return None
        if isinstance(want, bool) or not isinstance(want, (int, float)):
            return any(type(x) is type(want) and x == want for x in doc)
        return any(
            isinstance(x, (int, float)) and not isinstance(x, bool)
            and float(x) == float(want)
            for x in doc
        )

    return _elem_result_val(ctx, col, [one(v) for v in d.values],
                            T.BOOLEAN)


register("json_array_contains", lambda a: T.BOOLEAN,
         _impl_json_array_contains)


# ----------------------------------------------------------- TRY / casts

# TRY(x) == x under the masked-eval policy (module docstring)
register("try", lambda a: a[0], lambda ctx, rt, vals: vals[0],
         propagate_nulls=False)


def _parse_scalar(s: str, to: T.SqlType):
    s = s.strip()
    if T.is_integral(to):
        return int(s)
    if T.is_floating(to):
        return float(s)
    if isinstance(to, T.BooleanType):
        low = s.lower()
        if low in ("true", "t", "1"):
            return True
        if low in ("false", "f", "0"):
            return False
        raise ValueError(s)
    if isinstance(to, T.DecimalType):
        from decimal import Decimal

        q = Decimal(s).scaleb(to.scale)
        return int(q.to_integral_value(rounding="ROUND_HALF_UP"))
    if isinstance(to, T.DateType):
        import datetime

        return (datetime.date.fromisoformat(s)
                - datetime.date(1970, 1, 1)).days
    if isinstance(to, T.TimestampType):
        import datetime

        dt = datetime.datetime.fromisoformat(s)
        epoch = datetime.datetime(1970, 1, 1)
        return int((dt - epoch).total_seconds() * 1_000_000)
    raise TypeError(f"cannot parse varchar as {to}")


def _string_cast_val(ctx: Ctx, col: Val, to: T.SqlType) -> Val:
    if col.dictionary is None and col.is_const:
        # string literal: parse once on the host
        try:
            r = _parse_scalar(str(col.py_value), to)
        except Exception:  # noqa: BLE001 - SQL CAST yields NULL on
            r = None       # unparseable input, not a query failure
        if r is None:
            return Val(
                ctx.xp.zeros((ctx.capacity,),
                             dtype=np.dtype(to.numpy_dtype)),
                ctx.xp.ones((ctx.capacity,), dtype=bool), to,
            )
        return Val(
            # xfercheck: raw-ok - r is a host Python value (CAST fold)
            ctx.xp.asarray(np.asarray(r, np.dtype(to.numpy_dtype))),
            None, to, py_value=r,
        )
    d = _dict_of(col)

    def one(v):
        try:
            return _parse_scalar(str(v), to)
        except Exception:  # noqa: BLE001 - SQL CAST yields NULL on
            return None    # unparseable input, not a query failure

    return _elem_result_val(ctx, col, [one(v) for v in d.values], to)


def _impl_try_cast(ctx: Ctx, rt: T.SqlType, vals: List[Val]) -> Val:
    from presto_tpu.expr.values import cast_data

    v = vals[0]
    if T.is_string(v.type) and not T.is_string(rt):
        return _string_cast_val(ctx, v, rt)
    try:
        data, nulls = cast_data(ctx.xp, v, rt, ctx.capacity)
        return Val(data, nulls, rt, v.dictionary if T.is_string(rt)
                   else None)
    except TypeError:
        return Val(
            ctx.xp.zeros((ctx.capacity,),
                         dtype=np.dtype(rt.numpy_dtype)),
            ctx.xp.ones((ctx.capacity,), dtype=bool),
            rt,
        )


register("try_cast", lambda a: a[0], _impl_try_cast)


def _install_string_source_cast() -> None:
    """Teach plain CAST to parse varchar sources (per distinct value;
    unparsable -> NULL, the masked-eval divergence)."""
    base = lookup("cast")
    base_impl = base.impl

    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        v = vals[0]
        if T.is_string(v.type) and not T.is_string(rt):
            return _string_cast_val(ctx, v, rt)
        return base_impl(ctx, rt, vals)

    register("cast", base.resolve, impl, base.propagate_nulls)


_install_string_source_cast()


# ---------------------------------------------------------------- bitwise

def _bitwise_resolve(args):
    for a in args:
        if not T.is_integral(a):
            raise TypeError(f"bitwise function over {a}")
    return T.BIGINT


def _impl_bitwise(op):
    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        a = vals[0].data.astype(np.int64)
        if op == "not":
            return Val(~a, None, T.BIGINT)
        b = vals[1].data.astype(np.int64)
        if op == "and":
            return Val(a & b, None, T.BIGINT)
        if op == "or":
            return Val(a | b, None, T.BIGINT)
        return Val(a ^ b, None, T.BIGINT)

    return impl


register("bitwise_and", _bitwise_resolve, _impl_bitwise("and"))
register("bitwise_or", _bitwise_resolve, _impl_bitwise("or"))
register("bitwise_xor", _bitwise_resolve, _impl_bitwise("xor"))
register("bitwise_not", _bitwise_resolve, _impl_bitwise("not"))


def _impl_bit_count(ctx: Ctx, rt, vals: List[Val]) -> Val:
    bits = 64
    if len(vals) > 1:
        bits = int(_require_const(vals[1], "bit_count bits"))
    u = vals[0].data.astype(np.int64)
    if bits < 64:
        u = u & np.int64((1 << bits) - 1)
    # SWAR popcount over int64 (no gathers, vector-unit friendly)
    x = u - ((u >> np.int64(1)) & np.int64(0x5555555555555555))
    x = ((x >> np.int64(2)) & np.int64(0x3333333333333333)) + (
        x & np.int64(0x3333333333333333))
    x = (x + (x >> np.int64(4))) & np.int64(0x0F0F0F0F0F0F0F0F)
    c = ctx.xp.zeros_like(u)
    for k in range(8):
        c = c + ((x >> np.int64(8 * k)) & np.int64(0xFF))
    return Val(c, None, T.BIGINT)


register("bit_count", lambda a: T.BIGINT, _impl_bit_count)


# -------------------------------------------------------------------- URL

def _url_part(part: str):
    def one(v):
        try:
            u = urllib.parse.urlsplit(str(v))
        except Exception:  # noqa: BLE001 - url functions yield NULL
            return None    # on malformed input (reference semantics)
        if part == "protocol":
            return u.scheme or None
        if part == "host":
            return u.hostname or None
        if part == "port":
            return u.port
        if part == "path":
            return u.path
        if part == "query":
            return u.query
        if part == "fragment":
            return u.fragment
        raise ValueError(part)

    return one


for _p in ("protocol", "host", "path", "query", "fragment"):
    register(
        f"url_extract_{_p}", _str_resolve,
        (lambda p: lambda ctx, rt, vals: _varchar_results(
            ctx, vals[0],
            [_url_part(p)(v) for v in _dict_of(vals[0]).values]
        ))(_p),
    )
register(
    "url_extract_port", lambda a: T.BIGINT,
    lambda ctx, rt, vals: _elem_result_val(
        ctx, vals[0],
        [_url_part("port")(v) for v in _dict_of(vals[0]).values],
        T.BIGINT,
    ),
)


def _impl_url_extract_parameter(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    name = str(_require_const(vals[1], "parameter name"))

    def one(v):
        try:
            q = urllib.parse.urlsplit(str(v)).query
            params = urllib.parse.parse_qs(q, keep_blank_values=True)
        except Exception:  # noqa: BLE001 - url functions yield NULL
            return None    # on malformed input (reference semantics)
        vs = params.get(name)
        return vs[0] if vs else None

    return _varchar_results(
        ctx, col, [one(v) for v in _dict_of(col).values]
    )


register("url_extract_parameter", _str_resolve,
         _impl_url_extract_parameter)
register(
    "url_encode", _str_resolve,
    lambda ctx, rt, vals: _varchar_results(
        ctx, vals[0],
        [urllib.parse.quote(str(v), safe="") for v in
         _dict_of(vals[0]).values],
    ),
)
register(
    "url_decode", _str_resolve,
    lambda ctx, rt, vals: _varchar_results(
        ctx, vals[0],
        [urllib.parse.unquote(str(v)) for v in
         _dict_of(vals[0]).values],
    ),
)


# ----------------------------------------------------------- array / map

def _array_resolve_same(args):
    if not isinstance(args[0], T.ArrayType):
        raise TypeError(f"expected array, got {args[0]}")
    return args[0]


def _array_map(ctx: Ctx, col: Val, fn, rt) -> Val:
    d = _dict_of(col)
    return _elem_result_val(
        ctx, col, [fn(tuple(v)) for v in d.values], rt
    )


register(
    "array_distinct", _array_resolve_same,
    lambda ctx, rt, vals: _array_map(
        ctx, vals[0], lambda v: tuple(dict.fromkeys(v)), rt
    ),
)


def _sort_key(x):
    return (x is None, x)


register(
    "array_sort", _array_resolve_same,
    lambda ctx, rt, vals: _array_map(
        ctx, vals[0],
        lambda v: tuple(sorted(v, key=_sort_key)), rt
    ),
)


def _impl_array_join(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    delim = str(_require_const(vals[1], "array_join delimiter"))
    null_rep = None
    if len(vals) > 2:
        null_rep = str(_require_const(vals[2], "null replacement"))

    def one(v):
        parts = []
        for x in v:
            if x is None:
                if null_rep is None:
                    continue
                parts.append(null_rep)
            elif isinstance(x, bool):
                parts.append("true" if x else "false")
            else:
                parts.append(str(x))
        return delim.join(parts)

    return _varchar_results(
        ctx, col, [one(tuple(v)) for v in _dict_of(col).values]
    )


register("array_join", lambda a: T.VARCHAR, _impl_array_join)


def _impl_array_position(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    want = _require_const(vals[1], "array_position value")

    def one(v):
        for i, x in enumerate(v):
            if x == want:
                return i + 1
        return 0

    return _elem_result_val(
        ctx, col, [one(tuple(v)) for v in _dict_of(col).values],
        T.BIGINT,
    )


register("array_position", lambda a: T.BIGINT, _impl_array_position)
def _impl_array_remove(ctx: Ctx, rt, vals: List[Val]) -> Val:
    want = _require_const(vals[1], "array_remove value")
    return _array_map(
        ctx, vals[0],
        lambda v: tuple(x for x in v if x != want), rt,
    )


register("array_remove", _array_resolve_same, _impl_array_remove)


def _impl_slice(ctx: Ctx, rt, vals: List[Val]) -> Val:
    start = int(_require_const(vals[1], "slice start"))
    length = int(_require_const(vals[2], "slice length"))

    def one(v):
        if start > 0:
            i = start - 1
        elif start < 0:
            i = max(len(v) + start, 0)
        else:
            return None  # slice(x, 0, n) is an error in the reference
        return tuple(v[i:i + max(length, 0)])

    return _array_map(ctx, vals[0], lambda v: one(v), rt)


register("slice", _array_resolve_same, _impl_slice)


def _impl_flatten(ctx: Ctx, rt, vals: List[Val]) -> Val:
    def one(v):
        out = []
        for x in v:
            if x is not None:
                out.extend(x)
        return tuple(out)

    return _array_map(ctx, vals[0], one, rt)


def _flatten_resolve(args):
    t = args[0]
    if not (isinstance(t, T.ArrayType)
            and isinstance(t.element, T.ArrayType)):
        raise TypeError(f"flatten over {t}")
    return t.element


register("flatten", _flatten_resolve, _impl_flatten)


def _impl_sequence(ctx: Ctx, rt, vals: List[Val]) -> Val:
    a = int(_require_const(vals[0], "sequence start"))
    b = int(_require_const(vals[1], "sequence stop"))
    step = (int(_require_const(vals[2], "sequence step"))
            if len(vals) > 2 else (1 if b >= a else -1))
    if step == 0:
        raise ValueError("sequence step cannot be zero")
    val = tuple(range(a, b + (1 if step > 0 else -1), step))
    return Val(
        ctx.xp.zeros((ctx.capacity,), dtype=np.int32), None,
        T.ArrayType(T.BIGINT), Dictionary([val]), py_value=val,
    )


register("sequence", lambda a: T.ArrayType(T.BIGINT), _impl_sequence)


def _impl_repeat(ctx: Ctx, rt, vals: List[Val]) -> Val:
    n = int(_require_const(vals[1], "repeat count"))
    el = vals[0]
    if not el.is_const:
        raise TypeError("repeat element must be a constant")
    val = tuple([el.py_value] * max(n, 0))
    return Val(
        ctx.xp.zeros((ctx.capacity,), dtype=np.int32), None,
        T.ArrayType(el.type), Dictionary([val]), py_value=val,
    )


register("repeat", lambda a: T.ArrayType(a[0]), _impl_repeat)


def _install_reverse_for_arrays() -> None:
    base = lookup("reverse")
    base_impl = base.impl

    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        if isinstance(vals[0].type, T.ArrayType):
            return _array_map(
                ctx, vals[0], lambda v: tuple(reversed(v)),
                vals[0].type,
            )
        return base_impl(ctx, rt, vals)

    def resolve(args):
        if isinstance(args[0], T.ArrayType):
            return args[0]
        return base.resolve(args)

    register("reverse", resolve, impl, base.propagate_nulls)


_install_reverse_for_arrays()


def _impl_split(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    delim = str(_require_const(vals[1], "split delimiter"))
    limit = (int(_require_const(vals[2], "split limit"))
             if len(vals) > 2 else None)

    def one(v):
        s = str(v)
        parts = (s.split(delim, limit - 1)
                 if limit is not None else s.split(delim))
        return tuple(parts)

    return _elem_result_val(
        ctx, col, [one(v) for v in _dict_of(col).values],
        T.ArrayType(T.VARCHAR),
    )


register("split", lambda a: T.ArrayType(T.VARCHAR), _impl_split)


def _map_resolve(args):
    if not isinstance(args[0], T.MapType):
        raise TypeError(f"expected map, got {args[0]}")
    return args[0]


def _impl_map_entries(ctx: Ctx, rt, vals: List[Val]) -> Val:
    t = vals[0].type
    return _elem_result_val(
        ctx, vals[0],
        [tuple(tuple(kv) for kv in v)
         for v in _dict_of(vals[0]).values],
        T.ArrayType(T.RowType((t.key, t.value))),
    )


register(
    "map_entries",
    lambda a: T.ArrayType(T.RowType((a[0].key, a[0].value)))
    if isinstance(a[0], T.MapType) else T.UNKNOWN,
    _impl_map_entries,
)


def _impl_typeof(ctx: Ctx, rt, vals: List[Val]) -> Val:
    name = str(vals[0].type)
    return Val(
        ctx.xp.zeros((ctx.capacity,), dtype=np.int32), None,
        T.VARCHAR, Dictionary([name]), py_value=name,
    )


register("typeof", lambda a: T.VARCHAR, _impl_typeof,
         propagate_nulls=False)


# ------------------------------------------------------------------ misc

def _impl_chr(ctx: Ctx, rt, vals: List[Val]) -> Val:
    v = vals[0]
    n = _require_const(v, "chr codepoint")
    s = chr(int(n))
    return Val(
        ctx.xp.zeros((ctx.capacity,), dtype=np.int32), None,
        T.VARCHAR, Dictionary([s]), py_value=s,
    )


register("chr", lambda a: T.VARCHAR, _impl_chr)


def _impl_last_day_of_month(ctx: Ctx, rt, vals: List[Val]) -> Val:
    from presto_tpu.expr.values import (
        civil_from_days,
        days_from_civil,
        days_in_month,
    )

    xp = ctx.xp
    v = vals[0]
    days = v.data
    if isinstance(v.type, T.TimestampType):
        days = (days // np.int64(86_400_000_000)).astype(np.int32)
    y, m, _d = civil_from_days(xp, days)
    last = days_in_month(xp, y, m)
    return Val(
        days_from_civil(xp, y, m, last).astype(np.int32), None, T.DATE
    )


register(
    "last_day_of_month",
    lambda a: T.DATE,
    _impl_last_day_of_month,
)


def _impl_date_parse(ctx: Ctx, rt, vals: List[Val]) -> Val:
    """date_parse(varchar, mysql-format) -> timestamp (reference:
    MySQL-compatible DateTimeFunctions.dateParse). Supported
    specifiers: %Y %y %m %c %d %e %H %k %i %s %f."""
    import datetime

    col = vals[0]
    fmt = str(_require_const(vals[1], "date_parse format"))
    pyfmt = (fmt.replace("%c", "%m").replace("%e", "%d")
             .replace("%k", "%H").replace("%i", "%M")
             .replace("%s", "%S").replace("%f", "%f"))

    def one(v):
        try:
            dt = datetime.datetime.strptime(str(v), pyfmt)
        except Exception:  # noqa: BLE001 - unparseable datetime text
            return None    # yields NULL (reference semantics)
        epoch = datetime.datetime(1970, 1, 1)
        return int((dt - epoch).total_seconds() * 1_000_000)

    return _elem_result_val(
        ctx, col, [one(v) for v in _dict_of(col).values],
        T.TIMESTAMP,
    )


register("date_parse", lambda a: T.TIMESTAMP, _impl_date_parse)


def _impl_to_hex_from(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]
    d = _dict_of(col)
    return _varchar_results(
        ctx, col,
        [str(v).encode("utf-8").hex().upper() for v in d.values],
    )


register("to_hex", _str_resolve, _impl_to_hex_from)


def _impl_from_hex(ctx: Ctx, rt, vals: List[Val]) -> Val:
    col = vals[0]

    def one(v):
        try:
            return bytes.fromhex(str(v)).decode("utf-8")
        except Exception:  # noqa: BLE001 - undecodable input yields
            return None    # NULL (reference semantics)

    return _varchar_results(
        ctx, col, [one(v) for v in _dict_of(col).values]
    )


register("from_hex", _str_resolve, _impl_from_hex)


def _impl_hash_fn(algo):
    import hashlib

    def impl(ctx: Ctx, rt, vals: List[Val]) -> Val:
        col = vals[0]
        return _varchar_results(
            ctx, col,
            [hashlib.new(algo, str(v).encode("utf-8")).hexdigest()
             for v in _dict_of(col).values],
        )

    return impl


# hex-digest flavors of the reference's varbinary md5/sha256 (varbinary
# payloads stay host-side in this engine — see types.py docstring)
register("md5", _str_resolve, _impl_hash_fn("md5"))
register("sha256", _str_resolve, _impl_hash_fn("sha256"))
register("sha1", _str_resolve, _impl_hash_fn("sha1"))


def _impl_to_base64(ctx: Ctx, rt, vals: List[Val]) -> Val:
    import base64

    return _varchar_results(
        ctx, vals[0],
        [base64.b64encode(str(v).encode("utf-8")).decode("ascii")
         for v in _dict_of(vals[0]).values],
    )


def _impl_from_base64(ctx: Ctx, rt, vals: List[Val]) -> Val:
    import base64

    def one(v):
        try:
            return base64.b64decode(str(v)).decode("utf-8")
        except Exception:  # noqa: BLE001 - undecodable input yields
            return None    # NULL (reference semantics)

    return _varchar_results(
        ctx, vals[0], [one(v) for v in _dict_of(vals[0]).values]
    )


register("to_base64", _str_resolve, _impl_to_base64)
register("from_base64", _str_resolve, _impl_from_base64)


def _impl_normalize(ctx: Ctx, rt, vals: List[Val]) -> Val:
    import unicodedata

    form = "NFC"
    if len(vals) > 1:
        form = str(_require_const(vals[1], "normalize form")).upper()

    return _varchar_results(
        ctx, vals[0],
        [unicodedata.normalize(form, str(v))
         for v in _dict_of(vals[0]).values],
    )


register("normalize", _str_resolve, _impl_normalize)


def _impl_starts_with(ctx: Ctx, rt, vals: List[Val]) -> Val:
    prefix = str(_require_const(vals[1], "starts_with prefix"))
    return _elem_result_val(
        ctx, vals[0],
        [str(v).startswith(prefix)
         for v in _dict_of(vals[0]).values],
        T.BOOLEAN,
    )


register("starts_with", lambda a: T.BOOLEAN, _impl_starts_with)


# ------------------------------------------------- higher-order (lambdas)

def _infer_elem_type(vals_, declared):
    if declared is not None and not isinstance(declared, T.UnknownType):
        return declared
    for v in vals_:
        if v is None:
            continue
        if isinstance(v, bool):
            return T.BOOLEAN
        if isinstance(v, int):
            return T.BIGINT
        if isinstance(v, float):
            return T.DOUBLE
        if isinstance(v, str):
            return T.VARCHAR
        if isinstance(v, (list, tuple)):
            return T.ArrayType(T.UNKNOWN)
    return T.BIGINT


def _host_block(vals_, t):
    from presto_tpu.page import Block

    n = len(vals_)
    isnull = np.array([v is None for v in vals_], bool)
    has_null = bool(isnull.any())
    if t.is_dictionary_encoded:
        uniq: dict = {}
        codes = np.zeros(n, np.int32)
        for i, v in enumerate(vals_):
            if v is None:
                continue
            codes[i] = uniq.setdefault(v, len(uniq))
        return Block(
            data=codes, type=t,
            nulls=isnull if has_null else None,
            dictionary=Dictionary(list(uniq)),
        )
    data = np.zeros(n, np.dtype(t.numpy_dtype))
    for i, v in enumerate(vals_):
        if v is not None:
            data[i] = v
    return Block(data=data, type=t,
                 nulls=isnull if has_null else None)


def _val_to_pylist(val: Val, n: int) -> list:
    data = val.data
    if isinstance(data, tuple):
        raise TypeError("lambda bodies over long decimals unsupported")
    arr = XF.np_host(data, label="lambda-eval")
    if arr.ndim == 0:
        arr = np.broadcast_to(arr, (n,))
    nulls = (XF.np_host(val.nulls, label="lambda-eval")
             if val.nulls is not None else np.zeros(n, bool))
    if nulls.ndim == 0:
        nulls = np.broadcast_to(nulls, (n,))
    scale = (val.type.scale
             if isinstance(val.type, T.DecimalType) else None)
    out = []
    for i in range(n):
        if nulls[i]:
            out.append(None)
        elif val.dictionary is not None:
            out.append(
                val.dictionary.values[
                    int(np.clip(arr[i], 0, len(val.dictionary) - 1))
                ]
            )
        else:
            v = arr[i]
            # xfercheck: raw-ok - numpy scalar unboxing; arr is host
            v = v.item() if hasattr(v, "item") else v
            if scale is not None:
                # unscaled decimal -> exact Decimal value
                from decimal import Decimal

                v = Decimal(v).scaleb(-scale)
            out.append(v)
    return out


def _run_lambda(lam, columns, param_types) -> list:
    """Evaluate a lambda body over parallel element columns on the host
    (numpy xp) — the per-distinct-value translation of the reference's
    per-row lambda invocation. Returns body results (None = NULL)."""
    from presto_tpu.expr.eval import evaluate
    from presto_tpu.page import Page

    n = len(columns[0]) if columns else 0
    if n == 0:
        return []
    blocks = tuple(
        _host_block(c, _infer_elem_type(c, t))
        for c, t in zip(columns, param_types)
    )
    page = Page(blocks=blocks, valid=np.ones(n, bool))
    return _val_to_pylist(evaluate(lam.body, page, np), n)


def _lam_of(vals: List, i: int):
    from presto_tpu.expr import ir

    if not isinstance(vals[i], ir.Lambda):
        raise TypeError("expected a lambda argument")
    return vals[i]


def _impl_transform(ctx: Ctx, rt, vals: List) -> Val:
    col, lam = vals[0], _lam_of(vals, 1)
    elem_t = (col.type.element if isinstance(col.type, T.ArrayType)
              else T.UNKNOWN)
    outs = [
        tuple(_run_lambda(lam, [list(v)], [elem_t]))
        for v in _dict_of(col).values
    ]
    return _elem_result_val(ctx, col, outs, rt)


def _impl_filter(ctx: Ctx, rt, vals: List) -> Val:
    col, lam = vals[0], _lam_of(vals, 1)
    elem_t = (col.type.element if isinstance(col.type, T.ArrayType)
              else T.UNKNOWN)
    outs = []
    for v in _dict_of(col).values:
        v = tuple(v)
        keep = _run_lambda(lam, [list(v)], [elem_t])
        outs.append(tuple(x for x, k in zip(v, keep) if k is True
                          or k == 1 and k is not None))
    return _elem_result_val(ctx, col, outs, rt)


def _match_impl(mode: str):
    def impl(ctx: Ctx, rt, vals: List) -> Val:
        col, lam = vals[0], _lam_of(vals, 1)
        elem_t = (col.type.element
                  if isinstance(col.type, T.ArrayType) else T.UNKNOWN)
        outs = []
        for v in _dict_of(col).values:
            res = _run_lambda(lam, [list(v)], [elem_t])
            trues = sum(1 for r in res if r)
            has_null = any(r is None for r in res)
            if mode == "any":
                out = True if trues else (None if has_null else False)
            elif mode == "all":
                out = (False if any(r is False or r == 0 and r is not None
                                    for r in res)
                       else (None if has_null else True))
            else:  # none
                out = False if trues else (None if has_null else True)
            outs.append(out)
        return _elem_result_val(ctx, col, outs, T.BOOLEAN)

    return impl


def _hof_array_resolve_elem(args):
    if not isinstance(args[0], T.ArrayType):
        raise TypeError(f"expected array, got {args[0]}")
    return T.ArrayType(args[1])


register("transform", _hof_array_resolve_elem, _impl_transform)
register("filter", lambda a: a[0], _impl_filter)
register("any_match", lambda a: T.BOOLEAN, _match_impl("any"))
register("all_match", lambda a: T.BOOLEAN, _match_impl("all"))
register("none_match", lambda a: T.BOOLEAN, _match_impl("none"))


def _map_kv_columns(v):
    ks = [kv[0] for kv in v]
    vs_ = [kv[1] for kv in v]
    return ks, vs_


def _impl_transform_values(ctx: Ctx, rt, vals: List) -> Val:
    col, lam = vals[0], _lam_of(vals, 1)
    t = col.type
    outs = []
    for v in _dict_of(col).values:
        v = tuple(tuple(kv) for kv in v)
        ks, vs_ = _map_kv_columns(v)
        if lam.n_params == 1:
            res = _run_lambda(lam, [vs_], [t.value])
        else:
            res = _run_lambda(lam, [ks, vs_], [t.key, t.value])
        outs.append(tuple(zip(ks, res)))
    return _elem_result_val(ctx, col, outs, rt)


def _impl_transform_keys(ctx: Ctx, rt, vals: List) -> Val:
    col, lam = vals[0], _lam_of(vals, 1)
    t = col.type
    outs = []
    for v in _dict_of(col).values:
        v = tuple(tuple(kv) for kv in v)
        ks, vs_ = _map_kv_columns(v)
        if lam.n_params == 1:
            res = _run_lambda(lam, [ks], [t.key])
        else:
            res = _run_lambda(lam, [ks, vs_], [t.key, t.value])
        outs.append(tuple(zip(res, vs_)))
    return _elem_result_val(ctx, col, outs, rt)


def _impl_map_filter(ctx: Ctx, rt, vals: List) -> Val:
    col, lam = vals[0], _lam_of(vals, 1)
    t = col.type
    outs = []
    for v in _dict_of(col).values:
        v = tuple(tuple(kv) for kv in v)
        ks, vs_ = _map_kv_columns(v)
        keep = _run_lambda(lam, [ks, vs_], [t.key, t.value])
        outs.append(tuple(kv for kv, k in zip(v, keep) if k))
    return _elem_result_val(ctx, col, outs, rt)


def _map_hof_resolve(kind):
    def resolve(args):
        t = args[0]
        if not isinstance(t, T.MapType):
            raise TypeError(f"expected map, got {t}")
        if kind == "values":
            return T.MapType(t.key, args[1])
        if kind == "keys":
            return T.MapType(args[1], t.value)
        return t

    return resolve


register("transform_values", _map_hof_resolve("values"),
         _impl_transform_values)
register("transform_keys", _map_hof_resolve("keys"),
         _impl_transform_keys)
register("map_filter", _map_hof_resolve("filter"), _impl_map_filter)


def _impl_reduce(ctx: Ctx, rt, vals: List) -> Val:
    """reduce(array, init, (acc, x) -> acc', acc -> out): host fold per
    distinct value (init must be a constant)."""
    from presto_tpu.expr import ir

    col = vals[0]
    init = _require_const(vals[1], "reduce initial state")
    combine = _lam_of(vals, 2)
    output = vals[3] if len(vals) > 3 else None
    elem_t = (col.type.element if isinstance(col.type, T.ArrayType)
              else T.UNKNOWN)
    outs = []
    for v in _dict_of(col).values:
        acc = init
        for x in tuple(v):
            r = _run_lambda(combine, [[acc], [x]], [None, elem_t])
            acc = r[0] if r else None
        if output is not None and isinstance(output, ir.Lambda):
            r = _run_lambda(output, [[acc]], [None])
            acc = r[0] if r else None
        outs.append(acc)
    res_t = _infer_elem_type(outs, None)
    return _elem_result_val(ctx, col, outs, res_t)


register("reduce", lambda a: a[-1] if a else T.UNKNOWN, _impl_reduce)
