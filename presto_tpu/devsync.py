"""Forced device synchronization for honest timing on the axon runtime.

Round-4 discovery (see bench.py docstring): on axon,
``jax.block_until_ready`` returns at dispatch — it does NOT wait for
device completion, and queued work drains only when a device->host read
forces it. Every timing path in the tree (bench group children, the
executor's EXPLAIN ANALYZE stats_drain mode, tools/microbench.py) must
use THIS helper so a future protocol correction lands in one place.
"""

from __future__ import annotations


def drain(tree) -> None:
    """Force REAL completion of all device work queued before ``tree``
    was produced: reads one element of the last leaf; FIFO execution
    order means everything queued earlier has truly finished. Costs
    ~0.1s on an empty queue; dispatch+drain cycles are repeatable."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(tree)
    if leaves and hasattr(leaves[-1], "ravel") and leaves[-1].size:
        np.asarray(leaves[-1].ravel()[:1])
