"""Streaming subsystem (ISSUE 14): incremental view maintenance over
append-log connectors. See streaming/ivm.py for the refresh engine and
connectors/stream.py for the log itself; the tailing /v1/statement
cursors live in server/http_server.py."""

from presto_tpu.streaming.ivm import (  # noqa: F401
    IvmRegistry,
    MaterializedView,
    ivm_unsafe_reason,
    refresh,
    shared_registry,
    shared_registry_if_exists,
    view_shape_fingerprint,
    windowed_executor,
)
