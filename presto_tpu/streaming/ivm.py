"""Incremental view maintenance (IVM) over append-log streams.

Reference: the materialized-view refresh direction of the original —
a registered aggregate over changing data is maintained, not
recomputed. The TPU translation exploits a structural fact PR 10's
cache model could not: the engine's partial-aggregation machinery
(`_partial_agg_page` / `_merge_partials_page` / `_final_agg_page`,
exec/executor.py) is ALREADY a delta-fold — a settled partial-state
page plus the partial states of new rows merges to exactly the state
of the whole input. So for an IVM-SAFE view over an append-only
stream, a refresh:

  1. scans ONLY the delta rows ``[watermark, head)`` through a pinned
     StreamWindowConnector and folds them through the partial-step
     aggregation (Executor.ivm_delta_states — the same fused
     scan→filter→project→partial-agg kernels, the same overflow
     ladder, the same canonical jit-cache entries as a cold run);
  2. merges the delta states into the persisted settled state and
     finalizes (Executor.ivm_fold_finalize — the agg_merge/agg_final
     kernels the single-step path compiles);
  3. replays the plan's post-aggregation chain (ORDER BY / projection
     / LIMIT) over the finalized page via a RemoteSource supplier.

Cost: O(new rows) + O(group cardinality) per refresh instead of a
full recompute — ROOFLINE §12's model. "Advance on write": the view's
result-cache entry carries its offset WATERMARK and is replaced in
place by a refresh; the store's append-path reclaim keeps watermarked
entries alive (cache/store.advance_tables).

IVM-SAFE (decided statically, cache/rules.py-style, at registration):
one single-step GROUPED aggregation whose functions all have
mergeable partial states (collect-state aggregates are excluded —
array_agg order is not append-decomposable), a deterministic
Filter/Project chain between scan and aggregation, exactly one scan,
of an append-only connector. Everything else still registers but
refreshes by FULL recompute, loudly counted on ivm_full_recomputes —
degraded, never silently wrong.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu.cache.rules import uncacheable_reason
from presto_tpu.exec import agg_states as S
from presto_tpu.exec import plan as P
from presto_tpu.obs.profile import structural_fingerprint
from presto_tpu.obs.sanitizer import (
    make_condition,
    make_lock,
    register_owner,
)

# plan shapes allowed ABOVE the aggregation (replayed over the
# finalized page per refresh — O(groups), all deterministic) and
# BELOW it (folded into the delta partial program)
_ABOVE_OK = (P.Output, P.Sort, P.TopN, P.Limit, P.Project, P.Filter)
_BELOW_OK = (P.Filter, P.Project, P.Exchange)


def _aggregations(node: P.PhysicalNode) -> List[P.Aggregation]:
    out: List[P.Aggregation] = []

    def walk(n):
        if isinstance(n, P.Aggregation):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(node)
    return out


def ivm_unsafe_reason(plan: P.PhysicalNode, catalogs) -> Optional[str]:
    """None when ``plan`` can refresh incrementally; otherwise a short
    human-readable reason (surfaced by the registry and tests, never
    raised — unsafe views fall back to counted full recomputes)."""
    r = uncacheable_reason(plan, catalogs)
    if r is not None:
        return r  # non-deterministic / snapshot-less: not even safely
        # recomputable into a watermarked entry without this gate
    aggs = _aggregations(plan)
    if len(aggs) != 1:
        return (f"{len(aggs)} aggregations (IVM maintains exactly one "
                f"fold point)")
    agg = aggs[0]
    if agg.step != "single":
        return f"aggregation step {agg.step!r} (already fragmented)"
    if not agg.group_channels:
        return ("global aggregation (no group keys — the merge kernel "
                "plane is grouped; falls back to full recompute)")
    for spec in agg.aggregates:
        if spec.function in S.COLLECT_FNS:
            return (f"collect-state aggregate {spec.function}() "
                    f"(element order is not append-decomposable)")
    # the chain ABOVE the aggregation must reach it through
    # single-source deterministic operators only
    node = plan
    while node is not agg:
        if not isinstance(node, _ABOVE_OK):
            return (f"{type(node).__name__} above the aggregation "
                    f"(only sort/project/filter/limit replay over the "
                    f"finalized state)")
        node = node.source
    # the chain BELOW must be a pure per-row pipeline over ONE scan
    cur = agg.source
    while isinstance(cur, _BELOW_OK):
        cur = cur.source
    if not isinstance(cur, P.TableScan):
        return (f"{type(cur).__name__} between aggregation and scan "
                f"(delta rows must fold through a per-row pipeline)")
    conn = catalogs.get(cur.catalog)
    if not getattr(conn, "append_only", False):
        return (f"{cur.catalog}.{cur.table} is not an append-only "
                f"stream (writes may rewrite history)")
    if not hasattr(conn, "offset"):
        return f"{cur.catalog} connector exposes no offset"
    return None


def _normalized(node):
    """Plan copy with planner capacity estimates masked: capacities
    derive from connector row counts, so a growing log would move a
    view's structural identity between registration and later
    statements of the same SQL. Shape matching must be offset-free."""
    if not isinstance(node, P.PhysicalNode):
        return node
    changes = {}
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, P.PhysicalNode):
            nv = _normalized(v)
        elif isinstance(v, tuple) and v and any(
                isinstance(x, P.PhysicalNode) for x in v):
            nv = tuple(_normalized(x) for x in v)
        else:
            nv = v
        if nv is not v:
            changes[f.name] = nv
    if isinstance(node, P.Aggregation):
        changes["capacity"] = 0
    return dataclasses.replace(node, **changes) if changes else node


def view_shape_fingerprint(plan: P.PhysicalNode) -> str:
    """Offset- and capacity-independent structural identity of a
    statement's plan — how tailing cursors recognize "this statement
    IS registered view X" across re-plans of a growing log."""
    return structural_fingerprint(_normalized(plan))


def _replace_node(root, target, repl):
    """Structural rewrite: ``root`` with the node ``target`` (by
    identity) replaced by ``repl``."""
    if root is target:
        return repl
    if not isinstance(root, P.PhysicalNode):
        return root
    changes = {}
    for f in dataclasses.fields(root):
        v = getattr(root, f.name)
        if isinstance(v, P.PhysicalNode):
            nv = _replace_node(v, target, repl)
        elif isinstance(v, tuple) and v and any(
                isinstance(x, P.PhysicalNode) for x in v):
            nv = tuple(_replace_node(x, target, repl) for x in v)
        else:
            nv = v
        if nv is not v:
            changes[f.name] = nv
    return dataclasses.replace(root, **changes) if changes else root


def windowed_executor(catalogs, catalog: str, table: str, like=None):
    """(executor, window) pair whose scans of ``catalog.table`` read
    through a mutable pinned offset window (connectors/stream.
    StreamWindowConnector) — the refresh/tail execution engine. The
    jit cache is shared with ``like`` so refresh kernels and cold-run
    kernels are the same canonical compiled entries."""
    from presto_tpu.connectors.stream import StreamWindowConnector
    from presto_tpu.exec.executor import Executor

    window = StreamWindowConnector(catalogs[catalog], table)
    cats = dict(catalogs)
    cats[catalog] = window
    ex = Executor(cats, page_rows=like.page_rows if like is not None
                  else 1 << 18)
    if like is not None:
        ex._jit_cache = like._jit_cache
        ex.use_jit = like.use_jit
        ex.collect_k = like.collect_k
        ex.agg_optimistic_rows = like.agg_optimistic_rows
        ex.max_memory_bytes = like.max_memory_bytes
    return ex, window


class MaterializedView:
    """One registered materialized aggregate over a stream scan.

    State (all mutated under ``_cv``; the refresh itself runs
    UNLOCKED, serialized by the ``_refreshing`` flag so concurrent
    tailers coalesce onto one fold instead of racing the window):

      ``state_pages``  the settled partial-state page(s), HOST
                       pytrees — the persisted agg state a refresh
                       folds delta states into;
      ``watermark``    the log offset the state covers;
      ``last_*``       the last finalized result (names/rows/types).
    """

    # lock discipline (tools/lint `locks` rule): refresh publication
    # vs concurrent tailing readers
    _shared_attrs = ("state_pages", "state_offset", "watermark",
                     "last_names", "last_rows", "last_types",
                     "last_delta_rows", "refreshes",
                     "full_recomputes", "_refreshing")

    def __init__(self, name: str, sql: str, plan, catalogs, runner):
        self.name = name
        self.sql = sql
        self.plan = plan
        self.names = list(getattr(plan, "names", ()) or ())
        reason = ivm_unsafe_reason(plan, catalogs)
        self.ivm_safe = reason is None
        self.unsafe_reason = reason
        self.shape_fp = view_shape_fingerprint(plan)
        self.final_key = f"ivm:{name}"
        self.cache_key = f"ivm:{name}"
        # the stream scan (unsafe views may scan anything — fall back
        # to the first scanned table for watermark bookkeeping)
        from presto_tpu.cache.rules import scan_tables

        streams = [(c, t) for c, t in sorted(scan_tables(plan))
                   if getattr(catalogs.get(c), "append_only", False)]
        if not streams:
            raise ValueError(
                f"view {name!r} scans no append-only stream table")
        self.catalog, self.table = streams[0]
        self.source_conn = catalogs[self.catalog]
        self.executor, self.window = windowed_executor(
            catalogs, self.catalog, self.table,
            like=runner.executor if runner is not None else None,
        )
        self.result_cache = (
            getattr(runner.executor, "result_cache", None)
            if runner is not None else None
        )
        self.agg = None
        self.partial = None
        self.above_plan = None
        self.scan = None
        if self.ivm_safe:
            self.agg = _aggregations(plan)[0]
            self.partial = dataclasses.replace(self.agg, step="partial")
            cur = self.agg.source
            while isinstance(cur, _BELOW_OK):
                cur = cur.source
            self.scan = cur
            final_types = tuple(self.executor.output_types(self.agg))
            self.above_plan = _replace_node(
                plan, self.agg,
                P.RemoteSource(types=final_types, key=self.final_key),
            )
        # mutable refresh state. watermark = the offset the LAST
        # RESULT covers (drives tail pollers and the settled early
        # return); state_offset = the offset the persisted PARTIAL
        # STATE covers (a full recompute produces no state, so the two
        # diverge until the next incremental fold re-folds from 0)
        self.state_pages: List = []
        self.state_offset = 0
        self.watermark = 0
        self.last_names: Optional[List[str]] = None
        self.last_rows: List[tuple] = []
        self.last_types: List[str] = []
        self.last_delta_rows = 0
        self.refreshes = 0
        self.full_recomputes = 0
        self._refreshing = False
        self._cv = make_condition(
            "streaming.ivm.MaterializedView._cv")
        register_owner(self, lock_attrs=("_cv",))

    def settled_offset(self) -> int:
        with self._cv:
            return self.watermark

    def snapshot_result(self):
        with self._cv:
            if self.last_names is None:
                return None
            return (list(self.last_names), list(self.last_rows),
                    list(self.last_types))


def refresh(view: MaterializedView, session=None, sink=None):
    """Refresh ``view`` to the log's current offset and return
    ``(names, rows, types)``.

    IVM-safe views fold ONLY the pages appended since the watermark
    into the persisted settled state (O(new rows) + O(groups)); a
    disabled (``ivm_enabled=false`` session property) or statically
    unsafe view recomputes in full over the pinned ``[0, head)``
    window — counted on ``ivm_full_recomputes``, never silently
    wrong. ``sink`` (an Executor) receives the registry counters
    (``ivm_refreshes`` / ``ivm_full_recomputes`` /
    ``delta_pages_folded``) so EXPLAIN ANALYZE, /metrics, and
    system.metrics surface refresh activity."""
    use_ivm = view.ivm_safe and (
        session is None or bool(session.get("ivm_enabled"))
    )
    hi = view.source_conn.offset(view.table)
    with view._cv:
        while view._refreshing:
            view._cv.wait(0.05)
        if (use_ivm and view.last_names is not None
                and view.watermark >= hi):
            # settled: a concurrent tailer already folded this offset
            return (list(view.last_names), list(view.last_rows),
                    list(view.last_types))
        view._refreshing = True
        # re-read the head AFTER winning the flag: a refresher that
        # waited here must fold to at least the offset the winner
        # published, or a slow loser could re-publish an OLDER
        # snapshot (and regress the watermark) over a newer one
        hi = max(hi, view.source_conn.offset(view.table),
                 view.watermark)
        if (use_ivm and view.last_names is not None
                and view.watermark >= hi):
            # the winner we waited on already covered this offset
            view._refreshing = False
            view._cv.notify_all()
            return (list(view.last_names), list(view.last_rows),
                    list(view.last_types))
        lo = view.state_offset
        state = list(view.state_pages)
    try:
        ex = view.executor
        if not use_ivm:
            view.window.set_range(0, hi)
            names, rows = ex.execute(view.plan)
            types = [str(t) for t in ex.output_types(view.plan)]
            new_state: List = []  # full state lives in the result only
            scanned = hi
            if sink is not None:
                sink.count_ivm_refresh(full=True)
            full = True
        else:
            delta_states: List = []
            scanned = 0
            if hi > lo:
                view.window.set_range(lo, hi)
                own_stats = ex._collect_stats is None
                if own_stats:
                    ex._collect_stats = {}
                try:
                    delta_states = ex.ivm_delta_states(view.partial)
                    st = ex._collect_stats.get(id(view.scan))
                    scanned = st.rows if st is not None else hi - lo
                finally:
                    if own_stats:
                        ex._collect_stats = None
                if sink is not None:
                    sink.count_delta_pages(len(delta_states))
            state = state + delta_states
            if not state:
                names = list(view.names)
                rows = []
                types = [str(t) for t in ex.output_types(view.plan)]
                new_state = []
            else:
                # the observed group cardinality (valid rows of the
                # persisted settled state — host numpy, free to read)
                # sizes the fold: the planner estimate tracks the
                # whole LOG's row count, and an O(log)-slot state page
                # would make every re-merge pay for history; genuinely
                # new groups overflow onto the boost ladder
                prior = state[:len(state) - len(delta_states)] \
                    if delta_states else state
                hint = (sum(int(p.valid.sum()) for p in prior)
                        or None) if prior else None
                settled, final_page = ex.ivm_fold_finalize(
                    view.partial, state, cap_hint=hint)
                new_state = [settled]
                ex.remote_sources[view.final_key] = (
                    lambda: iter([final_page]))
                try:
                    names, rows = ex.execute(view.above_plan)
                finally:
                    ex.remote_sources.pop(view.final_key, None)
                types = [str(t)
                         for t in ex.output_types(view.above_plan)]
            if sink is not None:
                sink.count_ivm_refresh(full=False)
            full = False
        cache = view.result_cache
        if cache is not None:
            # ADVANCE the view's cache entry in place — the offset
            # watermark rides on the entry, so the append path's
            # reclaim (store.advance_tables) keeps it alive
            cache.put_rows(
                view.cache_key, list(names or []), rows, types,
                {(view.catalog, view.table)}, watermark=hi,
            )
        with view._cv:
            view.state_pages = new_state
            # a full recompute leaves no partial state: the next
            # incremental fold must re-fold from offset 0
            view.state_offset = hi if (not full and new_state) else 0
            view.watermark = hi
            view.last_names = list(names or [])
            view.last_rows = rows
            view.last_types = types
            view.last_delta_rows = int(scanned)
            view.refreshes += 1
            if full:
                view.full_recomputes += 1
    finally:
        with view._cv:
            view._refreshing = False
            view._cv.notify_all()
    return list(names or []), rows, types


class IvmRegistry:
    """Registered materialized views, keyed by name AND by structural
    shape fingerprint (the tailing-cursor lookup)."""

    # lock discipline (tools/lint `locks` rule): registration from
    # setup threads vs shape lookups from protocol handler threads
    _shared_attrs = ("_views", "_by_shape")

    def __init__(self):
        self._views: Dict[str, MaterializedView] = {}
        self._by_shape: Dict[str, MaterializedView] = {}
        self._lock = make_lock("streaming.ivm.IvmRegistry._lock")
        register_owner(self)

    def register(self, runner, name: str, sql: str) -> MaterializedView:
        """Plan ``sql`` on ``runner`` and register it as a maintained
        view. Planning runs outside the registry lock (it may execute
        plan-time scalar subqueries)."""
        plan = runner.plan(sql)
        view = MaterializedView(name, sql, plan, runner.catalogs,
                                runner)
        with self._lock:
            old = self._views.get(name)
            if old is not None:
                self._by_shape.pop(old.shape_fp, None)
            self._views[name] = view
            self._by_shape[view.shape_fp] = view
        return view

    def get(self, name: str) -> Optional[MaterializedView]:
        with self._lock:
            return self._views.get(name)

    def views(self) -> List[MaterializedView]:
        with self._lock:
            return list(self._views.values())

    def match(self, plan: P.PhysicalNode) -> Optional[MaterializedView]:
        """The registered view whose shape this plan IS, or None —
        how a tailing /v1/statement cursor decides to ride the IVM
        path instead of re-executing per poll."""
        fp = view_shape_fingerprint(plan)
        with self._lock:
            return self._by_shape.get(fp)

    def unregister(self, name: str) -> bool:
        with self._lock:
            view = self._views.pop(name, None)
            if view is not None:
                self._by_shape.pop(view.shape_fp, None)
            return view is not None


# ------------------------------------------------- the shared instance
_shared_lock = make_lock("streaming.ivm._shared_lock")
_shared: Optional[IvmRegistry] = None


def shared_registry() -> IvmRegistry:
    """THE process-shared registry (the shared_cache() pattern): the
    HTTP server's tail cursors and library users see one view set."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = IvmRegistry()
        return _shared


def shared_registry_if_exists() -> Optional[IvmRegistry]:
    return _shared
