"""Plugin SPI: connectors + user-defined scalar functions.

Reference: presto-spi spi/Plugin.java — a plugin contributes
ConnectorFactories, functions (@ScalarFunction classes), types, event
listeners; presto-main's PluginManager installs them into the engine
registries at startup (with classloader isolation, which Python does not
need). The TPU translation: a Plugin contributes Connector instances,
EventListeners, and scalar functions that register into the expression
registry (presto_tpu/expr/functions.py) — from there they resolve, type-
check, and jit-compile exactly like builtins (the @ScalarFunction ->
FunctionRegistry -> compiled-call path, SURVEY §4.4).

UDF authoring surface: `scalar_function` wraps an elementwise array
function (operating on the `xp` namespace — numpy or jax.numpy, so the
same UDF runs in both the compiled and oracle evaluators) with a fixed
signature; generic NULL propagation is applied by the evaluator like any
default-null-convention scalar.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.connectors.base import Connector
from presto_tpu.events import EventListener
from presto_tpu.expr import functions as F
from presto_tpu.expr.values import Val


@dataclasses.dataclass(frozen=True)
class ScalarFunctionSpec:
    """One UDF: fixed argument types, result type, elementwise impl
    fn(xp, *data_arrays) -> data_array (reference: one @ScalarFunction
    method signature)."""

    name: str
    arg_types: Sequence[T.SqlType]
    result_type: T.SqlType
    fn: Callable
    propagate_nulls: bool = True


def scalar_function(
    name: str,
    arg_types: Sequence[T.SqlType],
    result_type: T.SqlType,
    propagate_nulls: bool = True,
):
    """Decorator form:

        @scalar_function("clamp01", [T.DOUBLE], T.DOUBLE)
        def clamp01(xp, x):
            return xp.clip(x, 0.0, 1.0)
    """

    def deco(fn):
        spec = ScalarFunctionSpec(
            name, tuple(arg_types), result_type, fn, propagate_nulls
        )
        fn.__presto_tpu_spec__ = spec
        return fn

    return deco


class Plugin:
    """Reference: spi/Plugin.java. Override any subset."""

    name: str = "plugin"

    def connectors(self) -> Dict[str, Connector]:
        """catalog name -> Connector instance (reference:
        getConnectorFactories; ours are instances, config-free)."""
        return {}

    def scalar_functions(self) -> List[ScalarFunctionSpec]:
        """UDFs to install (reference: getFunctions). Entries may be
        ScalarFunctionSpec or functions decorated with
        @scalar_function."""
        return []

    def aggregate_functions(self):
        """Aggregate UDFs (reference: @AggregationFunction classes in
        getFunctions). Entries are
        presto_tpu.exec.agg_states.AggregateFunctionSpec: state columns
        decomposed into the primitive segmented-reduction kinds, so a
        plugin aggregate inherits PARTIAL/FINAL splits, spill
        partitioning, and mesh repartition for free."""
        return []

    def event_listeners(self) -> List[EventListener]:
        """Reference: getEventListenerFactories."""
        return []

    def types(self) -> Dict[str, object]:
        """Named types to register (reference: getTypes): name ->
        SqlType instance; they then resolve in CAST/DDL like
        builtins."""
        return {}

    def access_control(self):
        """An AccessControl to install (reference:
        getSystemAccessControlFactories); None = contribute none. At
        most one plugin in a process may contribute one."""
        return None


def _as_spec(item) -> ScalarFunctionSpec:
    if isinstance(item, ScalarFunctionSpec):
        return item
    spec = getattr(item, "__presto_tpu_spec__", None)
    if spec is None:
        raise TypeError(
            f"not a scalar function spec: {item!r} (use "
            f"@scalar_function or ScalarFunctionSpec)"
        )
    return spec


def _install_function(spec: ScalarFunctionSpec) -> None:
    want = tuple(spec.arg_types)

    def resolve(args: List[T.SqlType]) -> T.SqlType:
        if len(args) != len(want):
            raise TypeError(
                f"{spec.name}: expected {len(want)} args, got {len(args)}"
            )
        for got, exp in zip(args, want):
            if T.common_super_type(got, exp) is None:
                raise TypeError(
                    f"{spec.name}: argument {got} not coercible to {exp}"
                )
        return spec.result_type

    def impl(ctx, rt, vals: List[Val]) -> Val:
        from presto_tpu.expr.values import cast_data

        # coerce arguments to the declared signature (the registry's
        # resolve proved coercibility; e.g. a decimal literal passed to a
        # DOUBLE parameter arrives as unscaled ints and must be scaled)
        datas = []
        for v, exp in zip(vals, want):
            if v.type == exp:
                datas.append(v.data)
            else:
                d, _ = cast_data(ctx.xp, v, exp, ctx.capacity)
                datas.append(d)
        data = spec.fn(ctx.xp, *datas)
        return Val(data, None, rt)

    F.register(spec.name, resolve, impl,
               propagate_nulls=spec.propagate_nulls)


def install(
    plugin: Plugin,
    catalogs: Optional[Dict] = None,
    allow_access_control: bool = False,
) -> Plugin:
    """Install a plugin into the process-wide registries; when a catalogs
    dict is passed (LocalRunner/PrestoTpuServer wiring), the plugin's
    connectors are added to it (reference: PluginManager.installPlugin +
    ConnectorManager.createConnection).

    A plugin contributing an AccessControl must be installed through an
    engine that can enforce it (LocalRunner(plugins=...) /
    PrestoTpuServer(plugins=...)); those callers pass
    allow_access_control=True and wire it themselves. Direct install()
    raises instead of silently dropping the contributed policy."""
    if not allow_access_control and plugin.access_control() is not None:
        raise ValueError(
            "plugin contributes an AccessControl that install() cannot "
            "enforce; install it via LocalRunner(plugins=...) or "
            "PrestoTpuServer(plugins=...)"
        )
    for item in plugin.scalar_functions():
        _install_function(_as_spec(item))
    for agg in plugin.aggregate_functions():
        from presto_tpu.exec import agg_states as AS

        AS.register_aggregate(agg)
    for name, t in plugin.types().items():
        T.register_type(name, t)
    if catalogs is not None:
        for name, conn in plugin.connectors().items():
            if name in catalogs:
                raise ValueError(f"catalog already exists: {name}")
            catalogs[name] = conn
    return plugin
