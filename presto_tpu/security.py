"""Access control SPI.

Reference: presto-spi spi/security/* — SystemAccessControl +
ConnectorAccessControl checks (checkCanExecuteQuery, checkCanSelect...,
denials raise AccessDeniedException). The engine consults ONE installed
AccessControl (plugins contribute it; default allows everything) at two
choke points: statement admission and planned table access — the same
places the reference's AccessControlManager sits in the analyzer.
"""

from __future__ import annotations

from typing import Sequence


class AccessDeniedError(PermissionError):
    """Reference: spi/security/AccessDeniedException."""

    def __init__(self, what: str):
        super().__init__(f"Access Denied: {what}")


class AccessControl:
    """Override any subset; the default allows everything (reference:
    AllowAllAccessControl). Deny by raising AccessDeniedError (the
    `deny` helper formats the message like the reference does)."""

    @staticmethod
    def deny(what: str):
        raise AccessDeniedError(what)

    def check_can_execute_query(self, user: str, sql: str) -> None:
        pass

    def check_can_select(self, user: str, catalog: str, table: str,
                         columns: Sequence[str]) -> None:
        pass

    def check_can_create_table(self, user: str, catalog: str,
                               table: str) -> None:
        pass

    def check_can_insert(self, user: str, catalog: str,
                         table: str) -> None:
        pass

    def check_can_delete(self, user: str, catalog: str,
                         table: str) -> None:
        pass

    def check_can_update(self, user: str, catalog: str,
                         table: str) -> None:
        pass

    def check_can_drop_table(self, user: str, catalog: str,
                             table: str) -> None:
        pass

    def check_can_create_view(self, user: str, catalog: str,
                              name: str) -> None:
        pass

    def check_can_drop_view(self, user: str, catalog: str,
                            name: str) -> None:
        pass

    def check_can_set_session(self, user: str, name: str) -> None:
        pass


ALLOW_ALL = AccessControl()
