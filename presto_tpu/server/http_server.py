"""Coordinator HTTP service speaking the Presto client protocol.

Reference: presto-main server/protocol/StatementResource.java (the
/v1/statement paged REST protocol: POST the SQL, follow nextUri until it
disappears, token-addressed result pages, DELETE to cancel) plus
server/PrestoServer bootstrap. Sessions are client-carried exactly like
the reference: X-Presto-Session request headers hold property overrides,
SET SESSION responds with X-Presto-Set-Session and the client echoes it
back on later requests — the server itself stays stateless per query.

The engine is the in-process LocalRunner (single- or mesh-distributed);
queries execute on a worker thread under a global lock (one query on the
device at a time) while the protocol surface stays responsive.
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse

from presto_tpu import types as T
from presto_tpu.obs.sanitizer import (
    make_condition,
    make_lock,
    register_owner,
)
from presto_tpu.session import SYSTEM_SESSION_PROPERTIES, Session

_PAGE_ROWS = 4096  # rows per protocol fetch (client paging granularity)
# tailing cursors retain only this many recent token spans' rows —
# the retry horizon; a never-finishing cursor must not hold every row
# it ever emitted (clients only ever re-fetch their latest token)
_TAIL_RETAIN_SPANS = 8


class _Query:
    """Reference: server/protocol/Query.java — one statement's life."""

    def __init__(self, qid: str, sql: str, session: Session):
        self.id = qid
        self.sql = sql
        self.session = session
        self.state = "QUEUED"
        self.columns: Optional[List[Dict]] = None
        self.rows: List[tuple] = []
        self.error: Optional[Dict] = None
        self.update_type: Optional[str] = None
        self.set_session: Dict[str, str] = {}
        # ONE wall anchor (display/correlation); every elapsed-time
        # computation runs on monotonic so an NTP step mid-query can
        # neither stretch nor collapse it (ISSUE 9 timing-source rule)
        self.created = time.time()
        self.created_mono = time.monotonic()
        self.finished_at: Optional[float] = None
        self.finished_mono: Optional[float] = None
        self.cancelled = False
        self.done = threading.Event()
        # lifecycle trace (obs.QueryTrace), captured from the runner
        # when the query completes; while RUNNING the live trace is
        # read off the runner's executor (see QueryManager.query_info)
        self.trace = None
        self.runner = None
        # tailing cursor (ISSUE 14): non-None turns this query into a
        # never-finishing stream cursor served by _tail_results
        self.tail: Optional["TailCursor"] = None
        # durable journal handle (ISSUE 20): non-None means this
        # query's lifecycle + protocol-token advances are journaled
        # for crash re-attach (dist/checkpoint.QueryCheckpoint)
        self.checkpoint = None

    def _finish_clock(self) -> None:
        if self.finished_at is None:
            self.finished_at = time.time()
            self.finished_mono = time.monotonic()

    def info(self) -> Dict:
        end_mono = (self.finished_mono if self.finished_mono
                    is not None else time.monotonic())
        return {
            "queryId": self.id,
            "state": self.state,
            "query": self.sql,
            "createTime": self.created,
            "elapsedTimeMillis": int(
                (end_mono - self.created_mono) * 1000
            ),
            "error": self.error,
            "rowCount": len(self.rows),
        }


class TailCursor:
    """One tailing /v1/statement cursor over an append-only stream
    (ISSUE 14): the statement's nextUri never terminates — each poll
    long-polls the log (StreamConnector.wait_for_offset) and emits
    ONLY rows derived from new offsets. Three poll strategies, chosen
    once at creation from the planned statement:

      view       the statement IS a registered materialized view
                 (shape-fingerprint match, streaming/ivm.py): polls
                 ride the incremental refresh — O(new rows) fold —
                 and emit the multiset delta of the refreshed result
                 vs the previously emitted snapshot (changed/new
                 aggregate rows, the live-dashboard diff);
      delta      a pure per-row pipeline (Output → Filter/Project* →
                 stream scan): polls execute the plan over the pinned
                 [last, head) window only — exactly the new rows, no
                 recompute, no diff;
      recompute  anything else over a stream: polls re-execute the
                 statement and emit the multiset delta — degraded
                 (O(full) per poll) but never wrong, the same
                 loud-fallback stance as non-IVM-safe views.

    Concurrency: protocol GETs may race on one cursor. State mutates
    only under ``_cv``; the poll's query execution runs UNLOCKED
    behind the ``_polling`` flag (concurrent pollers wait, then read
    the freshly appended span) so no blocking work ever happens under
    an engine lock. Token paging is span-addressed: token t re-serves
    its recorded row span verbatim (retry-safe), the first fresh
    token takes everything new."""

    # lock discipline (tools/lint `locks` rule)
    _shared_attrs = ("columns", "types", "rows", "error", "closed",
                     "_polling", "_spans", "last_rows", "last_offset",
                     "_base", "_span_base", "resource_group")

    def __init__(self, runner, plan, streams, sink):
        from presto_tpu.streaming import ivm as IVM

        self.runner = runner
        self.plan = plan
        # every append-only table the statement scans: polls wake on
        # ANY of them advancing (view/delta modes have exactly one by
        # construction; recompute mode may join several streams)
        self.streams = list(streams)
        self.catalog, self.table = self.streams[0]
        self.conn = runner.catalogs[self.catalog]
        self.sink = sink  # bootstrap executor: registry counters
        self.poll_ms = int(runner.session.get("stream_poll_ms"))
        reg = IVM.shared_registry_if_exists()
        self.view = reg.match(plan) if reg is not None else None
        self.window = None
        self.executor = None
        if self.view is not None:
            self.mode = "view"
        elif self._delta_shape(plan):
            self.mode = "delta"
            self.executor, self.window = IVM.windowed_executor(
                runner.catalogs, self.catalog, self.table,
                like=runner.executor,
            )
        else:
            self.mode = "recompute"
        self.columns: Optional[List[Dict]] = None
        self.types: List[str] = []
        # emitted rows, trimmed to the retry horizon: _base is the
        # ABSOLUTE index of rows[0] — a never-finishing cursor must
        # not retain every row it ever emitted (spans older than
        # _TAIL_RETAIN_SPANS tokens are beyond any client retry)
        self.rows: List[tuple] = []
        self._base = 0
        # recent token spans only (ABSOLUTE (lo, hi) row indices);
        # _span_base counts the spans trimmed off the front — an idle
        # cursor heartbeats one span per poll forever, so the span
        # list is bounded exactly like the rows it addresses
        self._spans: List[tuple] = []
        self._span_base = 0
        self.last_rows: List[tuple] = []  # last full result (diff)
        # SUM of offsets across all scanned streams (single-stream
        # cursors: just that table's offset)
        self.last_offset = 0
        self.error: Optional[Dict] = None
        self.closed = False
        self._polling = False
        # resource-group admission slot (start_tail admits a tailing
        # statement through the same queue gate as submit(); close
        # releases it) — the manager reference rides along so close
        # can release without reaching back into the server
        self.resource_group = None
        self._rg_manager = None
        self._cv = make_condition(
            "server.http_server.TailCursor._cv")
        register_owner(self, lock_attrs=("_cv",))

    def _delta_shape(self, plan) -> bool:
        """True for Output → (Filter|Project)* → TableScan of THE
        stream table — the shape whose delta-window execution equals
        the delta of its results."""
        from presto_tpu.exec import plan as P

        node = plan
        if not isinstance(node, P.Output):
            return False
        node = node.source
        while isinstance(node, (P.Filter, P.Project)):
            node = node.source
        return (isinstance(node, P.TableScan)
                and node.catalog == self.catalog
                and node.table == self.table)

    # ------------------------------------------------------- polling
    def _offsets_total(self) -> int:
        return sum(self.runner.catalogs[c].offset(t)
                   for c, t in self.streams)

    def _wait_any(self, timeout_s: float) -> int:
        """Long-poll until ANY scanned stream advances past the last
        observed offsets (or the timeout lapses); returns the summed
        offset. Single-stream cursors ride the connector's condition;
        multi-stream recompute cursors poll in slices (appends to
        EITHER side of a stream join must produce rows)."""
        base = self.last_offset
        if len(self.streams) == 1:
            c, t = self.streams[0]
            self.runner.catalogs[c].wait_for_offset(t, base, timeout_s)
            return self._offsets_total()
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while True:
            total = self._offsets_total()
            remaining = deadline - time.monotonic()
            if total > base or remaining <= 0:
                return total
            time.sleep(min(0.05, remaining))

    def poll(self, timeout_s: float) -> None:
        """Advance the cursor: wait for new offsets (up to
        ``timeout_s``), compute the delta-derived rows, append them.
        Serialized by the ``_polling`` flag; a failure closes the
        cursor with an error body (the protocol's FAILED contract —
        never a dropped connection)."""
        with self._cv:
            while self._polling and not self.closed:
                self._cv.wait(0.05)
            if self.closed:
                return
            self._polling = True
        new_rows: List[tuple] = []
        full: Optional[List[tuple]] = None
        err = None
        cols = None
        types = None
        offset = None
        try:
            new_rows, full, cols, types, offset = self._compute(
                timeout_s)
        except Exception as e:  # noqa: BLE001 - the protocol surfaces
            # every tail failure as an error body on the cursor
            err = {"message": str(e)[:2000],
                   "errorName": type(e).__name__}
        with self._cv:
            self._polling = False
            if err is not None:
                self.error = err
                self.closed = True
            else:
                if cols is not None and self.columns is None:
                    self.columns = cols
                    self.types = types or []
                if full is not None:
                    self.last_rows = full
                if offset is not None:
                    self.last_offset = offset
                self.rows.extend(new_rows)
            self._cv.notify_all()

    def _compute(self, timeout_s: float):
        """(delta rows, full result or None, columns or None, types,
        new offset). Runs UNLOCKED — see class docstring."""
        from presto_tpu.streaming import ivm as IVM

        initial = self.columns is None
        if initial:
            hi = self._offsets_total()
        else:
            hi = self._wait_any(timeout_s)
        self.sink.count_cursor_poll()
        if not initial and hi <= self.last_offset:
            return [], None, None, None, None  # quiet poll
        if not initial:
            # the log moved under a tailing cursor: one observed batch
            self.sink.count_stream_append()
        if self.mode == "view":
            names, rows, types = IVM.refresh(
                self.view, session=self.runner.session,
                sink=self.sink)
            delta = rows if initial else _multiset_delta(
                rows, self.last_rows)
            cols = [{"name": n, "type": t}
                    for n, t in zip(names, types)]
            return delta, list(rows), cols, types, hi
        if self.mode == "delta":
            ex = self.executor
            self.window.set_range(
                0 if initial else self.last_offset, hi)
            names, rows = ex.execute(self.plan)
            types = [str(t) for t in ex.output_types(self.plan)]
            cols = [{"name": n, "type": t}
                    for n, t in zip(names or [], types)]
            return rows, None, cols, types, hi
        # recompute: full statement re-execution + multiset diff —
        # degraded loudly (every poll is a real run), never wrong
        ex = self.runner.executor
        names, rows = ex.execute(self.plan)
        types = [str(t) for t in ex.output_types(self.plan)]
        cols = [{"name": n, "type": t}
                for n, t in zip(names or [], types)]
        delta = rows if initial else _multiset_delta(
            rows, self.last_rows)
        return delta, list(rows), cols, types, hi

    # ------------------------------------------------- token paging
    def take_span(self, token: int):
        """JSON rows for ``token``: a RECENT token re-serves its exact
        recorded span (retry-safe); the next fresh token takes every
        row emitted since the previous span. None for tokens further
        ahead or already trimmed past the retry horizon (protocol
        clients only ever retry their latest token)."""
        with self._cv:
            idx = token - self._span_base
            if idx < 0:
                return None  # trimmed: beyond the retry horizon
            if idx < len(self._spans):
                lo, hi = self._spans[idx]
            elif idx == len(self._spans):
                lo = self._spans[-1][1] if self._spans else self._base
                hi = self._base + len(self.rows)
                self._spans.append((lo, hi))
                # bound the never-finishing cursor's memory: spans AND
                # the rows they address drop past the retry horizon
                # (spans keep ABSOLUTE indices; _base/_span_base track
                # what rows[0]/_spans[0] correspond to)
                if len(self._spans) > _TAIL_RETAIN_SPANS:
                    drop = len(self._spans) - _TAIL_RETAIN_SPANS
                    floor = self._spans[drop][0]
                    del self._spans[:drop]
                    self._span_base += drop
                    if floor > self._base:
                        del self.rows[:floor - self._base]
                        self._base = floor
            else:
                return None
            types = self.types
            return [_json_row(r, types)
                    for r in self.rows[lo - self._base:hi - self._base]]

    def spans_served(self) -> int:
        with self._cv:
            return self._span_base + len(self._spans)

    def close(self) -> None:
        """Stop the cursor and RELEASE its heavy engine state (the
        dedicated runner/executor, delta window, diff snapshot) and
        its resource-group admission slot — the _Query record stays
        in the manager registry like any finished query, but a closed
        cursor must not pin an Executor. The already-emitted row tail
        stays servable for the final page. Waits out an in-flight
        poll (bounded by the poll timeout) so the engine refs are
        never nulled under a running query."""
        with self._cv:
            self.closed = True
            self._cv.notify_all()
            while self._polling:
                self._cv.wait(0.05)
            self.last_rows = []
            group, self.resource_group = self.resource_group, None
        if group is not None and self._rg_manager is not None:
            self._rg_manager.cancel_queued(group)
        self.runner = None
        self.executor = None
        self.window = None
        self.view = None


def _multiset_delta(new_rows, old_rows):
    """Rows of ``new_rows`` not covered by ``old_rows`` as a multiset
    (repr-keyed: rows may carry unhashable nested values) — the
    changed/new rows a dashboard diff emits per refresh."""
    import collections

    old = collections.Counter(map(repr, old_rows))
    out = []
    for r in new_rows:
        k = repr(r)
        if old[k] > 0:
            old[k] -= 1
        else:
            out.append(r)
    return out


class MemoryArbiter:
    """Admission by estimated HBM footprint (reference:
    memory/ClusterMemoryManager + query.max-memory): queries reserve
    their estimate and block until it fits the budget. A query larger
    than the whole budget is admitted only when it would run alone —
    progress is guaranteed, concurrency degrades to serial exactly
    when memory demands it (the reference's reserved-pool promotion)."""

    # lock discipline (tools/lint `locks` rule): the reservation
    # tallies every query's admission thread contends on
    _shared_attrs = ("used", "active")

    def __init__(self, total_bytes: int):
        self.total = int(total_bytes)
        self.used = 0
        self.active = 0
        self._cv = make_condition(
            "server.http_server.MemoryArbiter._cv")
        register_owner(self, lock_attrs=("_cv",))

    def acquire(self, est: int, should_abort=None) -> bool:
        with self._cv:
            while True:
                if should_abort is not None and should_abort():
                    return False
                if self.used + est <= self.total or self.active == 0:
                    self.used += est
                    self.active += 1
                    return True
                self._cv.wait(timeout=0.1)

    def release(self, est: int) -> None:
        with self._cv:
            self.used -= est
            self.active -= 1
            self._cv.notify_all()


def _xfer_totals():
    """Process-total transfer tallies (exec/xfer.py choke points)
    under the registry counter names — per-query executors come and
    go on the concurrent path; the copy-tax truth loadbench reads is
    the process accumulation."""
    from presto_tpu.exec import xfer as XFER

    return XFER.process_totals()


def _wire_totals():
    """Process-total exchange wire tallies (dist/serde.py codecs +
    dist/connpool.py reuse) under the registry counter names — same
    rationale as _xfer_totals: worker task executors never surface
    on the scrape path, the process accumulation is the fleet truth
    loadbench grades wire efficiency from."""
    from presto_tpu.dist import connpool as CONNPOOL
    from presto_tpu.dist import serde as SERDE

    out = SERDE.wire_totals()
    out.update(CONNPOOL.pool_totals())
    return out


def _result_cache_totals():
    """Process-total result-cache tallies under the registry counter
    names (zeros when no session ever created the shared store —
    scraping metrics must never allocate a cache)."""
    from presto_tpu.cache import shared_cache_if_exists

    rc = shared_cache_if_exists()
    if rc is None:
        return {
            "result_cache_hits": 0,
            "result_cache_misses": 0,
            "result_cache_evictions": 0,
            "result_cache_invalidations": 0,
            "cache_warm_loads": 0,
            "cache_remote_hits": 0,
            "cache_subsumed_hits": 0,
            "cache_manifest_drops": 0,
        }
    return rc.counters()


class QueryManager:
    """Reference: execution/SqlQueryManager.java — registry + lifecycle
    (QUEUED -> RUNNING -> FINISHED/FAILED/CANCELED)."""

    # lock discipline (tools/lint `locks` rule): attributes touched
    # from both HTTP handler threads and query-execution threads —
    # written ONLY under self._lock outside __init__
    _shared_attrs = ("_queries", "_seq", "completed_by_state",
                     "rows_returned_total", "query_wall_ms_total",
                     "cache_admission_bypasses",
                     "exec_counter_totals",
                     "queued_now", "peak_queued", "journal")

    # launch/batch counters accumulated across the concurrent path's
    # per-query executors at completion (ISSUE 17): those executors
    # are discarded per query, so the PROCESS aggregate — the number
    # the loadbench launches-per-query A/B reads — lives here and
    # overlays the registry snapshot on /metrics + system.metrics
    # (the _result_cache_totals rationale applied to dispatch)
    _EXEC_TOTAL_SUMS = (
        "program_launches", "splits_scanned", "cross_query_batches",
        "cross_query_batched_queries", "batch_gather_wait_ms",
    )
    _EXEC_TOTAL_MAX = ("queries_per_launch",)

    def __init__(self, runner_factory, listeners=(),
                 resource_groups=None, memory_arbiter=None,
                 listener_error_counter=None, journal=None,
                 counter_executor=None, dcn=None):
        from presto_tpu.obs.histo import Histogram

        self._runner_factory = runner_factory
        # durable coordinator journal (ISSUE 20): server-configured
        # (checkpoint.dir etc key) or lazily bound from the
        # checkpoint_dir session property at first enabled submit
        self.journal = journal
        self._counter_ex = counter_executor
        # the DCN dispatch plane whose scheduler barriers the per-query
        # checkpoint handle is attached to for stage-boundary journaling
        self._dcn = dcn
        self._queries: Dict[str, _Query] = {}
        self._seq = 0
        self._lock = make_lock(
            "server.http_server.QueryManager._lock")
        # serial fallback when no arbiter is configured
        self._exec_lock = make_lock(
            "server.http_server.QueryManager._exec_lock")
        self.memory = memory_arbiter
        self.listeners = list(listeners)
        # swallowed-listener-exception sink (events.dispatch on_error
        # -> the executor's listener_errors registry counter)
        self._listener_error = listener_error_counter
        # admission control (reference: resourceGroups/*; None = admit
        # everything, the pre-RG behavior)
        self.resource_groups = resource_groups
        # /metrics counters (reference: airlift stats -> JMX; ours is a
        # Prometheus text endpoint, SURVEY §6.5 build mapping)
        self.completed_by_state: Dict[str, int] = {}
        self.rows_returned_total = 0
        self.query_wall_ms_total = 0
        # cache-aware admission (ISSUE 17): statements served whole
        # from the result cache without ever taking a resource-group
        # concurrency slot or an arbiter reservation
        self.cache_admission_bypasses = 0
        # process launch/batch aggregate (see _EXEC_TOTAL_SUMS)
        self.exec_counter_totals: Dict[str, int] = {}
        # admission queue depth (ISSUE 17): queries currently waiting
        # for admission (resource-group slot / memory reservation /
        # the serial exec lock) and the lifetime peak — the number the
        # cache-bypass loadbench assertion reads: replays must never
        # inflate this line
        self.queued_now = 0
        self.peak_queued = 0
        # latency histograms (obs/histo.py): bucketed query wall and
        # per-stage wall for p50/p95/p99 — internally locked, written
        # via observe() from completion paths, scraped by /metrics
        # (the surface ROADMAP item 1's load benchmark reads)
        self.latency_histo = Histogram()
        self.stage_histo = Histogram()
        register_owner(self)

    def _journal_for(self, session: Session):
        """The journal this query's barriers record to: the server-
        configured one (checkpoint.dir etc key / constructor kwarg),
        or one bound lazily from the checkpoint_dir session property.
        None = journaling off (checkpoint_enabled false, or no
        directory anywhere)."""
        if not bool(session.get("checkpoint_enabled")):
            return None
        if self.journal is not None:
            return self.journal
        d = session.get("checkpoint_dir")
        if not d:
            return None
        from presto_tpu.dist.checkpoint import CheckpointJournal

        j = CheckpointJournal(d, counter_ex=self._counter_ex)
        with self._lock:
            if self.journal is None:
                self.journal = j
        return self.journal

    def submit(self, sql: str, session: Session) -> _Query:
        from presto_tpu import events as E

        group = None
        if self.resource_groups is not None:
            # raises QueryQueueFullError before the query exists
            # (reference: admission happens ahead of planning)
            group = self.resource_groups.admit(session.user)
        with self._lock:
            self._seq += 1
            qid = time.strftime("%Y%m%d_%H%M%S") + \
                f"_{self._seq:05d}_{uuid.uuid4().hex[:5]}"
            q = _Query(qid, sql, session)
            q.resource_group = group
            self._queries[qid] = q
        j = self._journal_for(session)
        if j is not None:
            # admission barrier (ISSUE 20): statement + session +
            # group land durably before the execution thread exists
            q.checkpoint = j.admit(
                qid, sql, _session_snapshot(session),
                str(group.paths[-1]) if group is not None else None,
            )
        E.dispatch(self.listeners, "query_created", E.QueryCreatedEvent(
            query_id=q.id, sql=sql, user=session.user,
            create_time=q.created,
        ), on_error=self._listener_error)
        threading.Thread(
            target=self._run, args=(q,), daemon=True
        ).start()
        return q

    def get(self, qid: str) -> Optional[_Query]:
        return self._queries.get(qid)

    def register_tail(self, sql: str, session: Session,
                      cursor: TailCursor) -> _Query:
        """Register a tailing cursor as a RUNNING query: it appears
        in /v1/query and system.runtime_queries like any statement,
        but no execution thread is spawned — polls ride the protocol
        GET handlers (TailCursor.poll serializes them)."""
        with self._lock:
            self._seq += 1
            qid = time.strftime("%Y%m%d_%H%M%S") + \
                f"_{self._seq:05d}_{uuid.uuid4().hex[:5]}"
            q = _Query(qid, sql, session)
            q.tail = cursor
            q.state = "RUNNING"
            self._queries[qid] = q
        return q

    def cancel(self, qid: str) -> bool:
        q = self._queries.get(qid)
        if q is None:
            return False
        q.cancelled = True
        if not q.done.is_set():
            q.state = "CANCELED"
            q._finish_clock()
            q.done.set()
        return True

    def query_info(self, qid: str) -> Optional[Dict]:
        """The QueryInfo/StageInfo/TaskInfo tree for one query
        (reference: /v1/query/{id}). Served LIVE: a RUNNING query's
        tree comes straight off its runner's active trace, so a
        mid-query poll sees the stages/tasks recorded so far."""
        q = self._queries.get(qid)
        if q is None:
            return None
        info = q.info()
        tr = q.trace
        if tr is None and not q.done.is_set():
            r = q.runner
            tr = getattr(r.executor, "trace", None) if r is not None \
                else None
        if tr is not None:
            tree = tr.to_info()
            info["stages"] = tree["stages"]
            info["spanCount"] = tree["spanCount"]
        else:
            info["stages"] = []
            info["spanCount"] = 0
        return info

    def _run(self, q: _Query) -> None:
        group = getattr(q, "resource_group", None)
        runner = None
        if self.memory is not None and not q.cancelled:
            # cache-aware admission (ISSUE 17): a statement the
            # result cache would serve whole costs near nothing —
            # parking it in the resource-group line or reserving HBM
            # for it would spend real slots on zero-cost work and
            # queue REAL queries behind replays. The probe is pure
            # host work (parse + plan + tally-free key peek); on a
            # hit the query executes immediately, outside every
            # admission gate. Advisory: a racing eviction between
            # probe and serve just runs the query for real, admitted
            # only by the arbiter-level backstop it skipped — an
            # accepted, bounded misestimate (est is small anyway).
            runner = self._runner_factory(q.session)
            if runner.statement_cache_probe(q.sql):
                if group is not None:
                    self.resource_groups.cancel_queued(group)
                with self._lock:
                    self.cache_admission_bypasses += 1
                self._execute(q, runner)
                return
        self._queue_enter(q)
        if group is not None:
            if q.cancelled:
                self.resource_groups.cancel_queued(group)
                self._record_completion(q)
                return
            if not self.resource_groups.acquire(
                group, should_abort=lambda: q.cancelled
            ):
                # canceled while queued: acquire released the queue slot
                self._record_completion(q)
                return
        try:
            self._run_admitted(q, runner)
        finally:
            if group is not None:
                self.resource_groups.release(group)

    def _queue_enter(self, q: _Query) -> None:
        """Mark q as waiting for admission. Paired with _queue_exit
        (first of: execution start, completion record) via a consumed-
        once flag, so abort paths and the execute path can both exit
        without double counting."""
        q.in_admission = True
        with self._lock:
            self.queued_now += 1
            self.peak_queued = max(self.peak_queued, self.queued_now)

    def _queue_exit(self, q: _Query) -> None:
        if getattr(q, "in_admission", False):
            q.in_admission = False
            with self._lock:
                self.queued_now -= 1

    # NB: not named `*_locked` — that suffix is the machine-checked
    # caller-holds-the-lock convention (tools/concheck.py); this
    # method ACQUIRES the execution lock/arbiter itself
    def _run_admitted(self, q: _Query, runner=None) -> None:
        if self.memory is None:
            with self._exec_lock:
                self._execute(q)
            return
        # concurrent path: admission by estimated footprint replaces
        # the global device lock (VERDICT r2 #8); each query runs on
        # its own runner/executor (shared jit cache), so small queries
        # interleave while the arbiter keeps the sum under budget
        if runner is None:
            runner = self._runner_factory(q.session)
        est = runner.estimate_memory(q.sql)
        group = getattr(q, "resource_group", None)
        if group is not None and self.resource_groups is not None:
            # per-group HBM shares (ISSUE 17): the group policy's
            # memory_share resolves into THIS query's governed
            # device budget (exec/membudget.py) — N concurrent
            # queries split the device by policy instead of
            # colliding into the OOM ladder. An explicit session
            # device_memory_budget always wins.
            share = self.resource_groups.memory_share_for(group)
            if share > 0 and not q.session.is_set(
                    "device_memory_budget"):
                from presto_tpu.exec import membudget as MB

                q.session.set(
                    "device_memory_budget",
                    MB.group_share_bytes(share),
                )
        if group is not None and self.resource_groups is not None:
            # per-group memory quotas gate before the global arbiter
            # (reference: soft_memory_limit per resource group)
            if not self.resource_groups.reserve_memory(
                group, est, should_abort=lambda: q.cancelled
            ):
                self._record_completion(q)
                return
        try:
            if not self.memory.acquire(
                est, should_abort=lambda: q.cancelled
            ):
                self._record_completion(q)
                return
            try:
                self._execute(q, runner)
            finally:
                self.memory.release(est)
        finally:
            if group is not None and self.resource_groups is not None:
                self.resource_groups.release_memory(group, est)

    def _execute(self, q: _Query, runner=None) -> None:
            self._queue_exit(q)
            ckpt = q.checkpoint
            if q.cancelled:
                # canceled while queued: still record completion so event
                # listeners and /metrics see every created query finish
                self._record_completion(q)
                if ckpt is not None:
                    ckpt.delivered()  # nothing left to recover
                return
            q.state = "RUNNING"
            if ckpt is not None:
                ckpt.running()
                # stage-boundary barriers ride the DCN scheduler
                # (dist/scheduler._checkpoint_stage reads this handle);
                # serial path only, so one query owns it at a time
                if self._dcn is not None:
                    self._dcn.checkpoint_handle = ckpt
            prev_trace = None
            try:
                if runner is None:
                    runner = self._runner_factory(q.session)
                q.runner = runner  # live-trace handle for query_info
                prev_trace = getattr(runner, "last_trace", None)
                result = runner.execute(q.sql)
                types = result.column_types or [
                    "unknown" for _ in result.column_names
                ]
                q.columns = [
                    {"name": n, "type": t}
                    for n, t in zip(result.column_names, types)
                ]
                q.rows = [_json_row(r, types) for r in result.rows]
                q.update_type = result.update_type
                if result.update_type == "SET SESSION":
                    # surface the new value so clients echo it back
                    # (X-Presto-Set-Session round trip)
                    from presto_tpu.sql.parser import parse
                    from presto_tpu.sql import ast_nodes as N

                    stmt = parse(q.sql)
                    if isinstance(stmt, N.SetSession):
                        q.set_session[stmt.name] = str(stmt.value)
                if not q.cancelled:
                    q.state = "FINISHED"
                    if ckpt is not None:
                        # results exist but the client hasn't drained
                        # them: the record survives (with columns +
                        # row count) until the stream completes, so a
                        # restart mid-delivery can regenerate + verify
                        ckpt.finished(q.columns or [], len(q.rows))
            except Exception as e:  # noqa: BLE001 - the protocol
                # surfaces EVERY query failure as a FAILED state with
                # an error body (reference: QueryResults.error), never
                # as a dropped HTTP connection
                if not q.cancelled:
                    q.error = {
                        "message": str(e)[:2000],
                        "errorName": type(e).__name__,
                    }
                    q.state = "FAILED"
                    if ckpt is not None:
                        ckpt.failed(str(e), type(e).__name__)
            finally:
                if ckpt is not None and self._dcn is not None:
                    self._dcn.checkpoint_handle = None
                q._finish_clock()
                if runner is not None:
                    # snapshot the finished trace before the serial
                    # runner moves on to its next query; a control
                    # statement keeps the runner's previous trace —
                    # only a NEW trace belongs to this query
                    lt = getattr(runner, "last_trace", None)
                    q.trace = lt if lt is not prev_trace else None
                q.done.set()
                self._record_completion(q)
                self._accumulate_exec_totals(runner)

    def _accumulate_exec_totals(self, runner) -> None:
        """Fold one finished query's launch/batch counters into the
        process aggregate (concurrent path only — the serial path's
        bootstrap executor already IS the process surface, and adding
        it here would double-count). Per-attempt gauges carry the
        final attempt's values, matching EXPLAIN ANALYZE."""
        if self.memory is None or runner is None:
            return
        ex = getattr(runner, "executor", None)
        if ex is None:
            return
        with self._lock:
            t = self.exec_counter_totals
            for name in self._EXEC_TOTAL_SUMS:
                t[name] = t.get(name, 0) + int(getattr(ex, name, 0))
            for name in self._EXEC_TOTAL_MAX:
                t[name] = max(
                    t.get(name, 0), int(getattr(ex, name, 0)))

    def _record_completion(self, q: _Query) -> None:
        from presto_tpu import events as E

        self._queue_exit(q)
        wall_ms = q.info()["elapsedTimeMillis"]
        with self._lock:
            self.completed_by_state[q.state] = (
                self.completed_by_state.get(q.state, 0) + 1
            )
            self.rows_returned_total += len(q.rows)
            self.query_wall_ms_total += wall_ms
        # histogram observations (internally locked): query latency
        # always; per-stage wall when the query was traced
        self.latency_histo.observe(wall_ms / 1000.0)
        query_info = None
        if q.trace is not None:
            query_info = q.trace.to_info()
            for stage in query_info["stages"]:
                self.stage_histo.observe(stage["wallMs"] / 1000.0)
        E.dispatch(
            self.listeners, "query_completed", E.QueryCompletedEvent(
                query_id=q.id, sql=q.sql, user=q.session.user,
                state=q.state, create_time=q.created,
                end_time=q.finished_at or time.time(),
                wall_ms=wall_ms,
                row_count=len(q.rows),
                error_name=(q.error or {}).get("errorName"),
                error_message=(q.error or {}).get("message"),
                query_info=query_info,
            ),
            on_error=self._listener_error,
        )

    def metrics_text(self, uptime: float, executor=None) -> str:
        """Prometheus text exposition (reference role: JMX beans +
        presto-jmx; a /metrics scrape replaces the MBean server)."""
        lines = [
            "# TYPE presto_tpu_uptime_seconds gauge",
            f"presto_tpu_uptime_seconds {uptime:.3f}",
            "# TYPE presto_tpu_queries_total counter",
        ]
        with self._lock:
            for state, n in sorted(self.completed_by_state.items()):
                lines.append(
                    f'presto_tpu_queries_total{{state="{state}"}} {n}'
                )
            running = sum(
                1 for q in self._queries.values() if not q.done.is_set()
            )
            lines += [
                "# TYPE presto_tpu_queries_running gauge",
                f"presto_tpu_queries_running {running}",
                "# TYPE presto_tpu_rows_returned_total counter",
                f"presto_tpu_rows_returned_total "
                f"{self.rows_returned_total}",
                "# TYPE presto_tpu_query_wall_ms_total counter",
                f"presto_tpu_query_wall_ms_total "
                f"{self.query_wall_ms_total}",
            ]
        # latency histograms (obs/histo.py): bucketed for p50/p95/p99
        # — Prometheus-native histogram exposition, the surface the
        # concurrent-load benchmark (ROADMAP item 1) scrapes
        lines += self.latency_histo.prom_lines(
            "presto_tpu_query_latency_seconds")
        lines += self.stage_histo.prom_lines(
            "presto_tpu_stage_wall_seconds")
        if executor is not None:
            # device-memory governor (exec/membudget.py): resolved
            # budget plus the last attempt's peak
            lines += [
                "# TYPE presto_tpu_device_memory_budget_bytes gauge",
                f"presto_tpu_device_memory_budget_bytes "
                f"{executor._budget()}",
                "# TYPE presto_tpu_peak_device_bytes gauge",
                f"presto_tpu_peak_device_bytes "
                f"{executor.peak_memory_bytes}",
            ]
            # every declared execution counter (exec/counters.py): the
            # registry IS the exposition list, so a counter added to
            # the engine cannot silently miss the fleet surface (the
            # pre-registry wiring lost split_batch_fallbacks and the
            # spill counters). Lifetime counters keep their historical
            # _total suffix.
            from presto_tpu.exec import counters as CTRS

            snap = CTRS.snapshot(executor)
            # result-cache totals come from the PROCESS-shared store,
            # not the bootstrap executor: on the concurrent path each
            # query runs its own executor whose counters are
            # discarded, while the store the queries actually shared
            # keeps the fleet truth (the hit-rate surface
            # tools/loadbench.py scrapes)
            snap.update(_result_cache_totals())
            # transfer counters overlay the same way (exec/xfer.py
            # process totals — the aggregate copy tax next to QPS/p99)
            xf = _xfer_totals()
            snap.update({k: int(v) for k, v in xf.items()
                         if k in CTRS.QUERY_COUNTERS})
            # exchange wire/codec + connection-reuse totals ride the
            # same process-shared overlay (dist/serde, dist/connpool)
            snap.update({k: int(v) for k, v in _wire_totals().items()
                         if k in CTRS.QUERY_COUNTERS})
            # launch/batch totals accumulate across the concurrent
            # path's discarded per-query executors (ISSUE 17): sums
            # ADD to the bootstrap executor's own counts (zero when
            # idle), the width gauge takes the max — the aggregate
            # launches-per-query truth the loadbench A/B reads
            with self._lock:
                for name in self._EXEC_TOTAL_SUMS:
                    snap[name] = snap.get(name, 0) + \
                        self.exec_counter_totals.get(name, 0)
                for name in self._EXEC_TOTAL_MAX:
                    snap[name] = max(
                        snap.get(name, 0),
                        self.exec_counter_totals.get(name, 0))
            for name, (kind, _help) in CTRS.QUERY_COUNTERS.items():
                suffix = "_total" if kind == "counter" else ""
                lines += [
                    f"# TYPE presto_tpu_{name}{suffix} {kind}",
                    f"presto_tpu_{name}{suffix} {snap[name]}",
                ]
            lines += [
                "# TYPE presto_tpu_transfer_wall_seconds gauge",
                f"presto_tpu_transfer_wall_seconds "
                f"{xf['transfer_wall_s']}",
            ]
        # cache-aware admission (ISSUE 17): replays that never took a
        # resource-group slot — next to the hit-rate so loadbench can
        # assert near-zero-cost hits stop occupying the queue
        with self._lock:
            bypasses = self.cache_admission_bypasses
            peak_q = self.peak_queued
        lines += [
            "# TYPE presto_tpu_admission_cache_bypasses_total counter",
            f"presto_tpu_admission_cache_bypasses_total {bypasses}",
            "# TYPE presto_tpu_peak_queued gauge",
            f"presto_tpu_peak_queued {peak_q}",
        ]
        return "\n".join(lines) + "\n"


_DECIMAL_RE = re.compile(r"decimal\((\d+),\s*(\d+)\)")


def _render_decimal(unscaled: int, scale: int) -> str:
    """Engine-internal unscaled int -> SQL decimal text (reference:
    server/protocol renders decimals scaled: 1529698.00, never the raw
    152969800)."""
    if scale == 0:
        return str(int(unscaled))
    u = int(unscaled)
    sign = "-" if u < 0 else ""
    u = abs(u)
    return f"{sign}{u // 10**scale}.{u % 10**scale:0{scale}d}"


def _json_row(row: tuple, types=None) -> list:
    out = []
    for j, v in enumerate(row):
        t = types[j] if types and j < len(types) else ""
        if v is None:
            out.append(None)
        elif isinstance(v, int) and not isinstance(v, bool) and t:
            m = _DECIMAL_RE.match(t)
            if m:
                out.append(_render_decimal(v, int(m.group(2))))
            elif t == "date":
                import datetime

                out.append(str(
                    datetime.date(1970, 1, 1)
                    + datetime.timedelta(days=v)
                ))
            elif t == "timestamp":
                import datetime

                out.append(
                    (datetime.datetime(1970, 1, 1)
                     + datetime.timedelta(microseconds=v)
                     ).isoformat(sep=" ")
                )
            else:
                out.append(v)
        elif isinstance(v, (bool, int, float, str)):
            out.append(v)
        elif isinstance(v, (tuple, list)):
            if t.startswith("map("):
                # map values serialize as JSON objects (reference:
                # protocol renders MAP as {key: value})
                out.append({str(k): mv for k, mv in v})
            else:
                out.append(_json_value(v))
        else:
            out.append(str(v))
    return out


def _json_value(v):
    if isinstance(v, (tuple, list)):
        return [_json_value(x) for x in v]
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


def _session_snapshot(session: Session) -> Dict:
    """JSON-safe session state for the checkpoint journal: user/
    catalog/schema plus the EXPLICITLY set properties (typed values
    are already JSON-shaped) — enough to reconstruct an equivalent
    Session on a restarted coordinator."""
    return {
        "user": session.user,
        "catalog": session.catalog,
        "schema": session.schema,
        "values": {
            k: v for k, v in session._values.items()
            if v is None or isinstance(v, (bool, int, float, str))
        },
    }


class _DcnServerRunner:
    """The serial path's runner when a worker fleet is configured
    (ISSUE 20): plain queries dispatch through the DcnRunner (stage
    DAG / legacy cuts / local fallback), everything else — SET, DDL,
    SHOW, EXPLAIN, prepared statements — runs on the local engine
    directly. The DCN coordinator's final stage executes on the SAME
    bootstrap runner/executor, so sessions, traces and counters are
    one surface either way."""

    def __init__(self, dcn, local):
        self._dcn = dcn
        self._local = local

    @property
    def session(self):
        return self._local.session

    @property
    def executor(self):
        return self._local.executor

    @property
    def last_trace(self):
        return getattr(self._local, "last_trace", None)

    def execute(self, sql: str):
        from presto_tpu.runner import QueryResult
        from presto_tpu.sql import ast_nodes as N
        from presto_tpu.sql.parser import parse

        try:
            stmt = parse(sql)
        except Exception:  # noqa: BLE001 - not dispatchable: the
            stmt = None    # local path raises the proper error body
        if isinstance(stmt, N.Query):
            rows = self._dcn.execute(sql)
            return QueryResult(
                column_names=self._dcn.last_output_names or [],
                rows=rows,
            )
        return self._local.execute(sql)


class _Handler(BaseHTTPRequestHandler):
    server_version = "presto-tpu/0.2"
    protocol_version = "HTTP/1.1"

    # silence default stderr logging
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    @property
    def app(self) -> "PrestoTpuServer":
        return self.server.app  # type: ignore[attr-defined]

    def _send_json(self, obj, status=200, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _session_from_headers(self) -> Session:
        props = {}
        user = self.headers.get("X-Presto-User", "presto")
        access = self.app._runner.access_control
        hdr = self.headers.get("X-Presto-Session", "")
        for part in hdr.split(","):
            part = part.strip()
            if part and "=" in part:
                k, v = part.split("=", 1)
                if k.strip() in SYSTEM_SESSION_PROPERTIES:
                    # header overrides pass the same choke point as
                    # SET SESSION statements (reference:
                    # checkCanSetSystemSessionProperty runs for header-
                    # carried properties too)
                    access.check_can_set_session(user, k.strip())
                    props[k.strip()] = v.strip()
        return Session(
            user=user,
            catalog=self.headers.get("X-Presto-Catalog"),
            schema=self.headers.get("X-Presto-Schema", "default"),
            properties=props,
        )

    def _maybe_task_plane(self, method: str) -> bool:
        """Serve /v1/task* and /v1/fault from the embedded task
        runtime (coordinator+worker single process). Returns True when
        the request was handled."""
        rt = self.app.task_runtime
        if rt is None:
            return False
        split = urlparse(self.path)
        if not (split.path.startswith("/v1/task")
                or split.path.startswith("/v1/fault")):
            return False
        from presto_tpu.server import worker as W

        if method == "POST":
            n = int(self.headers.get("Content-Length", "0"))
            resp = W.route_task_post(rt, split.path,
                                     self.rfile.read(n) or b"{}")
        elif method == "GET":
            resp = W.route_task_get(rt, split.path, split.query)
        else:
            resp = W.route_task_delete(rt, split.path)
        if resp is None:
            return False
        W.write_task_response(self, resp)
        return True

    def do_POST(self):
        path = urlparse(self.path).path
        if self._maybe_task_plane("POST"):
            return
        if path != "/v1/statement":
            self._send_json({"error": "not found"}, 404)
            return
        length = int(self.headers.get("Content-Length", 0))
        sql = self.rfile.read(length).decode()
        from presto_tpu.server.resource_groups import QueryQueueFullError

        from presto_tpu.security import AccessDeniedError

        try:
            session = self._session_from_headers()
            # tailing-cursor mode (ISSUE 14): the stream_tail_enabled
            # session property (set per request via X-Presto-Session —
            # the protocol's per-request flag — or via SET SESSION)
            # turns a query over an append-only stream table into a
            # never-finishing cursor; non-tailable statements fall
            # through to the normal submit path
            if bool(session.get("stream_tail_enabled")):
                q = self.app.start_tail(sql, session)
                if q is not None:
                    self._send_json(self._tail_results(q, 0))
                    return
            q = self.app.manager.submit(sql, session)
        except QueryQueueFullError as e:
            self._send_json({
                "error": {"message": str(e),
                          "errorName": "QUERY_QUEUE_FULL"},
                "stats": {"state": "FAILED"},
            }, 429)
            return
        except AccessDeniedError as e:
            self._send_json({
                "error": {"message": str(e),
                          "errorName": "PERMISSION_DENIED"},
                "stats": {"state": "FAILED"},
            }, 403)
            return
        # brief wait so fast statements (SET SESSION, DDL) answer in one
        # round trip with their headers (reference: ~100ms initial wait)
        q.done.wait(timeout=0.5)
        headers = {}
        for k, v in q.set_session.items():
            headers["X-Presto-Set-Session"] = f"{k}={v}"
        self._send_json(self._results(q, 0), headers=headers)

    def do_GET(self):
        path = urlparse(self.path).path
        if self._maybe_task_plane("GET"):
            return
        parts = [p for p in path.split("/") if p]
        if parts[:2] == ["v1", "statement"] and len(parts) == 4:
            q = self.app.manager.get(parts[2])
            if q is None:
                self._send_json({"error": "no such query"}, 404)
                return
            token = int(parts[3])
            if q.tail is not None:
                # tailing cursor: the poll IS the long-poll (it waits
                # on the append log, not on query completion)
                self._send_json(self._tail_results(q, token))
                return
            # long-poll up to ~1s for progress (reference client behavior)
            q.done.wait(timeout=1.0)
            headers = {}
            for k, v in q.set_session.items():
                headers["X-Presto-Set-Session"] = f"{k}={v}"
            self._send_json(self._results(q, token), headers=headers)
            return
        if parts == ["v1", "query"]:
            # reference: /v1/query lists every tracked query's
            # BasicQueryInfo (live + finished)
            mgr = self.app.manager
            with mgr._lock:
                qs = list(mgr._queries.values())
            self._send_json([
                q.info() for q in sorted(qs, key=lambda x: x.id)
            ])
            return
        if parts[:2] == ["v1", "query"] and len(parts) == 3:
            # the full QueryInfo/StageInfo/TaskInfo tree, served LIVE
            # mid-query from the active trace (obs/trace.to_info)
            info = self.app.manager.query_info(parts[2])
            if info is None:
                self._send_json({"error": "no such query"}, 404)
                return
            self._send_json(info)
            return
        if parts == ["v1", "info"] or parts == ["v1", "status"]:
            info = {
                "nodeId": "presto-tpu-coordinator",
                "coordinator": True,
                "uptime": time.time() - self.app.started,
                "backend": self.app.backend_name,
            }
            from presto_tpu.obs import sanitizer as SAN

            if SAN.is_armed():
                # sanitized chaos runs poll the coordinator subprocess
                # the same way they poll workers (worker.py /v1/info)
                info["sanitizerViolations"] = SAN.violation_count()
            self._send_json(info)
            return
        if parts == ["v1", "resourceGroup"]:
            rg = self.app.manager.resource_groups
            self._send_json(rg.snapshot() if rg else [])
            return
        if parts == ["v1", "node"]:
            # reference: /v1/node lists cluster members with health
            # (DiscoveryNodeManager + HeartbeatFailureDetector view)
            det = self.app.failure_detector
            self._send_json(det.snapshot() if det else [])
            return
        if parts == ["metrics"]:
            body = self.app.manager.metrics_text(
                time.time() - self.app.started,
                executor=self.app._runner.executor,
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self._send_json({"error": "not found"}, 404)

    def do_DELETE(self):
        if self._maybe_task_plane("DELETE"):
            return
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if parts[:2] == ["v1", "statement"] and len(parts) >= 3:
            q = self.app.manager.get(parts[2])
            ok = self.app.manager.cancel(parts[2])
            if q is not None and q.tail is not None:
                # stop tailing: wake blocked pollers, final GET then
                # serves the remaining rows with no nextUri
                q.tail.close()
            self._send_json({"cancelled": ok})
            return
        self._send_json({"error": "not found"}, 404)

    # --------------------------------------------------------- protocol
    def _tail_results(self, q: _Query, token: int) -> Dict:
        """Protocol page for a tailing cursor (ISSUE 14): a fresh
        token first POLLS (long-polling the append log up to
        stream_poll_ms), then serves the rows the poll derived from
        new offsets; known tokens re-serve their recorded span.
        nextUri persists until the cursor is cancelled/closed — empty
        pages with a fresh nextUri are the idle-tail heartbeat."""
        cur = q.tail
        base = f"http://{self.headers.get('Host', 'localhost')}"
        out: Dict = {
            "id": q.id,
            "infoUri": f"{base}/v1/query/{q.id}",
            "stats": {
                "state": q.state,
                "queued": False,
                "elapsedTimeMillis": q.info()["elapsedTimeMillis"],
                "tail": True,
            },
        }
        fresh = token >= cur.spans_served()
        if fresh and not q.cancelled and not cur.closed:
            cur.poll(cur.poll_ms / 1000.0)
        if cur.error is not None:
            q.error = cur.error
            q.state = "FAILED"
            q._finish_clock()
            q.done.set()
            out["stats"]["state"] = "FAILED"
            out["error"] = cur.error
            return out
        chunk = cur.take_span(token)
        if chunk is None:
            out["error"] = {
                "message": f"unknown result token {token}",
                "errorName": "INVALID_TOKEN",
            }
            return out
        if cur.columns is not None:
            out["columns"] = cur.columns
        if chunk:
            out["data"] = chunk
        done = (q.cancelled or cur.closed) and \
            token + 1 >= cur.spans_served()
        if done:
            out["stats"]["state"] = q.state
        else:
            out["nextUri"] = f"{base}/v1/statement/{q.id}/{token + 1}"
        return out

    def _results(self, q: _Query, token: int) -> Dict:
        base = f"http://{self.headers.get('Host', 'localhost')}"
        out: Dict = {
            "id": q.id,
            "infoUri": f"{base}/v1/query/{q.id}",
            "stats": {
                "state": q.state,
                "queued": q.state == "QUEUED",
                "elapsedTimeMillis": q.info()["elapsedTimeMillis"],
            },
        }
        if q.error is not None:
            out["error"] = q.error
            return out
        if not q.done.is_set():
            # still running: client polls the same token
            out["nextUri"] = f"{base}/v1/statement/{q.id}/{token}"
            return out
        if q.columns is not None:
            out["columns"] = q.columns
        if q.update_type:
            out["updateType"] = q.update_type
        lo = token * _PAGE_ROWS
        hi = lo + _PAGE_ROWS
        chunk = q.rows[lo:hi]
        if chunk:
            out["data"] = chunk
        ckpt = q.checkpoint
        if hi < len(q.rows):
            out["nextUri"] = f"{base}/v1/statement/{q.id}/{token + 1}"
            if ckpt is not None:
                # protocol-token barrier (ISSUE 20): this page is now
                # in the client's hands — a restarted coordinator must
                # resume the stream AT token+1 with this page's digest
                # verified against the regenerated rows
                from presto_tpu.dist.checkpoint import page_digest

                ckpt.note_client_token(token + 1, page_digest(chunk))
        elif ckpt is not None:
            # stream fully delivered: nothing left to recover
            ckpt.delivered()
        return out


class PrestoTpuServer:
    """Reference: server/PrestoServer.java + StatementResource wiring."""

    def __init__(
        self,
        catalogs,
        default_catalog: str = "tpch",
        port: int = 8080,
        mesh=None,
        page_rows: int = 1 << 18,
        event_listeners=(),
        peer_uris=(),
        plugins=(),
        resource_groups=None,
        memory_budget_bytes: Optional[int] = None,
        session_defaults=None,
        worker_tasks: bool = False,
        worker_uris=(),
        checkpoint_dir: str = "",
    ):
        from presto_tpu.runner import LocalRunner

        event_listeners = list(event_listeners)
        for p in plugins:
            event_listeners.extend(p.event_listeners())
        self.catalogs = catalogs
        self.port = port
        self.started = time.time()
        # peer health monitoring (reference: HeartbeatFailureDetector
        # over discovered nodes; ours watches configured peer slices)
        self.failure_detector = None
        if peer_uris:
            from presto_tpu.server.heartbeat import (
                HeartbeatFailureDetector,
            )

            self.failure_detector = HeartbeatFailureDetector(
                list(peer_uris)
            )
        try:
            import jax

            self.backend_name = jax.default_backend()
        except Exception:  # noqa: BLE001 - /v1/info stays serveable
            self.backend_name = "unknown"  # without a jax runtime

        # bootstrap runner installs plugins into catalogs/registries;
        # it also serves the serial (no-arbiter) path
        self._runner = LocalRunner(
            catalogs, default_catalog=default_catalog,
            page_rows=page_rows, mesh=mesh, plugins=plugins,
        )
        self.catalogs = self._runner.catalogs  # incl. plugin catalogs
        # compiled kernels shared across per-query executors (the
        # compiled-expression LRU is process-wide in the reference too)
        self._shared_jit_cache = self._runner.executor._jit_cache
        self._mesh = mesh
        self._page_rows = page_rows
        self._default_catalog = default_catalog

        # distributed dispatch plane (ISSUE 20): a configured worker
        # fleet makes this server a DCN coordinator — plain queries on
        # the serial path execute through DcnRunner (stage DAG, legacy
        # cuts, local fallback), with the coordinator-side final stage
        # running on THE bootstrap runner/executor so sessions, traces
        # and every dist counter surface on /metrics + system.metrics
        self._dcn = None
        if worker_uris:
            from presto_tpu.dist.dcn import DcnRunner

            self._dcn = DcnRunner(
                self.catalogs, list(worker_uris),
                default_catalog=default_catalog,
                page_rows=page_rows,
            )
            self._dcn.runner = self._runner
        # durable coordinator journal (ISSUE 20 tentpole): configured
        # via the checkpoint.dir etc key / this kwarg; a bare
        # checkpoint_dir SESSION property instead binds lazily in the
        # manager at first enabled submit
        self._journal = None
        if checkpoint_dir:
            from presto_tpu.dist.checkpoint import CheckpointJournal

            self._journal = CheckpointJournal(
                checkpoint_dir, counter_ex=self._runner.executor)

        memory_arbiter = None
        # cross-query launch batching (ISSUE 17): ONE shared batch
        # point for the concurrent path's per-query executors —
        # attachment is what "auto" resolves against, so the serial
        # path and raw Executors never batch
        self._launch_batcher = None
        if memory_budget_bytes:
            memory_arbiter = MemoryArbiter(memory_budget_bytes)
            from presto_tpu.server.launch_batcher import LaunchBatcher

            self._launch_batcher = LaunchBatcher()

        # fail-fast validation: a bad deployment default (unknown name,
        # rejected value) must abort startup, not fail every query.
        # Kept introspectable (tests/test_config_etc.py verifies the
        # etc-registry plumbing against it; SHOW-style tooling can too)
        self.session_defaults = dict(session_defaults or {})
        if session_defaults:
            Session(properties=session_defaults)

        def runner_factory(session: Session):
            # deployment-tier session defaults (etc/config.properties,
            # see config.server_from_etc): seed properties the client
            # session did not explicitly set — an explicit
            # X-Presto-Session header or SET SESSION always wins.
            # Seeded values read as set() for this query's session (a
            # deployment default behaves like a header-supplied
            # property); they re-seed on every query, so there is no
            # cross-query unset() path back to the code default.
            for k, v in (session_defaults or {}).items():
                if not session.is_set(k):
                    session.set(k, v)
            # the server traces queries by default (ISSUE 9): the
            # /v1/query/{id} tree, system.runtime_tasks, and the
            # stage-wall histogram all read the lifecycle trace. An
            # explicit client/deployment off always wins.
            if not session.is_set("query_trace_enabled"):
                session.set("query_trace_enabled", True)
            if memory_arbiter is None:
                # serial path: one engine, re-sessioned per query;
                # with a worker fleet, plain queries route through the
                # DCN dispatch plane on that same engine
                self._runner.session = session
                if self._dcn is not None:
                    return _DcnServerRunner(self._dcn, self._runner)
                return self._runner
            # the concurrent server defaults the result cache ON
            # (ISSUE 17): the process-shared store is what collapses
            # repeated dashboard statements across per-query runners,
            # and cache-aware admission needs hits to exist to bypass
            # the queue. Raw Executor / serial-path / library defaults
            # stay off; an explicit client/deployment off wins.
            if not session.is_set("result_cache_enabled"):
                session.set("result_cache_enabled", True)
            # concurrent path: per-query runner/executor so query state
            # (overflow flags, capacity boosts, stream caches) never
            # crosses queries; compiled kernels and views are server-
            # wide (reference: views live in connector metadata); the
            # prepared registry is shared but keyed per user inside
            # LocalRunner, mirroring the reference's session scoping
            r = LocalRunner(
                self.catalogs, default_catalog=self._default_catalog,
                page_rows=self._page_rows, mesh=self._mesh,
                session=session,
            )
            r.executor._jit_cache = self._shared_jit_cache
            # every per-query executor shares THE batch point: a
            # compatible launch from any of them can lead or join a
            # gather group (runner.apply_session resolves the
            # session's cross_query_batching against this attachment)
            r.executor.launch_batcher = self._launch_batcher
            r.views = self._runner.views
            r.prepared = self._runner.prepared
            r.access_control = self._runner.access_control
            return r

        self.manager = QueryManager(
            runner_factory,
            listeners=event_listeners,
            resource_groups=resource_groups,
            memory_arbiter=memory_arbiter,
            # swallowed listener exceptions land on the bootstrap
            # executor's listener_errors registry counter
            listener_error_counter=(
                self._runner.executor.count_listener_error),
            journal=self._journal,
            counter_executor=self._runner.executor,
            dcn=self._dcn,
        )
        if self._launch_batcher is not None:
            # gather only when there is someone to gang with: a lone
            # client on the concurrent path must never pay the window
            mgr = self.manager

            def _running_queries() -> int:
                with mgr._lock:
                    return sum(1 for q in mgr._queries.values()
                               if q.state == "RUNNING")

            self._launch_batcher.concurrency_probe = _running_queries
        # coordinator+worker single process (reference: a node that is
        # both coordinator and worker): an embedded task runtime makes
        # this server a full DCN peer — it serves the /v1/task control
        # plane and the spooled-exchange fetch/ack data plane
        # (server/worker.route_task_*), so a DcnRunner or stage-DAG
        # scheduler can pool it like any worker
        self.task_runtime = None
        if worker_tasks:
            from presto_tpu.server.worker import TaskRuntime

            self.task_runtime = TaskRuntime(
                self.catalogs, node_id="coordinator-worker",
                default_catalog=default_catalog, page_rows=page_rows,
            )
        self._install_runtime_tables()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # crash re-attach (ISSUE 20): pick up every query a previous
        # coordinator process journaled but never delivered. Claimed
        # once per journal+process so a double-constructed server
        # can't run the pass twice.
        if self._journal is not None and self._journal.claim_reattach():
            self._reattach_pending()

    def _reattach_pending(self) -> None:
        """Register a _Query stub (under its ORIGINAL id — the
        client's persisted nextUri names it) for every journaled
        in-flight query and recover each on a daemon thread through
        dist.checkpoint.reattach_query: surviving producer spools
        resume, dead placements re-dispatch from persisted payloads,
        anything non-recoverable fails loudly with
        CoordinatorRestarted — never a hang."""
        pending = self._journal.pending()
        if not pending:
            return
        mgr = self.manager
        for qid in sorted(pending):
            rec = pending[qid]
            sess = rec.get("session") or {}
            try:
                session = Session(
                    user=sess.get("user", "presto"),
                    catalog=(sess.get("catalog")
                             or self._default_catalog),
                    schema=sess.get("schema", "default"),
                    properties=sess.get("values") or None,
                )
            except Exception:  # noqa: BLE001 - version skew on a
                # persisted property must not kill the whole pass:
                # recover the query under a default session instead
                session = Session(catalog=self._default_catalog)
            from presto_tpu.dist.checkpoint import QueryCheckpoint

            q = _Query(qid, rec.get("sql") or "", session)
            q.state = "RUNNING"
            q.checkpoint = QueryCheckpoint(self._journal, qid)
            with mgr._lock:
                mgr._queries[qid] = q
            threading.Thread(
                target=self._reattach_run, args=(q, rec), daemon=True
            ).start()

    def _reattach_run(self, q: _Query, rec: Dict) -> None:
        from presto_tpu.dist import checkpoint as CKPT

        ckpt = q.checkpoint
        try:
            if rec.get("state") == "failed":
                # the query had already failed: resurface the SAME
                # error body at the client's persisted nextUri
                q.error = rec.get("error") or {
                    "message": "query failed before the restart",
                    "errorName": "QueryFailed",
                }
                q.state = "FAILED"
                return
            # serialize against live queries: the recovery re-executes
            # on the shared serial engine
            with self.manager._exec_lock:
                self._runner.session = q.session
                res = CKPT.reattach_query(
                    rec, self._dcn, self._runner.executor)
            cols = rec.get("columns")
            if not cols:
                cols = [{"name": n, "type": "unknown"}
                        for n in res.column_names]
            types = [c["type"] for c in cols]
            rows = [_json_row(r, types) for r in res.rows]
            # verify every page the OLD process already handed the
            # client against the regenerated rows — the stream only
            # resumes when the delivered prefix is byte-identical
            page_sha = rec.get("page_sha") or {}
            for i in range(int(rec.get("token") or 0)):
                want = page_sha.get(str(i))
                got = CKPT.page_digest(
                    rows[i * _PAGE_ROWS:(i + 1) * _PAGE_ROWS])
                if want is not None and got != want:
                    raise CKPT.CoordinatorRestarted(
                        f"resumed result stream diverges at page {i}"
                        " (digest mismatch with the delivered prefix)"
                    )
            q.columns = cols
            q.rows = rows
            q.state = "FINISHED"
            if ckpt is not None:
                ckpt.finished(cols, len(rows))
        except Exception as e:  # noqa: BLE001 - the loud-fail leg of
            # the recovery contract: any non-recoverable state becomes
            # a FAILED query at the client's nextUri, never a hang
            q.error = {
                "message": str(e)[:2000],
                "errorName": ("CoordinatorRestarted"
                              if isinstance(e, CKPT.CoordinatorRestarted)
                              else type(e).__name__),
            }
            q.state = "FAILED"
            if ckpt is not None:
                ckpt.failed(str(e), q.error["errorName"])
        finally:
            q._finish_clock()
            q.done.set()
            self.manager._record_completion(q)

    def _install_runtime_tables(self) -> None:
        """system.runtime_queries / nodes / metrics over live server
        state (reference: system.runtime.* tables + the jmx connector's
        SQL-over-metrics)."""
        sys_conn = self.catalogs.get("system")
        if sys_conn is None or not hasattr(sys_conn, "register"):
            return
        V, B = T.VARCHAR, T.BIGINT
        mgr = self.manager

        def runtime_queries():
            out = []
            with mgr._lock:
                queries = list(mgr._queries.values())
            for q in queries:
                info = q.info()
                out.append((
                    q.id, q.state, q.session.user, q.sql,
                    info["elapsedTimeMillis"], len(q.rows),
                ))
            return sorted(out)

        def nodes():
            me = (f"http://127.0.0.1:{self.port}", "active", 1)
            peers = []
            fd = self.failure_detector
            if fd is not None:
                for info in fd.snapshot():
                    # one vocabulary with the coordinator row:
                    # active / failed
                    alive = info.get("state") == "ALIVE"
                    peers.append((
                        info.get("uri"),
                        "active" if alive else "failed",
                        0,
                    ))
            return [me] + sorted(peers)

        def metrics():
            with mgr._lock:
                out = [
                    ("rows_returned_total", mgr.rows_returned_total),
                    ("query_wall_ms_total", mgr.query_wall_ms_total),
                ]
                by_state = dict(mgr.completed_by_state)
            for state, n in sorted(by_state.items()):
                out.append((f"queries_completed_{state.lower()}", n))
            # device-memory governor (exec/membudget.py): the serial
            # runner's resolved budget and last-attempt peak — the
            # fleet-visible half of the peak_device_bytes contract
            ex = self._runner.executor
            out.append(("device_memory_budget_bytes", ex._budget()))
            out.append(("peak_device_bytes", ex.peak_memory_bytes))
            # every declared execution counter (exec/counters.py),
            # queryable with SQL like every other engine metric — the
            # same registry /metrics and EXPLAIN ANALYZE render, so
            # the three surfaces cannot drift
            from presto_tpu.exec import counters as CTRS

            snap = CTRS.snapshot(ex)
            # same process-shared overlay as /metrics (see
            # _result_cache_totals): one truth on both surfaces
            snap.update(_result_cache_totals())
            xf = _xfer_totals()
            snap.update({k: int(v) for k, v in xf.items()
                         if k in CTRS.QUERY_COUNTERS})
            snap.update({k: int(v) for k, v in _wire_totals().items()
                         if k in CTRS.QUERY_COUNTERS})
            # launch/batch totals: same overlay as /metrics (see
            # QueryManager.metrics_text) so the two surfaces agree
            with mgr._lock:
                for name in mgr._EXEC_TOTAL_SUMS:
                    snap[name] = snap.get(name, 0) + \
                        mgr.exec_counter_totals.get(name, 0)
                for name in mgr._EXEC_TOTAL_MAX:
                    snap[name] = max(
                        snap.get(name, 0),
                        mgr.exec_counter_totals.get(name, 0))
                bypasses = mgr.cache_admission_bypasses
                peak_q = mgr.peak_queued
            out.extend(sorted(snap.items()))
            # the float crossing wall rides as integer milliseconds
            # (system.metrics values are BIGINT)
            out.append(("transfer_wall_ms",
                        int(xf["transfer_wall_s"] * 1000)))
            out.append(("admission_cache_bypasses", bypasses))
            out.append(("peak_queued", peak_q))
            return out

        def runtime_tasks():
            # the task-level runtime table (reference:
            # system.runtime.tasks): one row per stage task from the
            # SAME QueryInfo tree /v1/query/{id} serves, so the two
            # surfaces cannot disagree
            with mgr._lock:
                qids = list(mgr._queries)
            out = []
            for qid in qids:
                info = mgr.query_info(qid)
                if not info:
                    continue
                for stage in info.get("stages", ()):
                    for t in stage["tasks"]:
                        out.append((
                            qid, str(stage["stageId"]), t["taskId"],
                            t["state"], t.get("uri") or "",
                            int(t["wallMs"]),
                            int(t.get("rows") or 0),
                            int(t.get("retries") or 0),
                        ))
            return sorted(out)

        sys_conn.register(
            "runtime_queries",
            [("query_id", V), ("state", V), ("user", V), ("query", V),
             ("elapsed_ms", B), ("result_rows", B)],
            runtime_queries,
        )
        sys_conn.register(
            "runtime_tasks",
            [("query_id", V), ("stage_id", V), ("task_id", V),
             ("state", V), ("uri", V), ("wall_ms", B), ("rows", B),
             ("retries", B)],
            runtime_tasks,
        )
        sys_conn.register(
            "nodes",
            [("uri", V), ("state", V), ("is_coordinator", B)], nodes,
        )
        sys_conn.register(
            "metrics", [("name", V), ("value", B)], metrics,
        )

    def start_tail(self, sql: str,
                   session: Session) -> Optional[_Query]:
        """Register a tailing cursor for ``sql`` when it is tailable
        (ISSUE 14): a plain query, local engine, scanning at least
        one append-only stream table. None otherwise — the statement
        then runs the normal protocol path (which also surfaces its
        parse/plan/access errors with the ordinary error body).
        Tailing statements pass the SAME resource-group queue gate as
        submitted ones (QueryQueueFullError surfaces as 429); the
        slot releases when the cursor closes."""
        rg = self.manager.resource_groups
        group = rg.admit(session.user) if rg is not None else None
        cursor = self.make_tail_cursor(sql, session)
        if cursor is None:
            if group is not None:
                rg.cancel_queued(group)
            return None
        with cursor._cv:
            cursor.resource_group = group
        cursor._rg_manager = rg
        return self.manager.register_tail(sql, session, cursor)

    def make_tail_cursor(self, sql: str,
                         session: Session) -> Optional[TailCursor]:
        if self._mesh is not None:
            return None  # tail cursors ride the local executor
        # cheap pre-check before ANY planning work: a deployment with
        # no append-only catalog can never tail — a session that left
        # stream_tail_enabled on must not pay a throwaway runner and
        # a second planning pass per ordinary statement
        if not any(getattr(c, "append_only", False)
                   for c in self.catalogs.values()):
            return None
        from presto_tpu.runner import LocalRunner
        from presto_tpu.sql import ast_nodes as N
        from presto_tpu.sql.parser import parse

        try:
            stmt = parse(sql)
        except Exception:  # noqa: BLE001 - not tailable; the normal
            return None    # path surfaces the parse error properly
        if not isinstance(stmt, N.Query):
            return None  # DDL/SET/EXPLAIN/... never tail
        # dedicated runner (the concurrent-path shape): cursor polls
        # run on protocol handler threads and must never race the
        # serial bootstrap runner's queries
        r = LocalRunner(
            self.catalogs, default_catalog=self._default_catalog,
            page_rows=self._page_rows, session=session,
        )
        r.executor._jit_cache = self._shared_jit_cache
        r.views = self._runner.views
        r.prepared = self._runner.prepared
        r.access_control = self._runner.access_control
        try:
            r.access_control.check_can_execute_query(
                session.user, sql)
            r.apply_session()
            plan = r._plan_statement_query(stmt)
        except Exception:  # noqa: BLE001 - not tailable; the normal
            return None    # path surfaces plan/access errors properly
        from presto_tpu.cache.rules import scan_tables

        streams = [
            (c, t) for c, t in sorted(scan_tables(plan))
            if getattr(r.catalogs.get(c), "append_only", False)
        ]
        if not streams:
            return None  # nothing appends: a plain finite statement
        return TailCursor(r, plan, streams,
                          sink=self._runner.executor)

    def start(self) -> int:
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), _Handler)
        self._httpd.app = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        if self.failure_detector:
            self.failure_detector.start()
        if self.task_runtime is not None:
            # coordinator+worker single process: register the embedded
            # runtime so same-process consumers and the stage-DAG root
            # drain take its spooled Pages directly (mesh-local
            # exchange fast path, server/worker registry)
            from presto_tpu.server.worker import register_local_runtime

            register_local_runtime(
                f"http://127.0.0.1:{self.port}", self.task_runtime)
        return self.port

    def stop(self) -> None:
        if self.task_runtime is not None:
            from presto_tpu.server.worker import (
                unregister_local_runtime,
            )

            unregister_local_runtime(f"http://127.0.0.1:{self.port}")
        if self.failure_detector:
            self.failure_detector.stop()
        if self._dcn is not None:
            self._dcn.close()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    def serve_forever(self) -> None:  # pragma: no cover - CLI entry
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            self.stop()


def main() -> int:  # pragma: no cover - subprocess entry
    """Coordinator subprocess entry (the kill-coordinator chaos mode's
    victim): boots a PrestoTpuServer over a configured worker fleet
    with a durable checkpoint journal, prints its port as one JSON
    line, then serves until killed — the harness SIGKILLs this process
    mid-query and boots a successor on the same --checkpoint-dir."""
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--suite", default="tpch")
    parser.add_argument("--scale", type=float, default=0.01)
    parser.add_argument("--page-rows", type=int, default=1 << 16)
    parser.add_argument("--workers", default="",
                        help="comma-separated worker base uris")
    parser.add_argument("--checkpoint-dir", default="")
    args = parser.parse_args()

    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.connectors.tpch import TpchConnector

    cls = TpchConnector if args.suite == "tpch" else TpcdsConnector
    srv = PrestoTpuServer(
        {args.suite: cls(scale=args.scale)}, port=args.port,
        default_catalog=args.suite, page_rows=args.page_rows,
        worker_uris=[u for u in args.workers.split(",") if u],
        checkpoint_dir=args.checkpoint_dir,
    )
    port = srv.start()
    print(json.dumps({"port": port}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
