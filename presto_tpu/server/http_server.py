"""Coordinator HTTP service speaking the Presto client protocol.

Reference: presto-main server/protocol/StatementResource.java (the
/v1/statement paged REST protocol: POST the SQL, follow nextUri until it
disappears, token-addressed result pages, DELETE to cancel) plus
server/PrestoServer bootstrap. Sessions are client-carried exactly like
the reference: X-Presto-Session request headers hold property overrides,
SET SESSION responds with X-Presto-Set-Session and the client echoes it
back on later requests — the server itself stays stateless per query.

The engine is the in-process LocalRunner (single- or mesh-distributed);
queries execute on a worker thread under a global lock (one query on the
device at a time) while the protocol surface stays responsive.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse

from presto_tpu import types as T
from presto_tpu.session import SYSTEM_SESSION_PROPERTIES, Session

_PAGE_ROWS = 4096  # rows per protocol fetch (client paging granularity)


class _Query:
    """Reference: server/protocol/Query.java — one statement's life."""

    def __init__(self, qid: str, sql: str, session: Session):
        self.id = qid
        self.sql = sql
        self.session = session
        self.state = "QUEUED"
        self.columns: Optional[List[Dict]] = None
        self.rows: List[tuple] = []
        self.error: Optional[Dict] = None
        self.update_type: Optional[str] = None
        self.set_session: Dict[str, str] = {}
        self.created = time.time()
        self.finished_at: Optional[float] = None
        self.cancelled = False
        self.done = threading.Event()

    def info(self) -> Dict:
        return {
            "queryId": self.id,
            "state": self.state,
            "query": self.sql,
            "elapsedTimeMillis": int(
                ((self.finished_at or time.time()) - self.created) * 1000
            ),
            "error": self.error,
            "rowCount": len(self.rows),
        }


class QueryManager:
    """Reference: execution/SqlQueryManager.java — registry + lifecycle
    (QUEUED -> RUNNING -> FINISHED/FAILED/CANCELED)."""

    def __init__(self, runner_factory):
        self._runner_factory = runner_factory
        self._queries: Dict[str, _Query] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._exec_lock = threading.Lock()  # one query on the device

    def submit(self, sql: str, session: Session) -> _Query:
        with self._lock:
            self._seq += 1
            qid = time.strftime("%Y%m%d_%H%M%S") + \
                f"_{self._seq:05d}_{uuid.uuid4().hex[:5]}"
            q = _Query(qid, sql, session)
            self._queries[qid] = q
        threading.Thread(
            target=self._run, args=(q,), daemon=True
        ).start()
        return q

    def get(self, qid: str) -> Optional[_Query]:
        return self._queries.get(qid)

    def cancel(self, qid: str) -> bool:
        q = self._queries.get(qid)
        if q is None:
            return False
        q.cancelled = True
        if not q.done.is_set():
            q.state = "CANCELED"
            q.finished_at = time.time()
            q.done.set()
        return True

    def _run(self, q: _Query) -> None:
        with self._exec_lock:
            if q.cancelled:
                return
            q.state = "RUNNING"
            try:
                runner = self._runner_factory(q.session)
                result = runner.execute(q.sql)
                types = result.column_types or [
                    "unknown" for _ in result.column_names
                ]
                q.columns = [
                    {"name": n, "type": t}
                    for n, t in zip(result.column_names, types)
                ]
                q.rows = [_json_row(r) for r in result.rows]
                q.update_type = result.update_type
                if result.update_type == "SET SESSION":
                    # surface the new value so clients echo it back
                    # (X-Presto-Set-Session round trip)
                    from presto_tpu.sql.parser import parse
                    from presto_tpu.sql import ast_nodes as N

                    stmt = parse(q.sql)
                    if isinstance(stmt, N.SetSession):
                        q.set_session[stmt.name] = str(stmt.value)
                if not q.cancelled:
                    q.state = "FINISHED"
            except Exception as e:  # noqa: BLE001
                if not q.cancelled:
                    q.error = {
                        "message": str(e)[:2000],
                        "errorName": type(e).__name__,
                    }
                    q.state = "FAILED"
            finally:
                if q.finished_at is None:
                    q.finished_at = time.time()
                q.done.set()


def _json_row(row: tuple) -> list:
    out = []
    for v in row:
        if v is None or isinstance(v, (bool, int, float, str)):
            out.append(v)
        else:
            out.append(str(v))
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "presto-tpu/0.2"
    protocol_version = "HTTP/1.1"

    # silence default stderr logging
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    @property
    def app(self) -> "PrestoTpuServer":
        return self.server.app  # type: ignore[attr-defined]

    def _send_json(self, obj, status=200, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _session_from_headers(self) -> Session:
        props = {}
        hdr = self.headers.get("X-Presto-Session", "")
        for part in hdr.split(","):
            part = part.strip()
            if part and "=" in part:
                k, v = part.split("=", 1)
                if k.strip() in SYSTEM_SESSION_PROPERTIES:
                    props[k.strip()] = v.strip()
        return Session(
            user=self.headers.get("X-Presto-User", "presto"),
            catalog=self.headers.get("X-Presto-Catalog"),
            schema=self.headers.get("X-Presto-Schema", "default"),
            properties=props,
        )

    def do_POST(self):
        path = urlparse(self.path).path
        if path != "/v1/statement":
            self._send_json({"error": "not found"}, 404)
            return
        length = int(self.headers.get("Content-Length", 0))
        sql = self.rfile.read(length).decode()
        q = self.app.manager.submit(sql, self._session_from_headers())
        # brief wait so fast statements (SET SESSION, DDL) answer in one
        # round trip with their headers (reference: ~100ms initial wait)
        q.done.wait(timeout=0.5)
        headers = {}
        for k, v in q.set_session.items():
            headers["X-Presto-Set-Session"] = f"{k}={v}"
        self._send_json(self._results(q, 0), headers=headers)

    def do_GET(self):
        path = urlparse(self.path).path
        parts = [p for p in path.split("/") if p]
        if parts[:2] == ["v1", "statement"] and len(parts) == 4:
            q = self.app.manager.get(parts[2])
            if q is None:
                self._send_json({"error": "no such query"}, 404)
                return
            token = int(parts[3])
            # long-poll up to ~1s for progress (reference client behavior)
            q.done.wait(timeout=1.0)
            headers = {}
            for k, v in q.set_session.items():
                headers["X-Presto-Set-Session"] = f"{k}={v}"
            self._send_json(self._results(q, token), headers=headers)
            return
        if parts[:2] == ["v1", "query"] and len(parts) == 3:
            q = self.app.manager.get(parts[2])
            if q is None:
                self._send_json({"error": "no such query"}, 404)
                return
            self._send_json(q.info())
            return
        if parts == ["v1", "info"] or parts == ["v1", "status"]:
            self._send_json({
                "nodeId": "presto-tpu-coordinator",
                "coordinator": True,
                "uptime": time.time() - self.app.started,
                "backend": self.app.backend_name,
            })
            return
        self._send_json({"error": "not found"}, 404)

    def do_DELETE(self):
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if parts[:2] == ["v1", "statement"] and len(parts) >= 3:
            ok = self.app.manager.cancel(parts[2])
            self._send_json({"cancelled": ok})
            return
        self._send_json({"error": "not found"}, 404)

    # --------------------------------------------------------- protocol
    def _results(self, q: _Query, token: int) -> Dict:
        base = f"http://{self.headers.get('Host', 'localhost')}"
        out: Dict = {
            "id": q.id,
            "infoUri": f"{base}/v1/query/{q.id}",
            "stats": {
                "state": q.state,
                "queued": q.state == "QUEUED",
                "elapsedTimeMillis": q.info()["elapsedTimeMillis"],
            },
        }
        if q.error is not None:
            out["error"] = q.error
            return out
        if not q.done.is_set():
            # still running: client polls the same token
            out["nextUri"] = f"{base}/v1/statement/{q.id}/{token}"
            return out
        if q.columns is not None:
            out["columns"] = q.columns
        if q.update_type:
            out["updateType"] = q.update_type
        lo = token * _PAGE_ROWS
        hi = lo + _PAGE_ROWS
        chunk = q.rows[lo:hi]
        if chunk:
            out["data"] = chunk
        if hi < len(q.rows):
            out["nextUri"] = f"{base}/v1/statement/{q.id}/{token + 1}"
        return out


class PrestoTpuServer:
    """Reference: server/PrestoServer.java + StatementResource wiring."""

    def __init__(
        self,
        catalogs,
        default_catalog: str = "tpch",
        port: int = 8080,
        mesh=None,
        page_rows: int = 1 << 18,
    ):
        from presto_tpu.runner import LocalRunner

        self.catalogs = catalogs
        self.port = port
        self.started = time.time()
        try:
            import jax

            self.backend_name = jax.default_backend()
        except Exception:  # pragma: no cover
            self.backend_name = "unknown"

        # one engine, re-sessioned per query (plans/jit caches persist)
        self._runner = LocalRunner(
            catalogs, default_catalog=default_catalog,
            page_rows=page_rows, mesh=mesh,
        )

        def runner_factory(session: Session):
            self._runner.session = session
            return self._runner

        self.manager = QueryManager(runner_factory)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), _Handler)
        self._httpd.app = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    def serve_forever(self) -> None:  # pragma: no cover - CLI entry
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            self.stop()
