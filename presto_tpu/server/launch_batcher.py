"""Cross-query launch batching (ISSUE 17): amortize the per-program
dispatch tax across CONCURRENT queries.

Reference: the same shape a batching inference server uses — requests
that arrive within a short gather window and want the SAME compiled
program run as ONE stacked device step with per-request demux. PR 3
proved the amortization model *within* a query (split-batched
lax.scan/vmap execution); this module extends it *across* queries: the
concurrent server path hands every per-query executor one shared
``LaunchBatcher``, and compatible fused-pipeline launches — same
canonical jit-key family, same ``exec/shapes.py`` ladder bucket — gang
into one vmapped program whose results demux in-program (the batched
function returns one (page, flags) pytree PER SLOT, so each query
walks away with exactly the page its solo launch would have produced).

Protocol (one Condition, no nesting — concheck's acquisition graph
stays a forest):

  - the FIRST submitter for a key becomes the group LEADER and waits
    up to ``wait_ms`` (bounded gather window: a lone query never
    stalls longer than that) for peers, or until the group hits its
    width cap;
  - later submitters for the same key become FOLLOWERS: they park on
    the Condition until the leader publishes per-slot results;
  - at the window's close the leader dispatches the shared program
    OUTSIDE the lock (concheck: no device work under an engine lock)
    via the ``make_batched`` callback its executor passed in, then
    publishes;
  - CONTINUOUS BATCHING: while a same-key batch is already executing
    on the device, the next leader's window extends until that batch
    publishes (bounded by FOLLOW_TIMEOUT_S) — arrivals during an
    in-flight step are free width, because the device queue was
    already charging them the predecessor's wall. Batch trains form
    back-to-back per key under sustained load, so steady-state width
    tracks per-key concurrency instead of the (deliberately tiny)
    gather window;
  - a lone leader (width 1), a trace failure, or any dispatch error
    resolves to ``None`` for every participant — the executor's
    existing solo path runs instead, so batching can only ever be a
    fallthrough optimization, never a correctness dependency.

Counter discipline (tools/lint `counters` rule): this module writes NO
registry counters — every ``cross_query_*`` / ``queries_per_launch``
attribution happens in ``exec/executor.py`` on the submitting query's
executor, from the (width, waited_ms, leader) facts submit() returns.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Tuple

from presto_tpu.obs.sanitizer import make_condition, register_owner

# hard ceiling on how long a follower waits for its leader to publish
# before giving up and running solo (the leader may be wedged in a
# pathological compile; duplicated work is correct work)
FOLLOW_TIMEOUT_S = 60.0

# dispatch-width ladder: a gang dispatches at the largest rung <= its
# gathered width; surplus slots ride the next train. Dense enough that
# truncation wastes < 1/3 of a gang, sparse enough that the compiled
# batch-program set per key family stays a handful of programs
DISPATCH_WIDTHS = (2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)


class _Group:
    """One gathering batch: the entries list is slot-ordered, results
    (when published) are slot-parallel. States: gather -> run ->
    done | fail."""

    __slots__ = ("key", "cap", "entries", "state", "results")

    def __init__(self, key, cap: int):
        self.key = key
        self.cap = cap
        self.entries: List[Tuple[int, int]] = []  # (start, count)/slot
        self.state = "gather"
        self.results: Optional[List] = None


class LaunchBatcher:
    """THE process-shared cross-query batch point. One instance per
    PrestoTpuServer (concurrent path), attached to every per-query
    executor by the runner factory."""

    # lock discipline (tools/lint `locks` rule): the pending-group map
    # and the per-key in-flight dispatch counts are shared across
    # every submitting query thread
    _shared_attrs = ("_pending", "_inflight")

    def __init__(self, wait_ms: int = 25):
        self.wait_ms = wait_ms
        self._cv = make_condition(
            "server.launch_batcher.LaunchBatcher._cv")
        self._pending: Dict = {}
        self._inflight: Dict = {}  # key -> executing batch count
        # optional server-wide active-query count (set once at server
        # startup, before serving): when it reports < 2 running
        # queries there is nobody to gang with, so submit() returns
        # immediately — a lone client NEVER pays the gather window
        self.concurrency_probe = None
        register_owner(self, lock_attrs=("_cv",))

    def submit(self, key, start: int, count: int, cap: int,
               wait_ms: Optional[int], make_batched):
        """Offer one pending launch for cross-query batching.

        ``key`` is the host-hashable compatibility key (plan node +
        jit-key salt + table + ladder bucket); ``cap`` bounds the
        group width (the caller computes it from the shapes.py fault
        line); ``make_batched(entries)`` — called on the LEADER's
        thread, outside the lock — runs the shared program over the
        slot-ordered (start, count) entries and returns one
        (page, flags) per slot.

        Returns ``(page, flags, width, waited_ms, is_leader)`` or
        ``None`` when the caller should run its solo path (lone
        leader, dispatch failure, or follower timeout)."""
        if cap < 2:
            return None
        probe = self.concurrency_probe
        if probe is not None:
            try:
                if probe() < 2:
                    return None  # nobody to gang with: solo, no wait
            except Exception:  # noqa: BLE001 - a perf hint, never load
                pass           # bearing: a broken probe means "gather"
        window_s = (self.wait_ms if wait_ms is None else wait_ms) / 1e3
        t0 = time.monotonic()
        retries = 0
        while True:
            with self._cv:
                g = self._pending.get(key)
                if g is not None and (
                    g.state != "gather" or len(g.entries) >= g.cap
                ):
                    g = None  # closed or full: start a fresh group
                leader = g is None
                if leader:
                    g = _Group(key, cap)
                    self._pending[key] = g
                slot = len(g.entries)
                g.entries.append((start, count))
                if len(g.entries) >= g.cap:
                    self._cv.notify_all()  # wake the leader early
                if not leader:
                    deadline = t0 + FOLLOW_TIMEOUT_S
                    while g.state not in ("done", "fail"):
                        left = deadline - time.monotonic()
                        if left <= 0 or not self._cv.wait(timeout=left):
                            if g.state in ("done", "fail"):
                                break
                            # leader wedged: run solo (duplicate work
                            # is still correct work); the published
                            # result for this slot, if any, goes unread
                            return None
                    if g.state == "fail":
                        return None
                    if slot >= len(g.results):
                        # surplus past the quantized dispatch width:
                        # re-offer — the next train is already
                        # gathering behind the step that just landed
                        retries += 1
                        if retries > 3:
                            return None
                        continue
                    waited_ms = (time.monotonic() - t0) * 1e3
                    page, flags = g.results[slot]
                    return (page, flags, len(g.results), waited_ms,
                            False)
                # leader: bounded gather window — EXTENDED while a
                # same-key batch is still executing (continuous
                # batching: the device queue was already charging
                # those arrivals the predecessor's wall, so lingering
                # adds width, not latency)
                deadline = t0 + window_s
                hard = t0 + FOLLOW_TIMEOUT_S
                while g.state == "gather" and len(g.entries) < g.cap:
                    now = time.monotonic()
                    limit = (hard if self._inflight.get(key)
                             else deadline)
                    left = limit - now
                    if left <= 0:
                        break
                    self._cv.wait(timeout=left)
                g.state = "run"
                if self._pending.get(key) is g:
                    del self._pending[key]
                # quantize the dispatch width DOWN the ladder: bounds
                # the compiled batch-program set to the ladder rungs
                # per key family (no mid-run compile storm at every
                # distinct gang size) and never pads a dead lane (a
                # rounded-up lane is n_pad rows of dead compute);
                # surplus slots re-offer into the next train
                width = min(len(g.entries), g.cap)
                dispatch_n = max(
                    (n for n in DISPATCH_WIDTHS if n <= width),
                    default=1)
                entries = list(g.entries[:dispatch_n])
                waited_ms = (time.monotonic() - t0) * 1e3
                ganged = len(entries) >= 2
                if ganged:
                    self._inflight[key] = (
                        self._inflight.get(key, 0) + 1)
            # ---- leader, OUTSIDE the lock: dispatch the shared step
            if not ganged:
                self._publish(g, None, "fail")
                return None  # lone query: solo is strictly better
            try:
                results = make_batched(entries)
            except Exception:  # noqa: BLE001 - trace/dispatch failure
                # demotes every participant to the solo path; the
                # executor side counts the fallback
                # (split_batch_fallbacks)
                self._publish(g, None, "fail", dec=key)
                return None
            self._publish(g, results, "done", dec=key)
            page, flags = results[slot]
            return page, flags, len(entries), waited_ms, True

    def _publish(self, g: _Group, results, state: str,
                 dec=None) -> None:
        with self._cv:
            if dec is not None:
                n = self._inflight.get(dec, 0) - 1
                if n > 0:
                    self._inflight[dec] = n
                else:
                    self._inflight.pop(dec, None)
            g.results = results
            g.state = state
            self._cv.notify_all()

    # ------------------------------------------------------ solo chaining
    @contextlib.contextmanager
    def solo_inflight(self, key):
        """Mark a SOLO fallthrough execution as in flight for ``key``,
        so same-key arrivals linger behind it exactly as they would
        behind a batched step — lone launches seed trains instead of
        breaking them (a solo step is just a width-1 train car)."""
        with self._cv:
            self._inflight[key] = self._inflight.get(key, 0) + 1
        try:
            yield
        finally:
            self._publish_none(key)

    def _publish_none(self, key) -> None:
        with self._cv:
            n = self._inflight.get(key, 0) - 1
            if n > 0:
                self._inflight[key] = n
            else:
                self._inflight.pop(key, None)
            self._cv.notify_all()

    # ------------------------------------------------------- introspection
    def pending_groups(self) -> int:
        with self._cv:
            return len(self._pending)
