"""Heartbeat-based failure detection.

Reference: presto-main failureDetector/HeartbeatFailureDetector.java —
the coordinator periodically pings every discovered node's status
endpoint, keeps per-node success-rate stats, and marks nodes ALIVE/
FAILED so schedulers avoid dead workers (SURVEY §6.3; recovery model is
fail-query-retry, nodes rejoin between queries).

The TPU engine is a single fat worker per pod slice, so the monitored
"nodes" are peer coordinator/worker HTTP endpoints (/v1/info) — e.g.
other pod slices in a DCN deployment, or TestingPrestoServer-style peers
in tests. Detection is purely host-side (urllib over HTTP) and never
touches the device.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List

from presto_tpu.obs.sanitizer import make_lock, register_owner


@dataclasses.dataclass
class NodeHealth:
    uri: str
    alive: bool = True
    consecutive_failures: int = 0
    successes: int = 0
    failures: int = 0
    last_seen: float = 0.0
    last_error: str = ""

    def info(self) -> Dict:
        total = self.successes + self.failures
        return {
            "uri": self.uri,
            "state": "ALIVE" if self.alive else "FAILED",
            "successRate": (self.successes / total) if total else 1.0,
            "consecutiveFailures": self.consecutive_failures,
            "lastSeen": self.last_seen,
            "lastError": self.last_error or None,
        }


class HeartbeatFailureDetector:
    """Pings each node's /v1/info on a fixed interval; a node is FAILED
    after `fail_after` consecutive misses and returns to ALIVE on the
    first success (reference: success-rate window + expiry)."""

    # lock discipline (tools/lint `locks` rule): the nodes map (and
    # the NodeHealth records inside it) is shared between the
    # background ping loop and query-path readers — every access goes
    # through self._lock
    _shared_attrs = ("nodes",)

    def __init__(
        self,
        node_uris: List[str],
        interval_s: float = 1.0,
        timeout_s: float = 1.0,
        fail_after: int = 3,
    ):
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.fail_after = fail_after
        self.nodes: Dict[str, NodeHealth] = {
            uri: NodeHealth(uri) for uri in node_uris
        }
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # optional (uri, info_dict) callback fired on every successful
        # ping OUTSIDE self._lock — the fleet-cache index rides the
        # heartbeat plane this way (dist/cacheprobe.RemoteCacheIndex.
        # update_from_info) without a detector->index lock ordering
        self.on_info = None
        self._lock = make_lock(
            "server.heartbeat.HeartbeatFailureDetector._lock")
        register_owner(self)

    # ------------------------------------------------------------ control
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self.timeout_s + 1)

    def add_node(self, uri: str) -> None:
        with self._lock:
            self.nodes.setdefault(uri, NodeHealth(uri))

    # ------------------------------------------------------------- queries
    def alive_nodes(self) -> List[str]:
        with self._lock:
            return [u for u, n in self.nodes.items() if n.alive]

    def is_alive(self, uri: str) -> bool:
        with self._lock:
            n = self.nodes.get(uri)
            return bool(n and n.alive)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [n.info() for n in self.nodes.values()]

    # ------------------------------------------------------------ internal
    def check_once(self) -> None:
        """One ping round (exposed for deterministic tests)."""
        with self._lock:
            uris = list(self.nodes)
        for uri in uris:
            self.probe(uri)

    def probe(self, uri: str) -> bool:
        """Ping ONE node now and record the outcome in its NodeHealth
        stats — direct probes (e.g. the DCN re-admission path) stay
        visible in /v1/node snapshots instead of bypassing the
        bookkeeping."""
        ok, err, info = self._ping(uri)
        if ok and self.on_info is not None:
            # outside the lock by design (see __init__); a listener
            # failure must not poison the health bookkeeping
            try:
                self.on_info(uri, info)
            except Exception:  # noqa: BLE001 - advisory plane
                pass
        with self._lock:
            n = self.nodes.get(uri)
            if n is None:
                return ok
            if ok:
                n.successes += 1
                n.consecutive_failures = 0
                n.alive = True
                n.last_seen = time.time()
                n.last_error = ""
            else:
                n.failures += 1
                n.consecutive_failures += 1
                n.last_error = err
                if n.consecutive_failures >= self.fail_after:
                    n.alive = False
        return ok

    def _ping(self, uri: str):
        """(ok, error, info_dict) — the body parse is best-effort:
        health detection needs only the status code, the parsed body
        feeds the optional on_info listener (cacheSummary etc.)."""
        try:
            with urllib.request.urlopen(
                uri.rstrip("/") + "/v1/info", timeout=self.timeout_s
            ) as resp:
                body = resp.read()
                info = None
                try:
                    import json

                    info = json.loads(body)
                except (ValueError, UnicodeDecodeError):
                    info = None
                return resp.status == 200, "", info
        except (urllib.error.URLError, OSError, ValueError) as e:
            return False, str(e)[:200], None

    def _loop(self) -> None:  # pragma: no cover - timing loop
        while not self._stop.wait(self.interval_s):
            self.check_once()
