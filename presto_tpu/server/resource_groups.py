"""Resource groups: admission control for inter-query concurrency.

Reference: presto-main resourceGroups/* (InternalResourceGroupManager,
ResourceGroupSpec) — hierarchical groups with hard_concurrency_limit and
max_queued per group, selected per query by user/source; queries beyond
the queue limit are rejected with QUERY_QUEUE_FULL. The TPU engine keeps
the flat version (SURVEY §3.3: "simple admission queue first; full RG
later"): named groups with concurrency + queue limits and user-pattern
selectors. The device itself serializes execution (one query on the
chip), so hard_concurrency here bounds how many queries may be
in-flight (RUNNING or waiting on the device lock) rather than how many
execute simultaneously.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class ResourceGroupSpec:
    """One group (reference: ResourceGroupSpec in resource-group JSON
    config): selector is a regex over the session user."""

    name: str
    user_pattern: str = ".*"
    hard_concurrency: int = 1
    max_queued: int = 100


class QueryQueueFullError(RuntimeError):
    """Reference: QUERY_QUEUE_FULL error code."""


class ResourceGroupManager:
    """Admission: pick the first matching group; reject when its queue is
    full; callers acquire before running and release after."""

    def __init__(self, groups: Optional[List[ResourceGroupSpec]] = None):
        self.groups = list(groups or [ResourceGroupSpec("global")])
        self._lock = threading.Lock()
        self._running = {g.name: 0 for g in self.groups}
        self._queued = {g.name: 0 for g in self.groups}
        self._cv = threading.Condition(self._lock)

    def select(self, user: str) -> ResourceGroupSpec:
        for g in self.groups:
            if re.fullmatch(g.user_pattern, user or ""):
                return g
        raise QueryQueueFullError(
            f"no resource group matches user {user!r}"
        )

    def admit(self, user: str) -> ResourceGroupSpec:
        """Admission check at submit time: raises QueryQueueFullError when
        the group's queue is at capacity (reference: the coordinator
        rejects before planning)."""
        g = self.select(user)
        with self._lock:
            if self._queued[g.name] >= g.max_queued:
                raise QueryQueueFullError(
                    f"resource group {g.name!r} queue is full "
                    f"({g.max_queued})"
                )
            self._queued[g.name] += 1
        return g

    def acquire(self, group: ResourceGroupSpec, should_abort=None) -> bool:
        """Block until the group has a concurrency slot (QUEUED ->
        RUNNING transition). should_abort() is polled so a query
        canceled while queued releases its queue slot instead of
        blocking forever and then consuming a run slot; returns False
        when aborted (queue slot already released)."""
        with self._cv:
            while self._running[group.name] >= group.hard_concurrency:
                if should_abort is not None and should_abort():
                    self._queued[group.name] -= 1
                    return False
                self._cv.wait(timeout=0.05)
            self._queued[group.name] -= 1
            self._running[group.name] += 1
            return True

    def release(self, group: ResourceGroupSpec) -> None:
        with self._cv:
            self._running[group.name] -= 1
            self._cv.notify_all()

    def cancel_queued(self, group: ResourceGroupSpec) -> None:
        """A query canceled before acquire gives its queue slot back."""
        with self._lock:
            self._queued[group.name] -= 1

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "name": g.name,
                    "userPattern": g.user_pattern,
                    "hardConcurrency": g.hard_concurrency,
                    "maxQueued": g.max_queued,
                    "running": self._running[g.name],
                    "queued": self._queued[g.name],
                }
                for g in self.groups
            ]
