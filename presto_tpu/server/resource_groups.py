"""Hierarchical resource groups: admission control for inter-query
concurrency, queueing, and memory.

Reference: presto-main resourceGroups/* (InternalResourceGroupManager,
InternalResourceGroup, ResourceGroupSpec) — a TREE of groups, each with
hard_concurrency_limit, max_queued, and soft_memory_limit; selectors
pick a LEAF group per query (user regex here), and a query consumes a
queue slot, then a concurrency slot, then memory, at EVERY level of its
group path — a burst in one subgroup cannot starve its siblings beyond
the parent's quota. Queries beyond a queue limit are rejected with
QUERY_QUEUE_FULL.

The device itself serializes execution (one query on the chip), so
hard_concurrency bounds how many queries may be in-flight (RUNNING or
waiting on the device/memory arbiter) rather than how many execute
simultaneously.
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Dict, List, Optional, Tuple

from presto_tpu.obs.sanitizer import (
    make_condition,
    make_lock,
    register_owner,
)


@dataclasses.dataclass(frozen=True)
class ResourceGroupSpec:
    """One group node (reference: ResourceGroupSpec in the resource-
    group JSON config). ``sub_groups`` makes it a tree; a query selects
    the first matching LEAF depth-first. max_memory_bytes = 0 means no
    memory quota at this level."""

    name: str
    user_pattern: str = ".*"
    hard_concurrency: int = 1
    max_queued: int = 100
    max_memory_bytes: int = 0
    sub_groups: Tuple["ResourceGroupSpec", ...] = ()
    # scheduling policy (ISSUE 17; reference: the resource-group
    # schedulingPolicy/schedulingWeight knobs): higher-priority
    # waiters claim freed concurrency slots first, and every waiter
    # AGES — effective priority grows with time queued
    # (AGING_PRIORITY_PER_S) — so a long-scan group can never starve
    # an interactive group, and vice versa
    priority: int = 0
    # fraction of the resolved device budget (exec/membudget.py)
    # queries admitted through this group may each govern to;
    # 0.0 = no share configured (the session/default budget applies)
    memory_share: float = 0.0


@dataclasses.dataclass(frozen=True)
class GroupSelection:
    """A query's admitted path: root-to-leaf chain of specs plus the
    dotted path names (reference: ResourceGroupId)."""

    specs: Tuple[ResourceGroupSpec, ...]
    paths: Tuple[str, ...]

    @property
    def leaf(self) -> ResourceGroupSpec:
        return self.specs[-1]

    @property
    def name(self) -> str:
        return self.paths[-1]


class QueryQueueFullError(RuntimeError):
    """Reference: QUERY_QUEUE_FULL error code."""


class ResourceGroupManager:
    """Admission: select the first matching leaf (depth-first); a query
    holds a queue slot, then a concurrency slot, then (optionally)
    reserved memory at EVERY level of its path."""

    # lock discipline (tools/lint `locks` rule): the per-path slot/
    # queue/memory tallies plus the fair-scheduling waiter line are
    # shared across every query's admission thread
    _shared_attrs = ("_running", "_queued", "_memory", "_waiters",
                     "_ticket")

    # aging rate for fair scheduling: one effective-priority point per
    # this many seconds queued, so a low-priority waiter overtakes a
    # priority-P stream of arrivals after P * this many seconds —
    # bounded starvation by construction
    AGING_PRIORITY_PER_S = 2.0

    def __init__(self, groups: Optional[List[ResourceGroupSpec]] = None):
        self.groups = list(groups or [ResourceGroupSpec("global")])
        self._lock = make_lock(
            "server.resource_groups.ResourceGroupManager._lock")
        self._cv = make_condition(lock=self._lock)
        self._running: Dict[str, int] = {}
        self._queued: Dict[str, int] = {}
        self._memory: Dict[str, int] = {}
        # fair scheduling (ISSUE 17): the live waiter line —
        # [selection, arrival time, ticket] per blocked acquire —
        # ranked by (effective priority desc, ticket asc)
        self._waiters: List[list] = []
        self._ticket = 0
        self._all_paths: List[Tuple[str, ResourceGroupSpec]] = []

        def walk(g: ResourceGroupSpec, prefix: str):
            path = f"{prefix}.{g.name}" if prefix else g.name
            self._running[path] = 0
            self._queued[path] = 0
            self._memory[path] = 0
            self._all_paths.append((path, g))
            for s in g.sub_groups:
                walk(s, path)

        for g in self.groups:
            walk(g, "")
        register_owner(self)

    # ---------------------------------------------------------- selection
    def select(self, user: str) -> GroupSelection:
        def descend(g: ResourceGroupSpec, prefix: str):
            if not re.fullmatch(g.user_pattern, user or ""):
                return None
            path = f"{prefix}.{g.name}" if prefix else g.name
            if not g.sub_groups:
                return ((g,), (path,))
            for s in g.sub_groups:
                found = descend(s, path)
                if found is not None:
                    return ((g,) + found[0], (path,) + found[1])
            return None  # parent matched but no leaf did

        for g in self.groups:
            found = descend(g, "")
            if found is not None:
                return GroupSelection(found[0], found[1])
        raise QueryQueueFullError(
            f"no resource group matches user {user!r}"
        )

    # ---------------------------------------------------------- admission
    def admit(self, user: str) -> GroupSelection:
        """Queue-slot check at submit time, at every level (reference:
        the coordinator rejects before planning)."""
        sel = self.select(user)
        with self._lock:
            for spec, path in zip(sel.specs, sel.paths):
                if self._queued[path] >= spec.max_queued:
                    raise QueryQueueFullError(
                        f"resource group {path!r} queue is full "
                        f"({spec.max_queued})"
                    )
            for path in sel.paths:
                self._queued[path] += 1
        return sel

    def _slots_free_locked(self, sel: GroupSelection) -> bool:
        return all(
            self._running[path] < spec.hard_concurrency
            for spec, path in zip(sel.specs, sel.paths)
        )

    def _front_of_line_locked(self, entry: list) -> bool:
        """Fair scheduling (ISSUE 17): ``entry`` may claim its slots
        only when it ranks first — by (effective priority desc,
        arrival ticket asc) — among the waiters whose OWN groups have
        capacity right now. Effective priority = the leaf's configured
        priority plus time queued over AGING_PRIORITY_PER_S, so a
        short interactive query jumps a saturated line immediately
        while a long-scan waiter ages its way up instead of starving.
        A high-priority waiter whose group is itself full never blocks
        an admissible one (eligibility is capacity-filtered)."""
        now = time.monotonic()

        def rank(e):
            sel, arrival, ticket = e
            eff = sel.leaf.priority + (
                (now - arrival) / self.AGING_PRIORITY_PER_S
            )
            return (-eff, ticket)

        best = None
        for e in self._waiters:
            if not self._slots_free_locked(e[0]):
                continue
            if best is None or rank(e) < rank(best):
                best = e
        return best is entry

    def acquire(self, sel: GroupSelection, should_abort=None) -> bool:
        """Block until every level of the path has a concurrency slot
        (QUEUED -> RUNNING) AND this waiter is first in the fair-
        scheduling line for those slots. Returns False when aborted
        (queue slots already released)."""
        with self._cv:
            self._ticket += 1
            entry = [sel, time.monotonic(), self._ticket]
            self._waiters.append(entry)
            try:
                while True:
                    if (self._slots_free_locked(sel)
                            and self._front_of_line_locked(entry)):
                        for path in sel.paths:
                            self._queued[path] -= 1
                            self._running[path] += 1
                        return True
                    if should_abort is not None and should_abort():
                        for path in sel.paths:
                            self._queued[path] -= 1
                        return False
                    self._cv.wait(timeout=0.05)
            finally:
                self._waiters.remove(entry)
                # the line changed: the next-ranked waiter must
                # re-evaluate _front_of_line_locked
                self._cv.notify_all()

    def release(self, sel: GroupSelection) -> None:
        with self._cv:
            for path in sel.paths:
                self._running[path] -= 1
            self._cv.notify_all()

    def cancel_queued(self, sel: GroupSelection) -> None:
        """A query canceled before acquire gives its queue slots back."""
        with self._lock:
            for path in sel.paths:
                self._queued[path] -= 1

    # ------------------------------------------------------------- memory
    def reserve_memory(self, sel: GroupSelection, nbytes: int,
                       should_abort=None) -> bool:
        """Block until the estimate fits under every level's memory
        quota (reference: soft_memory_limit gating eligibility). A
        query larger than a quota alone is admitted only when that
        group holds no other memory, mirroring the MemoryArbiter's
        stance. Returns False when aborted."""
        with self._cv:
            while True:
                blocked = False
                for spec, path in zip(sel.specs, sel.paths):
                    limit = spec.max_memory_bytes
                    if not limit:
                        continue
                    used = self._memory[path]
                    if used + nbytes > limit and used > 0:
                        blocked = True
                        break
                if not blocked:
                    for path in sel.paths:
                        self._memory[path] += nbytes
                    return True
                if should_abort is not None and should_abort():
                    return False
                self._cv.wait(timeout=0.05)

    def release_memory(self, sel: GroupSelection, nbytes: int) -> None:
        with self._cv:
            for path in sel.paths:
                self._memory[path] -= nbytes
            self._cv.notify_all()

    def memory_share_for(self, sel: GroupSelection) -> float:
        """The HBM share governing queries admitted through this
        selection: the most specific (deepest) nonzero ``memory_share``
        along the path wins; 0.0 = no share configured. The server
        resolves it against the device budget via
        exec/membudget.group_share_bytes and seeds each admitted
        query's device_memory_budget, so N concurrent queries split
        the device by policy instead of colliding into the OOM
        ladder."""
        for spec in reversed(sel.specs):
            if spec.memory_share > 0:
                return spec.memory_share
        return 0.0

    # ----------------------------------------------------------- introspection
    def snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "name": path,
                    "userPattern": g.user_pattern,
                    "hardConcurrency": g.hard_concurrency,
                    "maxQueued": g.max_queued,
                    "maxMemoryBytes": g.max_memory_bytes,
                    "priority": g.priority,
                    "memoryShare": g.memory_share,
                    "running": self._running[path],
                    "queued": self._queued[path],
                    "reservedMemoryBytes": self._memory[path],
                }
                for path, g in self._all_paths
            ]
