from presto_tpu.server.http_server import PrestoTpuServer

__all__ = ["PrestoTpuServer"]
