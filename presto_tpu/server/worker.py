"""Worker process: the /v1/task control plane + page-buffer data plane.

Reference: presto-main server/TaskResource.java (task create/status/
cancel), execution/SqlTaskManager.java (task registry + execution),
execution/buffer/OutputBuffer (token-indexed page buffer consumed by
HttpPageBufferClient with at-least-once + token-dedupe semantics).

The TPU-native shape: one worker process = one host driving its local
devices. A task carries a SERIALIZED physical-plan fragment
(dist/plan_serde.py — the reference's TaskUpdateRequest PlanFragment)
plus a split assignment; the worker deserializes and executes exactly
the subtree the coordinator planned, restricted to its split share
(round-robin or hash-co-partitioned scans), and buffers serialized
pages (dist/serde.py) for token-indexed fetch. Legacy peers may still
send (sql, role) for worker-side replay.

Stage-DAG tasks (dist/scheduler.py) extend the same surface with a
SPOOLED-EXCHANGE plane (reference: Project Tardigrade's spooled
shuffle, PartitionedOutputOperator + ExchangeClient):

  - a task whose payload carries ``outputPartitions``/``outputKeys``
    hash-partitions every result page host-side (dist/spool.py) and
    publishes the serialized partitions into PageStore host/disk tiers
    (_TaskSpool) — partition buffers OUTLIVE execution, so a lost
    downstream task replays from its upstream spools;
  - a task whose payload carries ``sources`` registers RemoteSource
    suppliers that fetch its input partitions from upstream tasks'
    spools over HTTP (worker-to-worker exchange — the coordinator
    never relays inter-stage pages);
  - ``GET /v1/task/{id}/results/{token}?part=p`` fetches one spool
    partition token-indexed; ``DELETE /v1/task/{id}/spool/{p}`` acks
    (releases) a consumed partition.

Route handling is factored into module-level ``route_task_*``
functions so the coordinator HTTP server can serve the same task +
spool data plane in-process (a coordinator+worker single-process
deployment, server/http_server.py).

Fault-injection hooks (SURVEY §6.3: inject at the host page proxy —
ICI collectives cannot be faulted): FAULT_DELAY_MS delays every
results fetch; FAULT_DROP_EVERY=n returns HTTP 500 on every nth fetch;
FAULT_KILL_AFTER_FETCHES=n hard-exits the worker PROCESS once n result
fetches have been served (worker death mid-query — the coordinator's
task-retry path re-dispatches the fragment to a survivor);
FAULT_SUBMIT_DROP_EVERY=n returns HTTP 500 on every nth task submit
(exercises the coordinator's submit retry);
FAULT_TASK_EXEC_DELAY_MS stalls task EXECUTION (a deterministic
straggler for the stage scheduler's speculation policy);
FAULT_SPOOL_CORRUPT_EVERY=n bit-flips a byte inside every nth served
results body (framing intact, page content corrupt — proves the
consumer-side PageWireError loud-fail + replay ladder end to end).
Each knob reads the
runtime `fault_config` posted via POST /v1/fault as an OVERLAY on the
environment: posted keys win (an explicit 0 disables an env-seeded
fault), absent keys fall back to the environment, and `{}` restores
pure env-ruled mode (tools/chaos.py reconfigures live workers between
iterations without reboots). Token-indexed re-fetch makes drops
recoverable
(at-least-once); kills are recoverable only with task_retry_attempts>0.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from presto_tpu.connectors.split_filter import SplitFilterConnector
from presto_tpu.dist import serde
from presto_tpu.exec import plan as P
from presto_tpu.exec import xfer as XF
from presto_tpu.obs import sanitizer as SAN
from presto_tpu.obs.sanitizer import make_lock, register_owner
from presto_tpu.session import Session


class _PartitionSpool:
    """One partition's spooled output: host-tier PageStore blobs while
    the task's resident budget lasts, disk-tier PageStore past it (the
    FileSingleStreamSpiller analog for exchange pages) — plus, on the
    device-exchange tier (ISSUE 13), LAZY entries holding the
    partitioned Page itself (device- or host-resident): same-process
    consumers take the Page with no serde at all, and wire bytes
    materialize only when an HTTP fetch (a DCN-remote consumer or a
    replay) actually needs them (dist/spool.spool_blob, metered d2h).
    Entries are (store, index) for materialized blobs and
    ("page", Page, est_bytes) for lazy ones."""

    def __init__(self, spill_dir: Optional[str] = None):
        from presto_tpu.exec.pagestore import PageStore

        self._host = PageStore(tier="host")
        self._disk: Optional[PageStore] = None
        self._spill_dir = spill_dir
        self._entries: List = []  # (store, index) | ("page", p, est)
        self._page_bytes = 0
        self.released = False
        # spool-stats plane (ISSUE 15): EXACT rows/bytes published
        # into this partition, accumulated at put time and MONOTONE —
        # they survive release/close so the coordinator's adaptive
        # re-planner reads stable numbers whenever it asks, and a
        # replayed task re-accumulates identical values (the spool
        # content is deterministic)
        self.stat_rows = 0
        self.stat_bytes = 0
        # measured post-codec wire bytes (ISSUE 17): blob-tier entries
        # count their actual serialized length; device-resident pages
        # never serialized, so they count their raw footprint (an
        # upper bound — freight costing must never under-count)
        self.stat_wire_bytes = 0

    def put(self, blob: bytes, to_disk: bool, rows: int = 0) -> None:
        from presto_tpu.exec.pagestore import PageStore

        if to_disk:
            if self._disk is None:
                self._disk = PageStore(tier="disk",
                                       spill_dir=self._spill_dir)
            store = self._disk
        else:
            store = self._host
        store.put_bytes(blob)
        self._entries.append((store, store.page_count - 1))
        self.stat_rows += int(rows)
        self.stat_bytes += len(blob)
        self.stat_wire_bytes += len(blob)

    def put_page(self, page, est_bytes: int, rows: int = 0) -> None:
        """Spool one partitioned Page WITHOUT serializing (the device-
        resident tier). est_bytes is the static page footprint — the
        resident-budget accounting the blob tier does by len(blob)."""
        self._entries.append(("page", page, est_bytes))
        self._page_bytes += est_bytes
        self.stat_rows += int(rows)
        self.stat_bytes += int(est_bytes)
        self.stat_wire_bytes += int(est_bytes)

    def blob(self, token: int) -> bytes:
        entry = self._entries[token]
        if entry[0] == "page":
            # lazy host materialization: deterministic serialization,
            # so a token re-fetch or a verified replay prefix reads
            # byte-identical wire data (no caching — re-fetches are
            # the rare retry path, and an uncached serialize keeps the
            # entry list free of cross-thread mutation)
            from presto_tpu.dist import spool as SPOOL

            return SPOOL.spool_blob(entry[1])
        store, i = entry
        return store.blob_at(i)

    @property
    def count(self) -> int:
        return len(self._entries)

    @property
    def bytes(self) -> int:
        return (self._host.bytes + self._page_bytes
                + (self._disk.bytes if self._disk else 0))

    def close(self) -> None:
        self._host.close()
        if self._disk is not None:
            self._disk.close()
        self._entries = []  # drops lazy Page refs -> frees HBM
        self._page_bytes = 0
        self.released = True


class _TaskSpool:
    """A task's partitioned output spool: P token-indexed partition
    buffers sharing one resident-byte budget (the spool_exchange_bytes
    session property) — blobs past it go to the disk tier."""

    def __init__(self, nparts: int, host_budget: int,
                 spill_dir: Optional[str] = None):
        self.parts = [_PartitionSpool(spill_dir)
                      for _ in range(max(nparts, 1))]
        self.host_budget = host_budget
        self.host_bytes = 0

    def put(self, p: int, blob: bytes, rows: int = 0) -> None:
        to_disk = (self.host_budget > 0
                   and self.host_bytes + len(blob) > self.host_budget)
        if not to_disk:
            self.host_bytes += len(blob)
        self.parts[p].put(blob, to_disk, rows=rows)

    def put_page(self, p: int, page, rows: int = 0) -> None:
        """Device-exchange tier: spool the partitioned Page itself.
        The spool_exchange_bytes budget bounds RESIDENT bytes across
        tiers — a page past it materializes eagerly (spool_blob) and
        rides the existing blob demotion to disk, so device-resident
        spools can never hold more HBM than the knob allows."""
        from presto_tpu.exec.executor import page_bytes

        est = page_bytes(page)
        if self.host_budget > 0 and self.host_bytes + est > \
                self.host_budget:
            from presto_tpu.dist import spool as SPOOL

            self.put(p, SPOOL.spool_blob(page), rows=rows)
            return
        self.host_bytes += est
        self.parts[p].put_page(page, est, rows=rows)

    @property
    def page_count(self) -> int:
        return sum(p.count for p in self.parts)

    @property
    def byte_count(self) -> int:
        return sum(p.bytes for p in self.parts)

    def part_stats(self) -> Tuple[List[int], List[int], List[int]]:
        """(rows, bytes, wire bytes) per partition — the stage-
        boundary stats the adaptive re-planner sums coordinator-side
        (ISSUE 15; wire bytes ISSUE 17). Exact and monotone:
        accumulated at publish time, stable across release and
        identical after a deterministic replay."""
        return ([p.stat_rows for p in self.parts],
                [p.stat_bytes for p in self.parts],
                [p.stat_wire_bytes for p in self.parts])

    def release(self, p: int) -> bool:
        if 0 <= p < len(self.parts):
            self.parts[p].close()
            return True
        return False

    def close(self) -> None:
        for p in self.parts:
            p.close()


# --------------------------------------------------------------------
# Same-process placement registry (ISSUE 13): uri -> TaskRuntime for
# every task runtime served from THIS process (in-process WorkerServer
# threads, the coordinator's embedded worker_tasks runtime). The
# mesh-local exchange fast path — dist/spool.iter_source_pages and the
# stage scheduler's root drain — looks placements up here and takes
# spooled Pages directly (no HTTP, no serde, no h2d re-stage for
# device-resident spools). Subprocess workers never appear: the
# registry is per-process by construction, so a remote placement
# always falls back to the metered HTTP + lazy-materialization path.
_runtimes_lock = make_lock("server.worker._runtimes_lock")
_LOCAL_RUNTIMES: Dict[str, "TaskRuntime"] = {}


def register_local_runtime(uri: str, rt: "TaskRuntime") -> None:
    with _runtimes_lock:
        _LOCAL_RUNTIMES[uri] = rt


def unregister_local_runtime(uri: str) -> None:
    with _runtimes_lock:
        _LOCAL_RUNTIMES.pop(uri, None)


def local_runtime(uri: str) -> Optional["TaskRuntime"]:
    with _runtimes_lock:
        return _LOCAL_RUNTIMES.get(uri)


class _Task:
    # lock discipline (tools/lint `locks` rule): lifecycle flags and
    # result buffers shared between the execution thread and the
    # fetch/status/cancel handlers — written under self.lock (the
    # writes live in TaskRuntime/route_* but the contract is the
    # task's; the runtime sanitizer enforces it per instance)
    _shared_attrs = ("pages", "spool", "done", "error", "cancelled",
                     "spans", "boost_retries", "skew_preempted")

    def __init__(self, task_id: str):
        self.task_id = task_id
        self.pages: List[bytes] = []
        self.spool: Optional[_TaskSpool] = None
        self.done = False
        self.error: Optional[str] = None
        self.cancelled = False
        # per-task executor outcomes shipped on the status plane
        # (ISSUE 15): overflow-ladder re-entries and pre-engaged skew
        # chunking, mirrored onto the coordinator's registry counters
        # so "first-run boosts driven to zero" is visible where the
        # adaptive re-planner's own counters live
        self.boost_retries = 0
        self.skew_preempted = 0
        self.lock = make_lock("server.worker._Task.lock")
        # lifecycle tracing (ISSUE 9): interval math on monotonic,
        # ONE wall anchor for cross-node correlation — the span
        # timing-source rule (obs/trace.py docstring)
        self.created_mono = time.monotonic()
        self.created_wall = time.time()
        # worker-side spans (queue/run/attempt), exported as offsets
        # from created_mono and shipped to the coordinator on the
        # status plane so it can assemble one cross-node timeline
        self.spans: Optional[List[Dict]] = None
        register_owner(self, lock_attrs=("lock",))

    # --------- unified read surface (legacy byte list OR spool tiers)
    def part_count(self, part: int) -> int:
        if self.spool is not None:
            if part >= len(self.spool.parts):
                return 0
            return self.spool.parts[part].count
        return len(self.pages) if part == 0 else 0

    def part_blob(self, part: int, token: int) -> bytes:
        if self.spool is not None:
            return self.spool.parts[part].blob(token)
        return self.pages[token]

    def part_released(self, part: int) -> bool:
        return (self.spool is not None
                and 0 <= part < len(self.spool.parts)
                and self.spool.parts[part].released)

    def total_pages(self) -> int:
        if self.spool is not None:
            return self.spool.page_count
        return len(self.pages)

    def free(self) -> None:
        self.pages.clear()
        if self.spool is not None:
            self.spool.close()


def find_partial_cut(plan: P.PhysicalNode) -> Optional[P.Aggregation]:
    """The topmost single-step aggregation — the PARTIAL/FINAL split
    point for the DCN boundary (reference: AddExchanges splitting
    AggregationNode into PARTIAL below / FINAL above the exchange)."""
    if isinstance(node := plan, P.Aggregation) and node.step == "single":
        return node
    for c in plan.children():
        hit = find_partial_cut(c)
        if hit is not None:
            return hit
    return None


def row_local_scan_count(node: P.PhysicalNode,
                         split_table: str) -> Optional[int]:
    """How many times ``split_table`` is scanned under ``node``, or
    None when the subtree is not ROW-LOCAL — i.e. when the multiset of
    its output rows is NOT the disjoint union of the outputs over a
    row-partition of split_table (all other tables replicated).

    Row-local shapes: Filter / Project / Exchange / TableScan / INNER
    hash joins. Inner joins distribute over a partition of any single
    table (each result row maps to exactly one row of it); outer/semi/
    anti/cross joins, aggregations, sorts, limits, windows, and
    MarkDistinct do not (a MarkDistinct would mark first-occurrence
    per worker and double-count values spanning workers)."""
    if isinstance(node, P.TableScan):
        return 1 if node.table == split_table else 0
    if isinstance(node, (P.Filter, P.Project, P.Exchange)):
        return row_local_scan_count(node.source, split_table)
    if isinstance(node, P.HashJoin):
        if node.join_type != "inner":
            return None
        left = row_local_scan_count(node.left, split_table)
        right = row_local_scan_count(node.right, split_table)
        if left is None or right is None:
            return None
        return left + right
    return None


def fanout_safe(cut: P.Aggregation, split_table: str) -> bool:
    """Whether the PARTIAL subtree distributes over a round-robin
    partition of split_table's rows: decomposable aggregates with no
    DISTINCT masks, and a row-local source with exactly ONE scan of
    the split table (see row_local_scan_count). Queries outside this
    shape use the union-cut fallback (find_union_cut) or run local."""
    if any(s.mask is not None for s in cut.aggregates):
        return False
    return row_local_scan_count(cut.source, split_table) == 1


def find_union_cut(plan: P.PhysicalNode,
                   split_table: str) -> Optional[P.PhysicalNode]:
    """The TOPMOST row-local subtree scanning split_table exactly once
    — the general distribution shape for plans with no decomposable
    aggregation cut (reference: a SOURCE_DISTRIBUTION leaf fragment
    under a GATHER exchange; SqlQueryScheduler runs the leaf stage on
    every worker and the coordinator consumes the union). Workers
    execute the subtree over their split share; the coordinator
    replaces it with a RemoteSource and runs everything above (sort /
    topN / window / non-decomposable aggregation) over the unioned
    pages. Returns None when no useful cut exists (a bare scan or a
    pure projection of one is not worth shipping: generation is
    cheaper than the wire — the cut must contain a join or filter)."""

    def has_work(n) -> bool:
        if isinstance(n, (P.HashJoin, P.Filter)):
            return True
        return any(has_work(c) for c in n.children())

    n = row_local_scan_count(plan, split_table)
    if n == 1 and has_work(plan):
        return plan
    for c in plan.children():
        hit = find_union_cut(c, split_table)
        if hit is not None:
            return hit
    return None


def hash_fanout_plan(cut: P.Aggregation, catalogs,
                     partition_threshold: int = 1 << 17):
    """Co-partitioning spec for a PARTITIONED JOIN fan-out below an
    aggregation cut; decomposability of the aggregates follows
    fanout_safe's rules (no DISTINCT masks). See hash_fanout_source."""
    if any(s.mask is not None for s in cut.aggregates):
        return None
    return hash_fanout_source(cut.source, catalogs,
                              partition_threshold)


def hash_fanout_source(root: P.PhysicalNode, catalogs,
                       partition_threshold: int = 1 << 17):
    """Co-partitioning spec for a PARTITIONED JOIN fan-out (the DCN
    hash-repartition exchange; reference: AddExchanges choosing
    REPARTITION and inserting hash exchanges on both join sides).

    Returns {table: partition_column} covering every BIG scanned table
    (row_count >= partition_threshold), or None when the shape does
    not co-partition. Valid shape under ``root``: Filter / Project /
    Exchange / TableScan / INNER hash joins; every join with big
    tables on BOTH sides must equi-join on single keys that are
    provably those tables' columns (exec/plan.scan_column_of), and
    each big table must receive exactly ONE partition column; small
    tables replicate (broadcast side)."""
    parts: dict = {}
    state = {"ok": True}

    def big_tables_under(n) -> set:
        out = set()

        def walk(x):
            if isinstance(x, P.TableScan):
                if catalogs[x.catalog].row_count(x.table) >= \
                        partition_threshold:
                    out.add(x.table)
                return
            for c in x.children():
                walk(c)

        walk(n)
        return out

    def assign(table: str, column: str):
        if parts.get(table, column) != column:
            state["ok"] = False  # conflicting partition keys
        parts[table] = column

    def walk(n):
        if not state["ok"]:
            return
        if isinstance(n, (P.Filter, P.Project, P.Exchange,
                          P.TableScan)):
            for c in n.children():
                walk(c)
            return
        if isinstance(n, P.HashJoin):
            if n.join_type != "inner":
                state["ok"] = False
                return
            left_big = big_tables_under(n.left)
            right_big = big_tables_under(n.right)
            if left_big and right_big:
                # partitioned join: both sides keyed by their own
                # table columns, co-partitioned on this equi-key
                if len(n.left_keys) < 1:
                    state["ok"] = False
                    return
                lsrc = P.scan_column_of(n.left, n.left_keys[0])
                rsrc = P.scan_column_of(n.right, n.right_keys[0])
                if lsrc is None or rsrc is None:
                    state["ok"] = False
                    return
                # dictionary codes are table-local (same rule as
                # executor._keys_partitionable): equal string values
                # would hash to different workers on each side —
                # refuse string/dictionary-typed partition keys
                from presto_tpu import types as T

                for cat, table, col in (lsrc, rsrc):
                    t = catalogs[cat].table_schema(
                        table).column_type(col)
                    if T.is_string(t) or t.is_dictionary_encoded:
                        state["ok"] = False
                        return
                # the key must constrain EVERY big table on its side —
                # a second big table not keyed by this join cannot be
                # co-partitioned
                if left_big != {lsrc[1]} or right_big != {rsrc[1]}:
                    state["ok"] = False
                    return
                assign(f"{lsrc[0]}.{lsrc[1]}", lsrc[2])
                assign(f"{rsrc[0]}.{rsrc[1]}", rsrc[2])
            walk(n.left)
            walk(n.right)
            return
        state["ok"] = False

    walk(root)
    if not state["ok"] or len(parts) < 2:
        return None
    return parts


def largest_table(node: P.PhysicalNode, catalogs) -> Optional[str]:
    """The fact table to split across workers: the scanned table with
    the most rows under this subtree (SOURCE_DISTRIBUTION pick)."""
    tables = []

    def scans(n):
        if isinstance(n, P.TableScan):
            tables.append((n.catalog, n.table))
        for ch in n.children():
            scans(ch)

    scans(node)
    if not tables:
        return None
    return max(
        tables, key=lambda ct: catalogs[ct[0]].row_count(ct[1])
    )[1]


# ---------------------------------------------------------------------
# Task-plane routing, shared between the worker's own HTTP server and
# the coordinator server (http_server.py delegates /v1/task* and
# /v1/fault here when constructed with a task runtime). A response is
# (status, headers_list, content_type, body_bytes); None means "not a
# task-plane path".

_JSON_CT = "application/json"
_PAGES_CT = "application/x-presto-pages"


def _jresp(obj, status=200, headers=()):
    return (status, list(headers), _JSON_CT, json.dumps(obj).encode())


def write_task_response(handler, resp) -> None:
    """Render a (status, headers, content_type, body) route result on
    a BaseHTTPRequestHandler — ONE renderer for both the worker's own
    handler and the coordinator's delegating handler, so the task
    plane cannot drift between the two servers."""
    status, headers, ctype, body = resp
    handler.send_response(status)
    handler.send_header("Content-Type", ctype)
    if status != 204:
        handler.send_header("Content-Length", str(len(body)))
    for k, v in headers:
        handler.send_header(k, v)
    handler.end_headers()
    if status != 204 and body:
        handler.wfile.write(body)


def route_task_post(app, path: str, body: bytes):
    if path.startswith("/v1/fault"):
        # runtime fault reconfiguration (chaos harness): the posted
        # overlay replaces the previous one; {} clears every RUNTIME
        # fault and restores env-ruled mode
        app.set_fault_config({
            k: int(v) for k, v in json.loads(body or b"{}").items()
        })
        return _jresp({"ok": True, "fault": app.fault_config})
    if path.startswith("/v1/cache/task"):
        # fleet cache probe (ISSUE 19, dist/cacheprobe.py): serve one
        # fragment key from THIS process's result cache by parking
        # the cached host pages in a pre-finished task spool — the
        # consumer then fetches them over the ordinary pooled
        # spool-fetch plane, indistinguishable from an executed task
        req = json.loads(body)
        return _jresp(app.serve_cached_fragment(
            str(req.get("taskId") or ""), str(req.get("key") or "")))
    if not path.startswith("/v1/task"):
        return None
    if app.maybe_inject_submit_fault():
        return _jresp({"error": "injected submit fault"}, 500)
    req = json.loads(body)
    task = app.create_task(req)
    return _jresp({"taskId": task.task_id, "state": "RUNNING"})


def route_task_get(app, path: str, query: str):
    from urllib.parse import parse_qs

    parts = [p for p in path.split("/") if p]
    # /v1/task/{id}/results/{token}[?part=p][&max=bytes]
    if len(parts) == 5 and parts[:2] == ["v1", "task"] \
            and parts[3] == "results":
        task = app.get_task(parts[2])
        if task is None:
            return _jresp({"error": "no such task"}, 404)
        token = int(parts[4])
        qs = parse_qs(query or "")
        part = int(qs.get("part", ["0"])[0])
        # ?max engages the streaming/ranged response (ISSUE 16): up
        # to `max` bytes of CONSECUTIVE page frames ship in one
        # framed body (dist/spool.pack_frames) so the consumer drains
        # a partition page-at-a-time under a bounded in-flight-bytes
        # window. Absent ?max, the legacy single-blob shape is served
        # unchanged.
        max_bytes = int(qs.get("max", ["0"])[0])
        if app.maybe_inject_fault():
            return _jresp({"error": "injected fault"}, 500)
        # bounded long-poll until the page at `token` exists or the
        # task finishes (reference: HttpPageBufferClient long-poll).
        # Monotonic, not wall: an NTP step mid-poll must not stretch
        # or collapse the window (ISSUE 9 timing-source audit)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            entry = blob = None
            with task.lock:
                if task.error:
                    # X-Task-Error marks a DETERMINISTIC task failure
                    # (the fragment itself failed, not the transport):
                    # consumers surface the real message instead of
                    # spinning fetch retries against a dead task
                    return _jresp({"error": task.error}, 500,
                                  headers=(("X-Task-Error", "1"),))
                if task.part_released(part):
                    return _jresp(
                        {"error": f"spool partition {part} released "
                                  f"(already acked)"}, 410)
                if token < task.part_count(part):
                    if task.spool is not None:
                        # resolve under the lock, READ outside it: a
                        # disk-tier blob read must not serialize the
                        # other partitions' consumers and the status
                        # polls behind one file read
                        entry = (task.spool.parts[part]
                                 ._entries[token])
                    else:
                        blob = task.pages[token]
                elif task.done:
                    return (204, [("X-Done", "1")], _JSON_CT, b"")
            if entry is not None:
                try:
                    if entry[0] == "page":
                        # device-resident spool entry: lazy host
                        # materialization happens HERE, outside the
                        # task lock (a d2h + serialize under the lock
                        # would serialize every other consumer — the
                        # concheck blocking-under-lock rule)
                        from presto_tpu.dist import spool as SPOOL

                        blob = SPOOL.spool_blob(entry[1])
                    else:
                        store, i = entry
                        blob = store.blob_at(i)
                except (OSError, IndexError):
                    # raced a concurrent ack/release of this partition
                    return _jresp(
                        {"error": f"spool partition {part} released "
                                  f"(already acked)"}, 410)
            if blob is not None:
                # fault injection point: the flip lands INSIDE one
                # page body (framing stays intact), so the consumer's
                # decode — not the transport — catches it
                blob = app.maybe_corrupt_blob(blob)
                if max_bytes <= 0:
                    # legacy single-blob response shape
                    return (200, [("X-Next-Token", str(token + 1)),
                                  ("X-Done", "0")], _PAGES_CT, blob)
                from presto_tpu.dist import spool as SPOOL

                # streaming/ranged response: extend with CONSECUTIVE
                # ready frames until the byte window fills. Frames
                # stop once the total reaches max_bytes, so one
                # response carries at most window + one page — the
                # consumer's bounded in-flight-bytes contract. Extra
                # frames are best-effort: any race (ack, store close)
                # just ends the range and the next request sees the
                # canonical 410/204 answer.
                frames = [blob]
                total = 8 + len(blob)
                while total < max_bytes:
                    nxt = token + len(frames)
                    entry2 = blob2 = None
                    with task.lock:
                        if task.error or task.part_released(part):
                            break
                        if nxt >= task.part_count(part):
                            break
                        if task.spool is not None:
                            entry2 = (task.spool.parts[part]
                                      ._entries[nxt])
                        else:
                            blob2 = task.pages[nxt]
                    if entry2 is not None:
                        try:
                            if entry2[0] == "page":
                                blob2 = SPOOL.spool_blob(entry2[1])
                            else:
                                store, i = entry2
                                blob2 = store.blob_at(i)
                        except (OSError, IndexError):
                            break
                    if blob2 is None:
                        break
                    frames.append(blob2)
                    total += 8 + len(blob2)
                return (200,
                        [("X-Next-Token", str(token + len(frames))),
                         ("X-Done", "0"),
                         ("X-Frames", str(len(frames)))],
                        _PAGES_CT, SPOOL.pack_frames(frames))
            time.sleep(0.02)
        return (204, [("X-Done", "0")], _JSON_CT, b"")
    if len(parts) == 3 and parts[:2] == ["v1", "task"]:
        task = app.get_task(parts[2])
        if task is None:
            return _jresp({"error": "no such task"}, 404)
        with task.lock:
            spool = task.spool
            body = {
                "taskId": task.task_id,
                "state": ("FAILED" if task.error else
                          "FINISHED" if task.done else "RUNNING"),
                "pages": task.total_pages(),
                "spooledPages": spool.page_count if spool else 0,
                "spooledBytes": spool.byte_count if spool else 0,
                "partitions": len(spool.parts) if spool else 1,
                "error": task.error,
                # spool-stats plane (ISSUE 15): exact per-partition
                # row/byte counts + executor outcomes, summed
                # coordinator-side at the stage boundary — the input
                # the adaptive re-planner re-optimizes from
                "boostRetries": task.boost_retries,
                "skewPreempted": task.skew_preempted,
            }
            if spool is not None:
                rows, nbytes, wire = spool.part_stats()
                body["spoolRows"] = rows
                body["spoolBytes"] = nbytes
                body["spoolWireBytes"] = wire
            if task.spans is not None:
                # worker-side spans for the coordinator's cross-node
                # timeline: offsets from this task's creation, plus
                # the worker's wall anchor for correlation only
                body["spans"] = task.spans
                body["wallAnchor"] = task.created_wall
            return _jresp(body)
    return None


def route_task_delete(app, path: str):
    parts = [p for p in path.split("/") if p]
    # /v1/task/{id}/spool/{part}: ack (release) one consumed spool
    # partition — partition-granular buffer release so long queries
    # can return exchange memory before the whole task expires
    if len(parts) == 5 and parts[:2] == ["v1", "task"] \
            and parts[3] == "spool":
        task = app.get_task(parts[2])
        if task is None:
            return _jresp({"error": "no such task"}, 404)
        with task.lock:
            ok = (task.spool is not None
                  and task.spool.release(int(parts[4])))
        if ok:
            return _jresp({"taskId": task.task_id,
                           "partition": int(parts[4]),
                           "state": "RELEASED"})
        return _jresp({"error": "no such spool partition"}, 404)
    if len(parts) == 3 and parts[:2] == ["v1", "task"]:
        task = app.pop_task(parts[2])
        if task is not None:
            with task.lock:
                # under the task lock like every other lifecycle-flag
                # write (the execution thread polls it between pages)
                task.cancelled = True
                task.free()  # page buffers + spool tiers
            return _jresp({"taskId": task.task_id,
                           "state": "CANCELED"})
    return None


class _WorkerHandler(BaseHTTPRequestHandler):
    server_version = "presto-tpu-worker/0.3"
    # HTTP/1.1 so the shuffle plane's pooled clients
    # (dist/connpool.py) get keep-alive for real; every response path
    # sends Content-Length (write_task_response; 204s ship no body).
    # The socket timeout bounds how long an idle keep-alive handler
    # thread lingers after its client forgets it.
    protocol_version = "HTTP/1.1"
    timeout = 120

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    @property
    def app(self) -> "WorkerServer":
        return self.server.app  # type: ignore[attr-defined]

    def _write(self, resp) -> None:
        write_task_response(self, resp)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n) or b"{}"
        resp = route_task_post(self.app, self.path, body)
        self._write(resp if resp is not None
                    else _jresp({"error": "not found"}, 404))

    def do_GET(self):
        from urllib.parse import urlsplit

        split = urlsplit(self.path)
        if split.path.startswith("/v1/info"):
            info = {
                "nodeId": self.app.node_id,
                "state": "ACTIVE",
                "uptime_s": round(
                    time.monotonic() - self.app.started_mono, 1),
                "tasks": self.app.task_count(),
            }
            if SAN.is_armed():
                # sanitized-mode surface: tools/chaos.py --sanitize
                # polls each worker's violation count at the end of a
                # run (the worker process has no other reporting plane)
                info["sanitizerViolations"] = SAN.violation_count()
            # fleet-cache advertisement (ISSUE 19): a bloom summary of
            # this process's cached fragment keys rides every
            # heartbeat poll, so the coordinator's RemoteCacheIndex
            # stays fresh without a dedicated plane; absent when the
            # store doesn't exist or holds nothing (probe-free)
            from presto_tpu.cache import shared_cache_if_exists

            rc = shared_cache_if_exists()
            if rc is not None:
                keys = rc.pages_keys()
                if keys:
                    from presto_tpu.dist.cacheprobe import bloom_summary

                    info["cacheSummary"] = bloom_summary(keys)
            self._write(_jresp(info))
            return
        resp = route_task_get(self.app, split.path, split.query)
        self._write(resp if resp is not None
                    else _jresp({"error": "not found"}, 404))

    def do_DELETE(self):
        resp = route_task_delete(self.app, self.path)
        self._write(resp if resp is not None
                    else _jresp({"error": "not found"}, 404))


class TaskRuntime:
    """A process's task runtime (SqlTaskManager analog): task registry,
    fragment execution, spooled output buffers, fault injection — no
    HTTP server of its own. WorkerServer wraps it with one; the
    coordinator server (http_server.py) embeds one directly so a
    single process can serve both roles."""

    # lock discipline (tools/lint `locks` rule): the task registry is
    # mutated by HTTP handler threads (create/cancel) while status/
    # fetch handlers and expiry sweeps read it — guarded by
    # _tasks_lock; the fault overlay + its call counters by _fault_lock
    _shared_attrs = ("tasks", "fault_config", "_results_calls",
                     "_submit_calls", "_corrupt_calls")

    def __init__(self, catalogs, *, node_id: str = "w0",
                 default_catalog: Optional[str] = None,
                 page_rows: int = 1 << 16):
        self.catalogs = catalogs
        self.node_id = node_id
        self.default_catalog = default_catalog
        self.page_rows = page_rows
        self.tasks: Dict[str, _Task] = {}
        self._tasks_lock = make_lock(
            "server.worker.TaskRuntime._tasks_lock")
        self.started = time.time()
        # uptime arithmetic runs on monotonic (the wall `started` is
        # display/correlation only — timing-source audit, ISSUE 9)
        self.started_mono = time.monotonic()
        self._fault_lock = make_lock(
            "server.worker.TaskRuntime._fault_lock")
        self._results_calls = 0
        self._submit_calls = 0
        self._corrupt_calls = 0
        # runtime-settable fault injection (POST /v1/fault): posted
        # keys OVERRIDE the environment (an explicit 0 disables an
        # env-seeded fault); absent keys fall back to the environment,
        # so `{}` restores env-ruled mode — the overlay is never
        # one-way
        self.fault_config: Dict[str, int] = {}
        register_owner(self, lock_attrs=("_tasks_lock", "_fault_lock"))

    # ------------------------------------------------- task registry
    # The locked read/write surface: handler threads, task threads,
    # and expiry sweeps all go through these (the bare dict used to be
    # mutated from ThreadingHTTPServer handler threads while
    # create_task's expiry sweep iterated it — the unlocked-shared-
    # write shape this PR's concurrency pass exists to catch).

    def get_task(self, task_id: str) -> Optional[_Task]:
        with self._tasks_lock:
            return self.tasks.get(task_id)

    def pop_task(self, task_id: str) -> Optional[_Task]:
        with self._tasks_lock:
            return self.tasks.pop(task_id, None)

    def register_finished_task(self, task_id: str,
                               spool: "_TaskSpool") -> None:
        """Register an already-FINISHED task whose output is a
        pre-built spool — the ICI exchange plane's landing surface
        (ISSUE 18): the coordinator runs the all_to_all partitioning
        itself after the stage barrier and parks the per-partition
        device pages here, so consumers read them through the ONE
        spool data plane (mesh-local fast path or HTTP, token-indexed
        re-fetch, ack/release, task expiry) with no new protocol."""
        t = _Task(task_id)
        with t.lock:
            t.spool = spool
            t.done = True
        with self._tasks_lock:
            self.tasks[task_id] = t

    def serve_cached_fragment(self, task_id: str, key: str) -> Dict:
        """Fleet cache probe target (ISSUE 19): if this process's
        result cache holds ``key``, park its host pages in a
        pre-finished single-partition task spool (the
        register_finished_task landing surface) and report the hit —
        the prober then reads the pages over the ordinary pooled
        spool-fetch plane. A miss is one cheap dict probe."""
        from presto_tpu.cache import shared_cache_if_exists

        rc = shared_cache_if_exists()
        if rc is None or not task_id or not key:
            return {"hit": False}
        pages = rc.get_pages(key)
        if pages is None:
            return {"hit": False}
        spool = _TaskSpool(1, 0)
        for page in pages:
            spool.put_page(
                0, page,
                rows=int(XF.np_host(
                    page.valid, label="cache-remote-serve").sum()))
        self.register_finished_task(task_id, spool)
        rc.count_remote()
        return {"hit": True, "taskId": task_id,
                "pages": len(pages)}

    def task_count(self) -> int:
        with self._tasks_lock:
            return len(self.tasks)

    # -------------------------------------------------- fault injection
    def set_fault_config(self, cfg: Dict[str, int]) -> None:
        """Install a runtime fault config and RESET the call counters —
        'kill after n fetches' / 'drop every nth' count from the posted
        schedule, not from process-lifetime totals accumulated across
        earlier chaos iterations."""
        with self._fault_lock:
            self.fault_config = cfg
            self._results_calls = 0
            self._submit_calls = 0
            self._corrupt_calls = 0

    def _fault(self, name: str) -> int:
        if name in self.fault_config:
            return int(self.fault_config[name])
        return int(os.environ.get(name, "0") or 0)

    def maybe_inject_fault(self) -> bool:
        """SURVEY §6.3: faults inject at the host page proxy (delay /
        drop / kill); returns True when this fetch should fail with
        HTTP 500. Token-indexed re-fetch makes drops recoverable; a
        KILL is the real thing — the process hard-exits, recoverable
        only by the coordinator's task-retry re-dispatch."""
        delay = self._fault("FAULT_DELAY_MS")
        if delay:
            time.sleep(delay / 1000.0)
        with self._fault_lock:
            self._results_calls += 1
            calls = self._results_calls
        kill_after = self._fault("FAULT_KILL_AFTER_FETCHES")
        if kill_after and calls > kill_after:
            # worker death mid-query: bypass every finally/atexit, like
            # a real OOM-kill or host loss
            os._exit(137)
        drop = self._fault("FAULT_DROP_EVERY")
        if drop and calls % drop == 0:
            return True
        return False

    def maybe_corrupt_blob(self, blob: bytes) -> bytes:
        """FAULT_SPOOL_CORRUPT_EVERY=n: bit-flip one byte of every nth
        served results body (ISSUE 20 satellite) — proves the PR-16
        PageWireError loud-fail contract END TO END: the consumer's
        decode rejects the frame BEFORE its token advances, retries
        the same token boundedly, then climbs the replay ladder to a
        surviving replica or fails the query cleanly. Never garbage
        rows."""
        every = self._fault("FAULT_SPOOL_CORRUPT_EVERY")
        if not every or not blob:
            return blob
        with self._fault_lock:
            self._corrupt_calls += 1
            if self._corrupt_calls % every:
                return blob
        flipped = bytearray(blob)
        flipped[len(flipped) // 2] ^= 0x01
        return bytes(flipped)

    def maybe_inject_submit_fault(self) -> bool:
        """HTTP 500 on every nth /v1/task submit — exercises the
        coordinator's submit-retry-to-a-different-worker path."""
        drop = self._fault("FAULT_SUBMIT_DROP_EVERY")
        if drop:
            with self._fault_lock:
                self._submit_calls += 1
                if self._submit_calls % drop == 0:
                    return True
        return False

    # ------------------------------------------------------------ tasks
    MAX_RETAINED_TASKS = 32
    # spooled tasks expire far later: their partitions are REPLAY
    # inputs for downstream stage-DAG tasks (the scheduler releases
    # them explicitly via DELETE/ack at query end) — evicting one
    # mid-query would turn a healthy worker into a [source-lost] node
    MAX_RETAINED_SPOOLED = 256

    def create_task(self, req: Dict) -> _Task:
        # expire oldest finished tasks (reference: SqlTaskManager task
        # expiry) so a long-lived worker's page buffers are bounded.
        # Registry mutation happens under _tasks_lock (handler threads
        # create concurrently); the evictees' buffer frees happen
        # OUTSIDE it so spool-file cleanup never stalls task lookups.
        doomed: List[_Task] = []
        with self._tasks_lock:
            for pool, cap in (
                ([tid for tid, t in self.tasks.items()
                  if t.done and t.spool is None],
                 self.MAX_RETAINED_TASKS),
                ([tid for tid, t in self.tasks.items()
                  if t.done and t.spool is not None],
                 self.MAX_RETAINED_SPOOLED),
            ):
                while len(pool) > cap:
                    old = self.tasks.pop(pool.pop(0), None)
                    if old is not None:
                        doomed.append(old)
            task = _Task(req.get("taskId") or f"t{len(self.tasks)}")
            self.tasks[task.task_id] = task
        for old in doomed:
            with old.lock:
                old.free()
        t = threading.Thread(target=self._run_task, args=(task, req),
                             daemon=True)
        t.start()
        return task

    def _run_task(self, task: _Task, req: Dict) -> None:
        # worker-side lifecycle tracing (ISSUE 9): when the coordinator
        # traces the query, the payload carries trace=true and this
        # task records queue/run (+ the executor's attempt) spans,
        # anchored at task creation, shipped back on the status plane
        wtr = None
        if req.get("trace"):
            from presto_tpu import obs as OBS

            wtr = OBS.QueryTrace(task.task_id,
                                 anchor_mono=task.created_mono,
                                 anchor_wall=task.created_wall)
            wtr.complete("queue", task.task_id, 0.0, wtr.now())
        run_t0 = wtr.now() if wtr is not None else 0.0
        try:
            # FAULT_TASK_EXEC_DELAY_MS: stall task EXECUTION (not the
            # fetch path) — makes this worker a deterministic
            # straggler so the scheduler's speculation policy can be
            # exercised without wall-clock races
            exec_delay = self._fault("FAULT_TASK_EXEC_DELAY_MS")
            if exec_delay:
                time.sleep(exec_delay / 1000.0)
            from presto_tpu.connectors.split_filter import (
                HashSplitConnector,
            )
            from presto_tpu.runner import LocalRunner

            index, count = int(req["splitIndex"]), int(req["splitCount"])
            if req.get("splitMode") == "hash":
                # hash-repartition exchange: co-partitioned scans
                # (see HashSplitConnector); the spec is keyed
                # "catalog.table" so a same-named table in another
                # catalog replicates untouched
                part_cols = req["partitionColumns"]
                catalogs = {
                    name: HashSplitConnector(
                        conn,
                        {t.split(".", 1)[1]: c
                         for t, c in part_cols.items()
                         if t.split(".", 1)[0] == name},
                        index, count,
                    )
                    for name, conn in self.catalogs.items()
                }
            elif req.get("splitTable"):
                split_table = req["splitTable"]
                catalogs = {
                    name: SplitFilterConnector(conn, split_table,
                                               index, count)
                    for name, conn in self.catalogs.items()
                }
            elif req.get("sources"):
                # non-leaf stage-DAG fragment: no scans to split —
                # inputs arrive through the spooled-exchange sources
                catalogs = dict(self.catalogs)
            else:
                # a leaf payload with neither a split assignment nor
                # sources must fail LOUDLY: executing it over unsplit
                # catalogs would have every worker scan the full table
                # and the coordinator concatenate N identical copies
                raise ValueError(
                    "task payload carries neither a split assignment "
                    "(splitTable/splitMode) nor spooled-exchange "
                    "sources — refusing to run the fragment unsplit"
                )
            session = Session(catalog=self.default_catalog or
                              next(iter(catalogs)))
            for k, v in (req.get("session") or {}).items():
                session.set(k, v)
            runner = LocalRunner(
                catalogs, page_rows=self.page_rows,
                default_catalog=session.catalog, session=session,
            )
            if req.get("fragment") is not None:
                # plan SHIPPING (reference: TaskUpdateRequest carrying a
                # serialized PlanFragment): execute exactly the subtree
                # the coordinator planned — no worker-side re-planning
                from presto_tpu.dist import plan_serde

                partial = plan_serde.loads(req["fragment"])
            else:
                # legacy SQL replay (pre-round-5 protocol, kept for
                # mixed-version peers): re-plan and take the same cut
                plan = runner.plan(req["sql"])
                cut = find_partial_cut(plan)
                if cut is None:
                    raise ValueError("no aggregation cut in fragment")
                partial = dataclasses.replace(cut, step="partial")
            ex = runner.executor
            runner.apply_session()
            if wtr is not None:
                # the fragment executor records its attempt spans into
                # the task trace too (overflow-ladder visibility ships
                # to the coordinator with the queue/run phases)
                from presto_tpu import obs as OBS

                OBS.attach(ex, wtr)
            sources = req.get("sources") or {}
            nparts = int(req.get("outputPartitions") or 0)
            out_keys = tuple(req.get("outputKeys") or ())
            spooled = bool(sources) or nparts > 0
            if sources:
                # stage-DAG ingest: RemoteSource suppliers fetching
                # this task's input partitions from upstream tasks'
                # spools (worker-to-worker exchange; dist/spool.py).
                # A persistently unreachable source fails the task
                # with a [source-lost ...] marker the scheduler uses
                # to replay the upstream task instead of just this one
                from presto_tpu.dist import spool as SPOOL

                backoff = (
                    int(session.get("retry_backoff_ms")) / 1000.0
                )
                for key, spec in sources.items():
                    ex.remote_sources[key] = (
                        lambda spec=spec: SPOOL.iter_source_pages(
                            spec, retries=3, backoff_s=backoff,
                            deadline=ex.query_deadline,
                            # mesh-local fast path: a same-process
                            # producer's spool serves Pages directly
                            on_local=ex.count_mesh_local,
                        )
                    )

            # Worker-side overflow discipline: the executor's shared
            # query-scope retry ladder (Executor.stream_fragment) —
            # pages buffer locally and publish only after the
            # fragment's OR-reduced overflow flags clear, so a
            # truncated page set can NEVER reach the coordinator as a
            # silent result. On overflow the fragment re-runs with 4x
            # capacities (the coordinator's long-poll tolerates the
            # delay); persistent overflow fails the task loudly via
            # task.error.
            if spooled:
                from presto_tpu.dist import spool as SPOOL

                # spooled-exchange emit: partition each host page by
                # hash(outputKeys) % P (P=1 collapses to a single
                # gather/broadcast partition), serialize per
                # partition, and stream STRAIGHT into the tiered
                # spool — blobs past the resident budget go to the
                # disk tier DURING execution, so spool_exchange_bytes
                # bounds peak worker memory for large exchanges. The
                # spool stays unpublished (task.spool None ⇒
                # consumers long-poll) until the attempt completes
                # overflow-free, and on_attempt resets it so a
                # boosted retry never double-spools.
                state = {"spool": None}

                def on_attempt() -> None:
                    if state["spool"] is not None:
                        state["spool"].close()
                    state["spool"] = _TaskSpool(
                        max(nparts, 1),
                        int(session.get("spool_exchange_bytes")),
                        spill_dir=session.get("spill_path") or None,
                    )

                dev_exchange = ex._device_exchange_on()
                mesh_raw = bool(req.get("meshExchange"))

                def emit(page) -> int:
                    if mesh_raw:
                        # ICI exchange plane (ISSUE 18): spool the RAW
                        # page to partition 0 untouched — partitioning
                        # happens in the coordinator's post-barrier
                        # all_to_all program, and the per-partition
                        # stats plane moves there with it (no
                        # spool-stats d2h pull, no hashing, no P-way
                        # compaction on this side of the edge)
                        state["spool"].put_page(0, page, rows=0)
                        return 1
                    if dev_exchange:
                        # device tier (ISSUE 13): partition + compact
                        # ON DEVICE (dist/spool.device_partition_pages
                        # — one jitted program, skew joins the boosted
                        # ladder) and spool the partition Pages
                        # themselves; host bytes materialize lazily
                        # only for HTTP (remote/replay) fetches. The
                        # ROOFLINE §11 d2h-at-emit term deletes here.
                        # with_counts: the same program also emits the
                        # per-partition row counts (spool-stats plane)
                        pp, counts = SPOOL.device_partition_pages(
                            ex, page, out_keys, max(nparts, 1),
                            with_counts=True)
                        for p, part_page in pp:
                            state["spool"].put_page(
                                p, part_page, rows=int(counts[p]))
                        return len(pp)
                    host = XF.to_host(page, label="task-emit")
                    n = 0
                    for p, part_page in SPOOL.partition_host_page(
                            host, out_keys, max(nparts, 1)):
                        # host pages: the validity mask is already
                        # host numpy — the exact-count read is free
                        rows = int(XF.np_host(part_page.valid).sum())
                        state["spool"].put(
                            p, serde.serialize_page(part_page),
                            rows=rows)
                        n += 1
                    return n

                ex.skew_preengaged = bool(req.get("skewHint"))
                ex.stream_fragment(
                    partial, emit, cancelled=lambda: task.cancelled,
                    on_attempt=on_attempt,
                )
                if wtr is not None:
                    wtr.complete("run", task.task_id, run_t0,
                                 wtr.now(),
                                 spooled=state["spool"].page_count)
                with task.lock:
                    if wtr is not None:
                        task.spans = wtr.export()
                    task.spool = state["spool"]
                    task.boost_retries = ex.capacity_boost_retries
                    task.skew_preempted = ex.skew_preempted
                    task.done = True
            else:
                def emit(page) -> bytes:
                    return serde.serialize_page(
                        XF.to_host(page, label="task-emit"))

                blobs: List = ex.stream_fragment(
                    partial, emit, cancelled=lambda: task.cancelled
                )
                if wtr is not None:
                    wtr.complete("run", task.task_id, run_t0,
                                 wtr.now(), pages=len(blobs))
                with task.lock:
                    if wtr is not None:
                        task.spans = wtr.export()
                    task.pages.extend(blobs)
                    task.boost_retries = ex.capacity_boost_retries
                    task.done = True
        except Exception as e:  # noqa: BLE001 - task failures surface
            # to the coordinator via the X-Task-Error results header
            # (real error text, no fetch-retry spinning), never as a
            # hung task
            if wtr is not None:
                wtr.complete("run", task.task_id, run_t0, wtr.now(),
                             error=repr(e)[:200])
            with task.lock:
                if wtr is not None:
                    task.spans = wtr.export()
                task.error = repr(e)[:400]
                task.done = True


class WorkerServer(TaskRuntime):
    """One worker process's task runtime behind its own HTTP server."""

    def __init__(self, catalogs, *, port: int = 0, node_id: str = "w0",
                 default_catalog: Optional[str] = None,
                 page_rows: int = 1 << 16):
        super().__init__(catalogs, node_id=node_id,
                         default_catalog=default_catalog,
                         page_rows=page_rows)
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                          _WorkerHandler)
        self._httpd.app = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------- lifecycle
    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        # same-process placement registry: consumers in THIS process
        # take spooled Pages directly (mesh-local exchange fast path)
        register_local_runtime(f"http://127.0.0.1:{self.port}", self)
        return self.port

    def stop(self) -> None:
        # unregister FIRST: a stopped worker must look remote-and-dead
        # to local consumers (the forced-fallback replay path), never
        # serve stale spools through the fast path
        unregister_local_runtime(f"http://127.0.0.1:{self.port}")
        self._httpd.shutdown()


def main() -> int:  # pragma: no cover - subprocess entry
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--suite", default="tpch")
    parser.add_argument("--scale", type=float, default=0.01)
    parser.add_argument("--node-id", default="w0")
    parser.add_argument("--page-rows", type=int, default=1 << 16)
    args = parser.parse_args()

    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.connectors.tpch import TpchConnector

    cls = TpchConnector if args.suite == "tpch" else TpcdsConnector
    srv = WorkerServer(
        {args.suite: cls(scale=args.scale)}, port=args.port,
        node_id=args.node_id, default_catalog=args.suite,
        page_rows=args.page_rows,
    )
    port = srv.start()
    print(json.dumps({"port": port, "nodeId": args.node_id}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
