"""Worker process: the /v1/task control plane + page-buffer data plane.

Reference: presto-main server/TaskResource.java (task create/status/
cancel), execution/SqlTaskManager.java (task registry + execution),
execution/buffer/OutputBuffer (token-indexed page buffer consumed by
HttpPageBufferClient with at-least-once + token-dedupe semantics).

The TPU-native shape: one worker process = one host driving its local
devices. A task carries a SERIALIZED physical-plan fragment
(dist/plan_serde.py — the reference's TaskUpdateRequest PlanFragment)
plus a split assignment; the worker deserializes and executes exactly
the subtree the coordinator planned, restricted to its split share
(round-robin or hash-co-partitioned scans), and buffers serialized
pages (dist/serde.py) for token-indexed fetch. Legacy peers may still
send (sql, role) for worker-side replay.

Fault-injection hooks (SURVEY §6.3: inject at the host page proxy —
ICI collectives cannot be faulted): FAULT_DELAY_MS delays every
results fetch; FAULT_DROP_EVERY=n returns HTTP 500 on every nth fetch;
FAULT_KILL_AFTER_FETCHES=n hard-exits the worker PROCESS once n result
fetches have been served (worker death mid-query — the coordinator's
task-retry path re-dispatches the fragment to a survivor);
FAULT_SUBMIT_DROP_EVERY=n returns HTTP 500 on every nth task submit
(exercises the coordinator's submit retry). Each knob reads the
runtime `fault_config` posted via POST /v1/fault as an OVERLAY on the
environment: posted keys win (an explicit 0 disables an env-seeded
fault), absent keys fall back to the environment, and `{}` restores
pure env-ruled mode (tools/chaos.py reconfigures live workers between
iterations without reboots). Token-indexed re-fetch makes drops
recoverable
(at-least-once); kills are recoverable only with task_retry_attempts>0.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from presto_tpu.connectors.split_filter import SplitFilterConnector
from presto_tpu.dist import serde
from presto_tpu.exec import plan as P
from presto_tpu.session import Session


class _Task:
    def __init__(self, task_id: str):
        self.task_id = task_id
        self.pages: List[bytes] = []
        self.done = False
        self.error: Optional[str] = None
        self.cancelled = False
        self.lock = threading.Lock()


def find_partial_cut(plan: P.PhysicalNode) -> Optional[P.Aggregation]:
    """The topmost single-step aggregation — the PARTIAL/FINAL split
    point for the DCN boundary (reference: AddExchanges splitting
    AggregationNode into PARTIAL below / FINAL above the exchange)."""
    if isinstance(node := plan, P.Aggregation) and node.step == "single":
        return node
    for c in plan.children():
        hit = find_partial_cut(c)
        if hit is not None:
            return hit
    return None


def row_local_scan_count(node: P.PhysicalNode,
                         split_table: str) -> Optional[int]:
    """How many times ``split_table`` is scanned under ``node``, or
    None when the subtree is not ROW-LOCAL — i.e. when the multiset of
    its output rows is NOT the disjoint union of the outputs over a
    row-partition of split_table (all other tables replicated).

    Row-local shapes: Filter / Project / Exchange / TableScan / INNER
    hash joins. Inner joins distribute over a partition of any single
    table (each result row maps to exactly one row of it); outer/semi/
    anti/cross joins, aggregations, sorts, limits, windows, and
    MarkDistinct do not (a MarkDistinct would mark first-occurrence
    per worker and double-count values spanning workers)."""
    if isinstance(node, P.TableScan):
        return 1 if node.table == split_table else 0
    if isinstance(node, (P.Filter, P.Project, P.Exchange)):
        return row_local_scan_count(node.source, split_table)
    if isinstance(node, P.HashJoin):
        if node.join_type != "inner":
            return None
        left = row_local_scan_count(node.left, split_table)
        right = row_local_scan_count(node.right, split_table)
        if left is None or right is None:
            return None
        return left + right
    return None


def fanout_safe(cut: P.Aggregation, split_table: str) -> bool:
    """Whether the PARTIAL subtree distributes over a round-robin
    partition of split_table's rows: decomposable aggregates with no
    DISTINCT masks, and a row-local source with exactly ONE scan of
    the split table (see row_local_scan_count). Queries outside this
    shape use the union-cut fallback (find_union_cut) or run local."""
    if any(s.mask is not None for s in cut.aggregates):
        return False
    return row_local_scan_count(cut.source, split_table) == 1


def find_union_cut(plan: P.PhysicalNode,
                   split_table: str) -> Optional[P.PhysicalNode]:
    """The TOPMOST row-local subtree scanning split_table exactly once
    — the general distribution shape for plans with no decomposable
    aggregation cut (reference: a SOURCE_DISTRIBUTION leaf fragment
    under a GATHER exchange; SqlQueryScheduler runs the leaf stage on
    every worker and the coordinator consumes the union). Workers
    execute the subtree over their split share; the coordinator
    replaces it with a RemoteSource and runs everything above (sort /
    topN / window / non-decomposable aggregation) over the unioned
    pages. Returns None when no useful cut exists (a bare scan or a
    pure projection of one is not worth shipping: generation is
    cheaper than the wire — the cut must contain a join or filter)."""

    def has_work(n) -> bool:
        if isinstance(n, (P.HashJoin, P.Filter)):
            return True
        return any(has_work(c) for c in n.children())

    n = row_local_scan_count(plan, split_table)
    if n == 1 and has_work(plan):
        return plan
    for c in plan.children():
        hit = find_union_cut(c, split_table)
        if hit is not None:
            return hit
    return None


def hash_fanout_plan(cut: P.Aggregation, catalogs,
                     partition_threshold: int = 1 << 17):
    """Co-partitioning spec for a PARTITIONED JOIN fan-out below an
    aggregation cut; decomposability of the aggregates follows
    fanout_safe's rules (no DISTINCT masks). See hash_fanout_source."""
    if any(s.mask is not None for s in cut.aggregates):
        return None
    return hash_fanout_source(cut.source, catalogs,
                              partition_threshold)


def hash_fanout_source(root: P.PhysicalNode, catalogs,
                       partition_threshold: int = 1 << 17):
    """Co-partitioning spec for a PARTITIONED JOIN fan-out (the DCN
    hash-repartition exchange; reference: AddExchanges choosing
    REPARTITION and inserting hash exchanges on both join sides).

    Returns {table: partition_column} covering every BIG scanned table
    (row_count >= partition_threshold), or None when the shape does
    not co-partition. Valid shape under ``root``: Filter / Project /
    Exchange / TableScan / INNER hash joins; every join with big
    tables on BOTH sides must equi-join on single keys that are
    provably those tables' columns (exec/plan.scan_column_of), and
    each big table must receive exactly ONE partition column; small
    tables replicate (broadcast side)."""
    parts: dict = {}
    state = {"ok": True}

    def big_tables_under(n) -> set:
        out = set()

        def walk(x):
            if isinstance(x, P.TableScan):
                if catalogs[x.catalog].row_count(x.table) >= \
                        partition_threshold:
                    out.add(x.table)
                return
            for c in x.children():
                walk(c)

        walk(n)
        return out

    def assign(table: str, column: str):
        if parts.get(table, column) != column:
            state["ok"] = False  # conflicting partition keys
        parts[table] = column

    def walk(n):
        if not state["ok"]:
            return
        if isinstance(n, (P.Filter, P.Project, P.Exchange,
                          P.TableScan)):
            for c in n.children():
                walk(c)
            return
        if isinstance(n, P.HashJoin):
            if n.join_type != "inner":
                state["ok"] = False
                return
            left_big = big_tables_under(n.left)
            right_big = big_tables_under(n.right)
            if left_big and right_big:
                # partitioned join: both sides keyed by their own
                # table columns, co-partitioned on this equi-key
                if len(n.left_keys) < 1:
                    state["ok"] = False
                    return
                lsrc = P.scan_column_of(n.left, n.left_keys[0])
                rsrc = P.scan_column_of(n.right, n.right_keys[0])
                if lsrc is None or rsrc is None:
                    state["ok"] = False
                    return
                # dictionary codes are table-local (same rule as
                # executor._keys_partitionable): equal string values
                # would hash to different workers on each side —
                # refuse string/dictionary-typed partition keys
                from presto_tpu import types as T

                for cat, table, col in (lsrc, rsrc):
                    t = catalogs[cat].table_schema(
                        table).column_type(col)
                    if T.is_string(t) or t.is_dictionary_encoded:
                        state["ok"] = False
                        return
                # the key must constrain EVERY big table on its side —
                # a second big table not keyed by this join cannot be
                # co-partitioned
                if left_big != {lsrc[1]} or right_big != {rsrc[1]}:
                    state["ok"] = False
                    return
                assign(f"{lsrc[0]}.{lsrc[1]}", lsrc[2])
                assign(f"{rsrc[0]}.{rsrc[1]}", rsrc[2])
            walk(n.left)
            walk(n.right)
            return
        state["ok"] = False

    walk(root)
    if not state["ok"] or len(parts) < 2:
        return None
    return parts


def largest_table(node: P.PhysicalNode, catalogs) -> Optional[str]:
    """The fact table to split across workers: the scanned table with
    the most rows under this subtree (SOURCE_DISTRIBUTION pick)."""
    tables = []

    def scans(n):
        if isinstance(n, P.TableScan):
            tables.append((n.catalog, n.table))
        for ch in n.children():
            scans(ch)

    scans(node)
    if not tables:
        return None
    return max(
        tables, key=lambda ct: catalogs[ct[0]].row_count(ct[1])
    )[1]


class _WorkerHandler(BaseHTTPRequestHandler):
    server_version = "presto-tpu-worker/0.3"

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    @property
    def app(self) -> "WorkerServer":
        return self.server.app  # type: ignore[attr-defined]

    def _json(self, obj, status=200, headers=()):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n) or b"{}"
        if self.path.startswith("/v1/fault"):
            # runtime fault reconfiguration (chaos harness): the posted
            # overlay replaces the previous one; {} clears every
            # RUNTIME fault and restores env-ruled mode
            self.app.set_fault_config({
                k: int(v) for k, v in json.loads(body).items()
            })
            self._json({"ok": True, "fault": self.app.fault_config})
            return
        if not self.path.startswith("/v1/task"):
            self._json({"error": "not found"}, 404)
            return
        if self.app.maybe_inject_submit_fault():
            self._json({"error": "injected submit fault"}, 500)
            return
        req = json.loads(body)
        task = self.app.create_task(req)
        self._json({"taskId": task.task_id, "state": "RUNNING"})

    def do_GET(self):
        parts = self.path.strip("/").split("/")
        if self.path.startswith("/v1/info"):
            self._json({
                "nodeId": self.app.node_id,
                "state": "ACTIVE",
                "uptime_s": round(time.time() - self.app.started, 1),
                "tasks": len(self.app.tasks),
            })
            return
        # /v1/task/{id}/results/{token}
        if len(parts) == 5 and parts[:2] == ["v1", "task"] \
                and parts[3] == "results":
            task = self.app.tasks.get(parts[2])
            if task is None:
                self._json({"error": "no such task"}, 404)
                return
            token = int(parts[4])
            if self.app.maybe_inject_fault():
                self._json({"error": "injected fault"}, 500)
                return
            # bounded long-poll until the page at `token` exists or the
            # task finishes (reference: HttpPageBufferClient long-poll)
            deadline = time.time() + 10.0
            while time.time() < deadline:
                with task.lock:
                    if task.error:
                        # X-Task-Error marks a DETERMINISTIC task
                        # failure (the fragment itself failed, not the
                        # transport): the coordinator surfaces the real
                        # message instead of spinning fetch retries
                        # against a dead task
                        self._json({"error": task.error}, 500,
                                   headers=(("X-Task-Error", "1"),))
                        return
                    if token < len(task.pages):
                        body = task.pages[token]
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "application/x-presto-pages")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.send_header("X-Next-Token", str(token + 1))
                        self.send_header("X-Done", "0")
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    if task.done:
                        self.send_response(204)
                        self.send_header("X-Done", "1")
                        self.end_headers()
                        return
                time.sleep(0.02)
            self.send_response(204)
            self.send_header("X-Done", "0")
            self.end_headers()
            return
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            task = self.app.tasks.get(parts[2])
            if task is None:
                self._json({"error": "no such task"}, 404)
                return
            self._json({
                "taskId": task.task_id,
                "state": ("FAILED" if task.error else
                          "FINISHED" if task.done else "RUNNING"),
                "pages": len(task.pages),
                "error": task.error,
            })
            return
        self._json({"error": "not found"}, 404)

    def do_DELETE(self):
        parts = self.path.strip("/").split("/")
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            task = self.app.tasks.pop(parts[2], None)
            if task is not None:
                task.cancelled = True
                with task.lock:
                    task.pages.clear()  # free the page buffer
                self._json({"taskId": task.task_id,
                            "state": "CANCELED"})
                return
        self._json({"error": "not found"}, 404)


class WorkerServer:
    """One worker process's task runtime (SqlTaskManager analog)."""

    def __init__(self, catalogs, *, port: int = 0, node_id: str = "w0",
                 default_catalog: Optional[str] = None,
                 page_rows: int = 1 << 16):
        self.catalogs = catalogs
        self.node_id = node_id
        self.default_catalog = default_catalog
        self.page_rows = page_rows
        self.tasks: Dict[str, _Task] = {}
        self.started = time.time()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                          _WorkerHandler)
        self._httpd.app = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._fault_lock = threading.Lock()
        self._results_calls = 0
        self._submit_calls = 0
        # runtime-settable fault injection (POST /v1/fault): posted
        # keys OVERRIDE the environment (an explicit 0 disables an
        # env-seeded fault); absent keys fall back to the environment,
        # so `{}` restores env-ruled mode — the overlay is never
        # one-way
        self.fault_config: Dict[str, int] = {}

    # -------------------------------------------------- fault injection
    def set_fault_config(self, cfg: Dict[str, int]) -> None:
        """Install a runtime fault config and RESET the call counters —
        'kill after n fetches' / 'drop every nth' count from the posted
        schedule, not from process-lifetime totals accumulated across
        earlier chaos iterations."""
        with self._fault_lock:
            self.fault_config = cfg
            self._results_calls = 0
            self._submit_calls = 0

    def _fault(self, name: str) -> int:
        if name in self.fault_config:
            return int(self.fault_config[name])
        return int(os.environ.get(name, "0") or 0)

    def maybe_inject_fault(self) -> bool:
        """SURVEY §6.3: faults inject at the host page proxy (delay /
        drop / kill); returns True when this fetch should fail with
        HTTP 500. Token-indexed re-fetch makes drops recoverable; a
        KILL is the real thing — the process hard-exits, recoverable
        only by the coordinator's task-retry re-dispatch."""
        delay = self._fault("FAULT_DELAY_MS")
        if delay:
            time.sleep(delay / 1000.0)
        with self._fault_lock:
            self._results_calls += 1
            calls = self._results_calls
        kill_after = self._fault("FAULT_KILL_AFTER_FETCHES")
        if kill_after and calls > kill_after:
            # worker death mid-query: bypass every finally/atexit, like
            # a real OOM-kill or host loss
            os._exit(137)
        drop = self._fault("FAULT_DROP_EVERY")
        if drop and calls % drop == 0:
            return True
        return False

    def maybe_inject_submit_fault(self) -> bool:
        """HTTP 500 on every nth /v1/task submit — exercises the
        coordinator's submit-retry-to-a-different-worker path."""
        drop = self._fault("FAULT_SUBMIT_DROP_EVERY")
        if drop:
            with self._fault_lock:
                self._submit_calls += 1
                if self._submit_calls % drop == 0:
                    return True
        return False

    # -------------------------------------------------------- lifecycle
    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()

    # ------------------------------------------------------------ tasks
    MAX_RETAINED_TASKS = 32

    def create_task(self, req: Dict) -> _Task:
        # expire oldest finished tasks (reference: SqlTaskManager task
        # expiry) so a long-lived worker's page buffers are bounded
        done = [tid for tid, t in self.tasks.items() if t.done]
        while len(done) > self.MAX_RETAINED_TASKS:
            old = self.tasks.pop(done.pop(0), None)
            if old is not None:
                with old.lock:
                    old.pages.clear()
        task = _Task(req.get("taskId") or f"t{len(self.tasks)}")
        self.tasks[task.task_id] = task
        t = threading.Thread(target=self._run_task, args=(task, req),
                             daemon=True)
        t.start()
        return task

    def _run_task(self, task: _Task, req: Dict) -> None:
        try:
            from presto_tpu.connectors.split_filter import (
                HashSplitConnector,
            )
            from presto_tpu.runner import LocalRunner

            index, count = int(req["splitIndex"]), int(req["splitCount"])
            if req.get("splitMode") == "hash":
                # hash-repartition exchange: co-partitioned scans
                # (see HashSplitConnector); the spec is keyed
                # "catalog.table" so a same-named table in another
                # catalog replicates untouched
                part_cols = req["partitionColumns"]
                catalogs = {
                    name: HashSplitConnector(
                        conn,
                        {t.split(".", 1)[1]: c
                         for t, c in part_cols.items()
                         if t.split(".", 1)[0] == name},
                        index, count,
                    )
                    for name, conn in self.catalogs.items()
                }
            else:
                split_table = req["splitTable"]
                catalogs = {
                    name: SplitFilterConnector(conn, split_table,
                                               index, count)
                    for name, conn in self.catalogs.items()
                }
            session = Session(catalog=self.default_catalog or
                              next(iter(catalogs)))
            for k, v in (req.get("session") or {}).items():
                session.set(k, v)
            runner = LocalRunner(
                catalogs, page_rows=self.page_rows,
                default_catalog=session.catalog, session=session,
            )
            if req.get("fragment") is not None:
                # plan SHIPPING (reference: TaskUpdateRequest carrying a
                # serialized PlanFragment): execute exactly the subtree
                # the coordinator planned — no worker-side re-planning
                from presto_tpu.dist import plan_serde

                partial = plan_serde.loads(req["fragment"])
            else:
                # legacy SQL replay (pre-round-5 protocol, kept for
                # mixed-version peers): re-plan and take the same cut
                plan = runner.plan(req["sql"])
                cut = find_partial_cut(plan)
                if cut is None:
                    raise ValueError("no aggregation cut in fragment")
                partial = dataclasses.replace(cut, step="partial")
            ex = runner.executor
            runner.apply_session()
            import jax

            # Worker-side overflow discipline: the executor's shared
            # query-scope retry ladder (Executor.stream_fragment) —
            # pages buffer locally and publish only after the
            # fragment's OR-reduced overflow flags clear, so a
            # truncated page set can NEVER reach the coordinator as a
            # silent result. On overflow the fragment re-runs with 4x
            # capacities (the coordinator's long-poll tolerates the
            # delay); persistent overflow fails the task loudly via
            # task.error.
            def emit(page) -> bytes:
                return serde.serialize_page(jax.device_get(page))

            blobs: List[bytes] = ex.stream_fragment(
                partial, emit, cancelled=lambda: task.cancelled
            )
            with task.lock:
                task.pages.extend(blobs)
                task.done = True
        except Exception as e:  # noqa: BLE001 - task failures surface
            # to the coordinator via the X-Task-Error results header
            # (real error text, no fetch-retry spinning), never as a
            # hung task
            with task.lock:
                task.error = repr(e)[:400]
                task.done = True


def main() -> int:  # pragma: no cover - subprocess entry
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--suite", default="tpch")
    parser.add_argument("--scale", type=float, default=0.01)
    parser.add_argument("--node-id", default="w0")
    parser.add_argument("--page-rows", type=int, default=1 << 16)
    args = parser.parse_args()

    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.connectors.tpch import TpchConnector

    cls = TpchConnector if args.suite == "tpch" else TpcdsConnector
    srv = WorkerServer(
        {args.suite: cls(scale=args.scale)}, port=args.port,
        node_id=args.node_id, default_catalog=args.suite,
        page_rows=args.page_rows,
    )
    port = srv.start()
    print(json.dumps({"port": port, "nodeId": args.node_id}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
