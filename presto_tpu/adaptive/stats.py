"""Stage-boundary exchange statistics (the spool-stats plane).

One StageStats summarizes a COMPLETED stage's spooled output across
all of its tasks: exact row/byte totals, the per-partition histogram
(partition p sums over every producer task's partition p — the
consumer task p's actual input), and per-task totals (a passthrough
consumer reads exactly one producer task's spool). Workers publish
the per-partition counts on the task status plane
(server/worker.route_task_get: spoolRows/spoolBytes), accumulated at
spool-publish time so they are exact, monotone, stable across
release, and identical after a deterministic replay.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class StageStats:
    """Observed output of one completed stage."""

    fid: int
    rows: int
    bytes: int
    # partition p summed across producer tasks (repartition edges:
    # consumer task p's exact input)
    part_rows: Tuple[int, ...]
    part_bytes: Tuple[int, ...]
    # per producer task (passthrough edges: consumer task t's input)
    task_rows: Tuple[int, ...]
    # measured post-codec wire bytes of the spool (ISSUE 17): what the
    # exchange actually ships after the per-column page codecs
    # (dist/serde.py, ROOFLINE §14 codec table). Device-resident spool
    # entries that never serialized report their raw footprint, so
    # this is an upper bound on true freight. 0 = producer predates
    # the wire-stats plane (fall back to `bytes`).
    wire_bytes: int = 0
    # bytes that moved over the device interconnect instead of the
    # wire (ISSUE 18): >0 marks a stage whose repartition edge the
    # scheduler lowered to the in-program all_to_all plane — its
    # freight never touched the spool serde/HTTP path, which the
    # broadcast-flip cost model must charge differently (a flip to
    # broadcast would move the build BACK onto the wire).
    ici_bytes: int = 0

    @property
    def row_bytes(self) -> int:
        """Observed average spool bytes per row (>=1)."""
        return max(self.bytes // max(self.rows, 1), 1)

    @property
    def freight_bytes(self) -> int:
        """The byte count broadcast-vs-partitioned costing should
        charge: measured wire bytes when the producer reported them,
        else the raw spool bytes. Per-column codecs routinely ship
        2-8x under raw (ROOFLINE §14), so costing on raw bytes
        systematically over-prices broadcast."""
        return self.wire_bytes if self.wire_bytes > 0 else self.bytes

    @property
    def max_part_rows(self) -> int:
        return max(self.part_rows) if self.part_rows else 0

    @property
    def max_task_rows(self) -> int:
        return max(self.task_rows) if self.task_rows else 0

    def skew_ratio(self) -> float:
        """max/mean over the partition histogram (1.0 = balanced;
        meaningful only for multi-partition repartition spools)."""
        if len(self.part_rows) <= 1 or self.rows <= 0:
            return 1.0
        mean = self.rows / len(self.part_rows)
        return self.max_part_rows / max(mean, 1e-9)

    def observed_rows(self, read_kind: str) -> int:
        """Upper bound on ONE consumer task's input rows under the
        given edge read kind — the value stamped into RemoteSource
        est_rows (one fragment blob serves every task, so the stamp
        must be the per-task maximum, which also keeps jit-key
        material identical across tasks)."""
        if read_kind == "repartition":
            return max(self.max_part_rows, 1)
        if read_kind == "passthrough":
            return max(self.max_task_rows, 1)
        # gather / broadcast / adaptive broadcast-read: the full set
        return max(self.rows, 1)


def stats_from_statuses(fid: int,
                        statuses: List[Dict]) -> Optional[StageStats]:
    """Sum per-task status bodies (route_task_get) into one
    StageStats. None when no task reported spool stats (legacy
    peers / non-spooled tasks) — the re-planner then simply has no
    observation for this stage."""
    per_task: List[Tuple[List[int], List[int]]] = []
    wire_total = 0
    for st in statuses:
        rows = st.get("spoolRows")
        if rows is None:
            return None
        nbytes = list(st.get("spoolBytes") or [0] * len(rows))
        per_task.append((list(rows), nbytes))
        # measured wire bytes (ISSUE 17); a task missing the field
        # charges its raw spool bytes so freight never under-counts
        wire = st.get("spoolWireBytes")
        wire_total += (sum(wire) if wire is not None else sum(nbytes))
    if not per_task:
        return None
    nparts = max(len(r) for r, _ in per_task)
    part_rows = [0] * nparts
    part_bytes = [0] * nparts
    task_rows = []
    for rows, nbytes in per_task:
        task_rows.append(sum(rows))
        for p, n in enumerate(rows):
            part_rows[p] += int(n)
        for p, n in enumerate(nbytes):
            part_bytes[p] += int(n)
    return StageStats(
        fid=fid,
        rows=sum(task_rows),
        bytes=sum(part_bytes),
        part_rows=tuple(part_rows),
        part_bytes=tuple(part_bytes),
        task_rows=tuple(task_rows),
        wire_bytes=wire_total,
    )
