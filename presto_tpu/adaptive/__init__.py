"""Adaptive execution (ISSUE 15 / ROADMAP item 3): runtime
re-planning at spooled-exchange stage boundaries.

Reference: the Presto-era "adaptive query execution" direction — the
engine plans once from connector estimates (AddExchanges /
DetermineJoinDistributionType consult static stats), and every
misestimate is paid for at runtime as overflow-ladder re-runs,
capacity boosts, and skew discovered via failed attempts. The spooled
stage DAG (PR 7) creates exactly the barrier adaptive engines exploit:
every upstream stage's output is fully materialized on the producing
workers BEFORE the consumer stage dispatches, so at each stage
boundary the coordinator holds EXACT per-partition row/byte counts
(the spool-stats plane, server/worker._TaskSpool.part_stats) and the
not-yet-dispatched suffix of the DAG is still just data.

The Replanner re-optimizes that suffix:

  (a) DISTRIBUTION FLIPS — a repartitioned build side whose observed
      bytes fit one chip's broadcast share is re-read broadcast-style
      (every partition of every producer task; their union is the
      full build) and the sibling not-yet-dispatched repartition
      producer degrades to a passthrough edge, skipping its per-page
      hashing and P-way compaction entirely;
  (b) JOIN RE-ORDER — when both sides of a downstream join are
      observed, the smaller side becomes the build (inner joins swap
      sides behind a channel-restoring Project);
  (c) CAPACITY RE-SEEDING — downstream Aggregation capacities
      re-bucket onto the shapes.py ladder from observed input
      cardinality and RemoteSource leaves are stamped with
      est_rows, so first runs start at the settled bucket instead of
      climbing the boost ladder (the first-run analog of the PR-9
      observed-stats profiles, which only help the SECOND run);
  (d) SKEW PRE-ENGAGEMENT — a hot partition in the spool histogram
      pre-engages the position-chunked join rebalance on the consumer
      (skew_preempted) instead of discovering the hot key by
      overflowing a buffer.

Every mutated DAG re-verifies through plan_check.verify_dag before
anything dispatches; a failed re-verify rolls the mutation back and
the static plan runs (adaptive_replan_rejected — loud, never wrong).
Re-plans are bounded per query by `adaptive_max_replans`, and the
whole path is gated by the tri-state `adaptive_execution` session
property (auto = on under the stage scheduler). Mutated capacities
are ladder values, so re-planned fragments share the existing
program cache (jit-key material stays canonical).
"""

from presto_tpu.adaptive.replanner import (  # noqa: F401
    ReplanOutcome,
    Replanner,
)
from presto_tpu.adaptive.stats import (  # noqa: F401
    StageStats,
    stats_from_statuses,
)
