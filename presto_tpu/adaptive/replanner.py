"""The stage-boundary re-planner (see package docstring).

Pure DAG surgery: the Replanner holds the live StageDag, accumulates
StageStats observations as stages complete, and `replan()` mutates
the not-yet-dispatched suffix in place — distribution flips, join
re-orders, capacity re-buckets, skew hints — then re-verifies the
whole mutated DAG through plan_check.verify_dag and ROLLS BACK on any
violation. The scheduler (dist/scheduler.py) is a thin driver; the
seeded-misestimate audit (tools/plan_audit.py) drives the same class
with synthetic stats, so the mutation space stays strictly inside
what the verifier can prove.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from presto_tpu.adaptive.stats import StageStats
from presto_tpu.dist import fragmenter as F
from presto_tpu.exec import plan as P
from presto_tpu.exec import shapes as SH

# a partition histogram whose max exceeds this multiple of the mean
# marks the exchange skewed — consumers pre-engage the position-
# chunked rebalance instead of discovering the hot key via overflow.
# The histogram is only as fine as the consumer task count, so a hot
# key's measurable ratio is bounded by nparts: 3x is already deep
# skew on small pools while staying far above hash fluctuation
SKEW_RATIO = 3.0

# re-bucket an Aggregation capacity DOWN only for >=4x over-estimates
# (a tightened capacity saves sort/scatter work that scales with
# slots; below 4x the ladder bucket often coincides anyway)
TIGHTEN_FACTOR = 4

# build sides bigger than this multiple of the probe swap sides on an
# inner join (2x: swapping costs a channel-restoring Project, so only
# clear wins re-order)
SWAP_RATIO = 2

# ICI-vs-wire bandwidth handicap for broadcast flips (ISSUE 18): a
# stage whose exchange already lowered to the in-program all_to_all
# (StageStats.ici_bytes > 0) moved its freight over the device
# interconnect — a flip to broadcast would move the SAME bytes back
# onto the spool serde/HTTP wire, which ships this many times slower
# per byte (ROOFLINE §16 measures the q3-family rung; the TPU v4
# ICI:DCN ratio is far larger still). The flip must fit a budget
# shrunk by this ratio before it can win.
ICI_WIRE_RATIO = 16


@dataclasses.dataclass
class ReplanOutcome:
    """What one replan() call did (or why it was rejected)."""

    mutated_fids: List[int]
    dist_flips: int = 0
    capacity_seeds: int = 0
    skew_hints: int = 0
    root_mutated: bool = False
    rejected: bool = False
    reason: str = ""


class Replanner:
    """Re-optimizes a StageDag's not-yet-dispatched suffix from
    observed exchange stats. One instance per query (the
    adaptive_max_replans bound is per query)."""

    def __init__(self, ex, dag, *, broadcast_rows=None,
                 broadcast_bytes=None, max_replans: int = 4,
                 skew_ratio: float = SKEW_RATIO, strict: bool = False):
        self.ex = ex
        self.dag = dag
        self.broadcast_rows = broadcast_rows
        self.broadcast_bytes = broadcast_bytes
        self.max_replans = int(max_replans)
        self.skew_ratio = float(skew_ratio)
        self.strict = strict
        self.stats: Dict[int, StageStats] = {}
        self.replans_applied = 0
        self._dispatched: Set[int] = set()

    # ------------------------------------------------------ observe
    def observe(self, st: StageStats) -> None:
        self.stats[st.fid] = st

    # ------------------------------------------------------ helpers
    @staticmethod
    def _fid_of(n) -> Optional[int]:
        if isinstance(n, P.RemoteSource) and n.key.startswith("stage"):
            try:
                return int(n.key[len("stage"):])
            except ValueError:
                return None
        return None

    def _fits_broadcast(self, st: StageStats) -> bool:
        """The stats-driven AddExchanges broadcast test re-run on
        MEASURED numbers: the whole observed build must fit one
        chip's broadcast byte share (or the row threshold when no
        byte share was wired) and stay under the per-buffer row
        ceiling. The byte test charges freight_bytes (ISSUE 17) —
        broadcast ships the spool over the WIRE once per consumer,
        and after the per-column page codecs (ROOFLINE §14 table)
        the measured wire bytes run 2-8x under the raw spool bytes
        the static planner had to assume; costing on raw bytes
        over-prices broadcast and leaves codec-friendly builds
        (scan-ordered keys, low-cardinality dictionaries) stuck on
        the repartition path."""
        if st.rows > SH.SAFE_BUFFER_ROWS:
            return False
        if self.broadcast_bytes is not None:
            budget = int(self.broadcast_bytes)
            if st.ici_bytes > 0:
                # the observed exchange rode the ICI plane (ISSUE
                # 18): its partitioned freight never touched the
                # wire, so a broadcast flip would ADD serde+HTTP
                # traffic the current plan does not pay — charge it
                # the measured bandwidth handicap
                budget //= ICI_WIRE_RATIO
            return st.freight_bytes <= budget
        if self.broadcast_rows is not None:
            return st.rows <= int(self.broadcast_rows)
        return False

    def _read_kind(self, consumer_fid: int, fid: int) -> str:
        return self.dag.read_kind(consumer_fid, fid)

    # ---------------------------------------------- (a)+(b): joins
    def _try_flip(self, join: P.HashJoin, consumer_fid: int,
                  out: ReplanOutcome) -> Optional[P.PhysicalNode]:
        """One join's runtime distribution decision. Returns a
        replacement node (possibly Project-wrapped after a side
        swap) or None when nothing changed."""
        changed = False
        lf, rf = self._fid_of(join.left), self._fid_of(join.right)
        lst = self.stats.get(lf) if lf is not None else None
        rst = self.stats.get(rf) if rf is not None else None
        wrap = None
        # (b) join re-order, two triggers (inner joins only — swapping
        # an outer join changes which side's rows are preserved):
        #   - both sides observed and the current build is the
        #     clearly-bigger one;
        #   - the PROBE completed tiny (fits a broadcast) while the
        #     build-side producer has not even dispatched — stages run
        #     in topo order and the probe's fragment cuts first, so
        #     this is the window where the flip can still spare the
        #     pending producer its whole repartition pass.
        swap = False
        if join.join_type == "inner" and lst is not None:
            if rst is not None:
                swap = rst.rows > SWAP_RATIO * max(lst.rows, 1)
            else:
                swap = (rf is not None
                        and rf not in self._dispatched
                        and self._fits_broadcast(lst)
                        and self.dag.fragment(lf).output_kind
                        == "repartition"
                        and self._read_kind(consumer_fid, lf)
                        == "repartition")
        if swap:
            # Channel order is part of the join's contract (left
            # channels then right), so the swapped join hides behind
            # a restoring Project.
            lt = self.ex.output_types(join.left)
            rt = self.ex.output_types(join.right)
            join = dataclasses.replace(
                join, left=join.right, right=join.left,
                left_keys=join.right_keys, right_keys=join.left_keys,
            )
            from presto_tpu.expr.ir import InputRef

            exprs = tuple(
                InputRef(len(rt) + i, t) for i, t in enumerate(lt)
            ) + tuple(InputRef(i, t) for i, t in enumerate(rt))

            def wrap(j, _exprs=exprs):
                return P.Project(j, _exprs)

            lf, rf = rf, lf
            lst, rst = rst, lst
            out.dist_flips += 1
            changed = True
        if (rst is not None and rf is not None
                and join.join_type in ("inner", "left", "semi", "anti")
                and self.dag.fragment(rf).output_kind == "repartition"
                and self._read_kind(consumer_fid, rf) == "repartition"
                and self._fits_broadcast(rst)):
            # (a) partitioned -> broadcast: the observed build fits
            # one chip's share, so the consumer drains EVERY partition
            # of the already-spooled build (union = full build) and
            # the join stops depending on co-location. right/full
            # joins are excluded — a replicated build would emit its
            # globally-unmatched rows once per task (_dag_safe's
            # rule). The not-yet-dispatched probe-side repartition
            # producer then degrades to a passthrough edge: with a
            # replicated build, ANY disjoint probe split joins
            # correctly, so the producer skips per-page hashing and
            # P-way compaction entirely.
            self.dag.reads[(consumer_fid, rf)] = "broadcast"
            out.dist_flips += 1
            changed = True
            if (lf is not None and lf not in self._dispatched
                    and lf not in self.stats
                    and self.dag.fragment(lf).output_kind
                    == "repartition"
                    and self.dag.fragment(lf).sharded
                    and consumer_fid >= 0
                    and self.dag.fragment(consumer_fid).sharded
                    and self.dag.consumers(lf) == [consumer_fid]):
                self.dag.fragments[lf] = dataclasses.replace(
                    self.dag.fragment(lf),
                    output_kind="passthrough", output_keys=(),
                )
                out.mutated_fids.append(lf)
        if not changed:
            return None
        return wrap(join) if wrap is not None else join

    # ------------------------------------------------ (c): reseeds
    def _observed_input(self, n: P.PhysicalNode,
                        consumer_fid: int) -> Optional[int]:
        """Exact upper bound on ONE consumer task's rows flowing out
        of this subtree, known only when every leaf is an observed
        exchange (or literal rows) under row-bounded operators."""
        fid = self._fid_of(n)
        if fid is not None:
            st = self.stats.get(fid)
            if st is None:
                return None
            return st.observed_rows(self._read_kind(consumer_fid, fid))
        if isinstance(n, P.Values):
            return len(n.rows)
        if isinstance(n, (P.Filter, P.Project)):
            return self._observed_input(n.source, consumer_fid)
        if isinstance(n, P.Limit):
            src = self._observed_input(n.source, consumer_fid)
            return None if src is None else min(
                src, n.count + n.offset)
        if isinstance(n, P.Union):
            parts = [self._observed_input(s, consumer_fid)
                     for s in n.sources]
            if any(p is None for p in parts):
                return None
            return sum(parts)
        return None

    def _reseed(self, root: P.PhysicalNode, consumer_fid: int,
                out: ReplanOutcome) -> P.PhysicalNode:
        """Stamp observed est_rows onto completed RemoteSource edges
        and re-bucket Aggregation capacities whose input cardinality
        is now measured — both quantized onto the shapes.py ladder,
        so mutated fragments share the existing program cache."""

        def walk(n):
            if isinstance(n, P.RemoteSource):
                # stamp the edge node itself; NEVER descend into
                # .origin — origins are verification metadata carrying
                # whole producer subtrees (their interior joins belong
                # to OTHER fragments and must not be flipped/stamped
                # through this consumer's walk)
                fid = self._fid_of(n)
                st = self.stats.get(fid) if fid is not None else None
                if st is not None:
                    est = st.observed_rows(
                        self._read_kind(consumer_fid, fid))
                    if n.est_rows != est:
                        out.capacity_seeds += 1
                        return dataclasses.replace(n, est_rows=est)
                return n
            n2 = F._map_children(n, walk)
            if isinstance(n2, P.Aggregation) and n2.group_channels:
                obs = self._observed_input(n2.source, consumer_fid)
                if obs is not None:
                    # groups <= input rows, so bucket(observed input)
                    # can never overflow — raising kills the boost
                    # ladder on under-estimates, tightening (>=4x
                    # over-estimates only) trims slot-scaled work.
                    # Clamped under the governed buffer ceiling; a
                    # genuinely huge state still takes the governor's
                    # partitioned passes, exactly as a static plan
                    # with honest estimates would.
                    newcap = min(SH.bucket(obs), SH.SAFE_BUFFER_ROWS)
                    oldcap = SH.bucket(n2.capacity)
                    if (newcap > oldcap
                            or newcap * TIGHTEN_FACTOR <= oldcap):
                        out.capacity_seeds += 1
                        return dataclasses.replace(
                            n2, capacity=newcap)
            return n2

        return walk(root)

    # ------------------------------------------------------ replan
    def replan(self, dispatched: Set[int]) -> Optional[ReplanOutcome]:
        """Re-optimize every not-yet-dispatched fragment plus the
        coordinator root from the accumulated stats. Mutates the DAG
        in place and returns the outcome; None = no change. A mutated
        DAG that fails verify_dag (or exceeds adaptive_max_replans)
        rolls back completely — the static plan runs (rejected=True,
        counted loudly by the caller)."""
        if not self.stats or self.max_replans <= 0:
            # max_replans=0 pins observe-only mode: stats accumulate
            # (and surface on the status plane) but the DAG never
            # mutates — a diagnostic setting, not a rejection
            return None
        dag = self.dag
        self._dispatched = set(dispatched)
        snapshot = (list(dag.fragments), dag.root, dict(dag.reads),
                    {k: dict(v) for k, v in dag.hints.items()})
        out = ReplanOutcome(mutated_fids=[])
        changed: Set[int] = set(out.mutated_fids)

        pending = [f.fid for f in dag.fragments
                   if f.fid not in dispatched]

        # (a)+(b): flips and re-orders inside pending fragments
        for fid in pending:
            frag = dag.fragment(fid)

            def walk(n, _fid=fid):
                if isinstance(n, P.RemoteSource):
                    return n  # origins are metadata, not this
                    # fragment's operators (see _reseed)
                n2 = F._map_children(n, walk)
                if isinstance(n2, P.HashJoin):
                    repl = self._try_flip(n2, _fid, out)
                    if repl is not None:
                        return repl
                return n2

            new_root = walk(frag.root)
            if new_root is not frag.root:
                dag.fragments[fid] = dataclasses.replace(
                    dag.fragment(fid), root=new_root)
                changed.add(fid)

        # (c): est stamps + capacity re-buckets (pending + root)
        for fid in pending:
            frag = dag.fragment(fid)
            new_root = self._reseed(frag.root, fid, out)
            if new_root is not frag.root:
                dag.fragments[fid] = dataclasses.replace(
                    frag, root=new_root)
                changed.add(fid)
        new_croot = self._reseed(dag.root, -1, out)
        if new_croot is not dag.root:
            dag.root = new_croot
            out.root_mutated = True

        # (d): skew pre-engagement hints on pending consumers
        for st in self.stats.values():
            if len(st.part_rows) <= 1 or \
                    st.skew_ratio() < self.skew_ratio:
                continue
            for c in dag.consumers(st.fid):
                if c not in dispatched and \
                        not dag.hints.get(c, {}).get("skew"):
                    dag.hints.setdefault(c, {})["skew"] = True
                    out.skew_hints += 1

        changed.update(out.mutated_fids)
        if not changed and not out.root_mutated \
                and not out.skew_hints and not out.dist_flips \
                and not out.capacity_seeds:
            # a reads-only flip (dag.reads mutated, trees untouched)
            # still counts as a mutation: it must verify, respect the
            # replan bound, and report — only a genuinely untouched
            # DAG short-circuits here
            return None

        def rollback():
            dag.fragments[:] = snapshot[0]
            dag.root = snapshot[1]
            dag.reads.clear()
            dag.reads.update(snapshot[2])
            dag.hints.clear()
            dag.hints.update(snapshot[3])

        if self.replans_applied >= self.max_replans:
            rollback()
            return ReplanOutcome(
                mutated_fids=[], rejected=True,
                reason=f"adaptive_max_replans={self.max_replans} "
                       f"reached")
        from presto_tpu.exec import plan_check as PC

        try:
            PC.verify_dag(self.ex, dag, strict=self.strict)
        except PC.PlanCheckError as e:
            # the fallback the ISSUE demands: a re-plan the verifier
            # cannot prove rolls back to the static plan, loudly
            rollback()
            return ReplanOutcome(
                mutated_fids=[], rejected=True,
                reason=str(e)[:400])
        self.replans_applied += 1
        out.mutated_fids = sorted(changed)
        return out
