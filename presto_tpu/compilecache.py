"""Compilation-reuse layer: persistent XLA compile cache + counters.

Reference: Presto amortizes per-query codegen with compiled-artifact
caches (ExpressionCompiler's LRU, the coordinator reusing plans across
queries). The JAX-native analog is jax's persistent compilation cache:
programs compile once per canonical shape PER MACHINE, not per process
— repeated bench rungs, repeated tier-1 runs, and worker restarts all
reload compiled executables from disk instead of re-invoking XLA (on
the axon TPU toolchain a partitioned-join program set costs 40+ min
fresh; warm it is seconds). The other half of the bargain — making the
cache actually hit — is the shared shape ladder in exec/shapes.py.

Observability: jax.monitoring hooks below count real XLA backend
compiles (`programs_compiled`, `compile_wall_s`) and persistent-cache
hits/misses (`program_cache_hits` / `persistent_cache_misses`)
process-wide; the executor snapshots them around each query and
EXPLAIN ANALYZE / tools/analyze_rung.py / tools/compile_stats.py /
bench.py report the deltas. A persistent-cache HIT does not count as a
compile — `programs_compiled == 0` on a warmed run is the contract.

Counters are process-global (jax compiles are); concurrent queries in
one process attribute each other's compiles to whichever query's
window they land in — same caveat as every process-wide metric.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from presto_tpu.obs.sanitizer import make_lock

# NOTE on jax's event semantics (verified on 0.4.37): the
# backend_compile_duration event wraps compile_or_get_cached, so it
# fires once per compiled-program REQUEST — including persistent-cache
# HITS, where its duration is the (small) retrieval time. Real
# compiles are therefore requests minus hits, and real compile wall is
# total request wall minus the hits' retrieval wall.
_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_CACHE_RETRIEVAL = "/jax/compilation_cache/cache_retrieval_time_sec"
_CACHE_HIT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS = "/jax/compilation_cache/cache_misses"

_lock = make_lock("compilecache._lock")
_raw: Dict[str, float] = {
    "requests": 0,
    "request_wall_s": 0.0,
    "hits": 0,
    "retrieval_wall_s": 0.0,
    "misses": 0,
}
# recent per-request walls (tools/compile_stats.py's per-program
# breakdown; a persistent-cache hit's wall is its retrieval time);
# bounded so a long-lived server can't grow it
_MAX_WALLS = 4096
_compile_walls: List[float] = []
_installed = False
_cache_dir: Optional[str] = None


def _on_duration(event: str, duration: float, **kw) -> None:
    if event == _BACKEND_COMPILE:
        with _lock:
            _raw["requests"] += 1
            _raw["request_wall_s"] += duration
            if len(_compile_walls) < _MAX_WALLS:
                _compile_walls.append(duration)
    elif event == _CACHE_RETRIEVAL:
        with _lock:
            _raw["retrieval_wall_s"] += duration


def _on_event(event: str, **kw) -> None:
    if event == _CACHE_HIT:
        with _lock:
            _raw["hits"] += 1
    elif event == _CACHE_MISS:
        with _lock:
            _raw["misses"] += 1


def install() -> None:
    """Register the monitoring listeners once per process. Idempotent;
    counters work with or without the persistent cache enabled."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)


def snapshot() -> Dict[str, float]:
    """Current process-wide compile counters (install()s on first use):
    programs_compiled = real XLA compiles (requests minus persistent-
    cache hits), compile_wall_s = their summed wall (request wall minus
    the hits' retrieval wall)."""
    install()
    with _lock:
        return {
            "programs_compiled": int(_raw["requests"] - _raw["hits"]),
            "compile_wall_s": max(
                _raw["request_wall_s"] - _raw["retrieval_wall_s"], 0.0
            ),
            "program_cache_hits": int(_raw["hits"]),
            "persistent_cache_misses": int(_raw["misses"]),
        }


def delta(since: Dict[str, float]) -> Dict[str, float]:
    """Counter deltas since a snapshot(), rounding the wall."""
    cur = snapshot()
    out = {k: cur[k] - since.get(k, 0) for k in cur}
    out["compile_wall_s"] = round(max(out["compile_wall_s"], 0.0), 3)
    return out


def compile_walls() -> List[float]:
    """Recent individual backend-compile walls (seconds), compile order."""
    with _lock:
        return list(_compile_walls)


def cache_dir() -> Optional[str]:
    """The enabled persistent-cache directory, or None."""
    return _cache_dir


def enable_persistent_cache(
    path: str, min_compile_secs: float = 0.0
) -> str:
    """Point jax's persistent compilation cache at ``path`` (created if
    missing) and register the counters. min_compile_secs=0 caches every
    program — the engine's programs are numerous and individually
    cheap-ish on CPU but brutal through the remote TPU compiler, and a
    retry rung only pays off if its shape was cached too. Idempotent;
    re-pointing at a different dir is allowed (last call wins)."""
    global _cache_dir
    install()
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_secs
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _cache_dir = path
    return path
