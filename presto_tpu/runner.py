"""LocalRunner: full parse → plan → execute pipeline in one process.

Reference: presto-main testing/LocalQueryRunner.java — the single-JVM
engine harness with no HTTP and no scheduler, used by planner tests and
benchmarks. Ours is additionally the building block the coordinator wraps.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu.connectors.base import Connector
from presto_tpu.exec import plan as P
from presto_tpu.exec.executor import Executor
from presto_tpu.exec.prune import prune_plan
from presto_tpu.sql import ast_nodes as N
from presto_tpu.sql.parser import parse
from presto_tpu.sql.planner import Planner


@dataclasses.dataclass
class QueryResult:
    column_names: List[str]
    rows: List[tuple]
    update_type: Optional[str] = None
    column_types: Optional[List[str]] = None


class LocalRunner:
    """mesh=None runs single-stream; passing a jax.sharding.Mesh turns
    this into the distributed runner (reference analog: LocalQueryRunner
    vs DistributedQueryRunner — same engine, exchanges become real)."""

    def __init__(
        self,
        catalogs: Dict[str, Connector],
        default_catalog: str = "tpch",
        page_rows: int = 1 << 18,
        mesh=None,
        dist_options: Optional[Dict] = None,
        session=None,
        plugins=(),
    ):
        self.catalogs = dict(catalogs)
        from presto_tpu.security import ALLOW_ALL

        self.access_control = ALLOW_ALL
        if plugins:
            from presto_tpu.plugin import install

            for p in plugins:
                install(p, self.catalogs, allow_access_control=True)
                ac = p.access_control()
                if ac is not None:
                    if self.access_control is not ALLOW_ALL:
                        raise ValueError(
                            "multiple plugins contribute access control"
                        )
                    self.access_control = ac
        catalogs = self.catalogs
        self.default_catalog = default_catalog
        self.mesh = mesh
        self.dist_options = dist_options or {}
        from presto_tpu.session import Session

        self.session = session or Session(catalog=default_catalog)
        if "system" not in self.catalogs:
            # live engine state as SQL (reference: SystemConnector +
            # information_schema; SURVEY §6.5's SQL-over-own-metrics)
            from presto_tpu.connectors.system import (
                SystemConnector,
                install_standard_tables,
            )

            sys_conn = SystemConnector()
            install_standard_tables(sys_conn, self)
            self.catalogs["system"] = sys_conn
        # (catalog, name) -> view SQL text (reference: ConnectorMetadata
        # createView storage; ours is engine-level, expanded at analysis)
        self.views: Dict[tuple, str] = {}
        # prepared-statement registry, keyed by user so concurrent
        # clients can neither EXECUTE nor DEALLOCATE each other's
        # statements (reference scopes prepared statements to the
        # Session; user is the stable key a stateless HTTP session
        # carries across requests)
        self.prepared: Dict[str, Dict[str, str]] = {}
        # the last query's lifecycle trace (obs.QueryTrace), None when
        # tracing was off — tools and the HTTP server read it here
        self.last_trace = None
        # plan-time scalar-subquery plans of the CURRENT statement:
        # their scans execute during planning and fold into the plan
        # as literals, so the full-statement cache must fold THEIR
        # snapshot versions into its key too (reset per plan pass)
        self._scalar_subplans: List = []
        self._ctor_page_rows = page_rows
        if mesh is None:
            self.executor = Executor(catalogs, page_rows=page_rows)
        else:
            from presto_tpu.dist.executor import DistExecutor

            self.executor = DistExecutor(
                catalogs, mesh, page_rows=page_rows
            )

    def _planner(self) -> Planner:
        def scalar_exec(node):
            # plan-time scalar subqueries execute during planning, so
            # they get their own access check
            self._check_plan_access(node)
            # ...and record for the statement cache's key material
            # (their snapshot versions guard the baked-in literal)
            self._scalar_subplans.append(node)
            # ...and must be fragmented before they hit a distributed
            # executor
            if self.mesh is not None:
                from presto_tpu.dist.fragmenter import add_exchanges

                node, _ = add_exchanges(
                    node, self.catalogs, **self._session_dist_options()
                )
            return self.executor.execute(node)[1]

        return Planner(
            self.catalogs,
            self._current_catalog(),
            scalar_executor=scalar_exec,
            views=self.views,
        )

    def _current_catalog(self) -> str:
        # session catalog (X-Presto-Catalog / CLI --catalog) wins over the
        # engine default (reference: Session.getCatalog)
        cat = getattr(self.session, "catalog", None)
        return cat if cat in self.catalogs else self.default_catalog

    def _session_dist_options(self) -> Dict:
        opts = dict(self.dist_options)
        jd = self.session.get("join_distribution_type")
        if "broadcast_rows" not in opts:
            if jd == "broadcast":
                opts["broadcast_rows"] = 1 << 62
            elif jd == "partitioned":
                opts["broadcast_rows"] = 0
            else:
                opts["broadcast_rows"] = self.session.get(
                    "broadcast_join_rows"
                )
                if not self.session.is_set("broadcast_join_rows"):
                    # stats-driven broadcast-vs-partitioned (membudget
                    # + exact connector row counts): a build replicates
                    # only when its byte footprint fits one chip's
                    # broadcast share. Engages only when nothing pinned
                    # an explicit row threshold (constructor
                    # dist_options or SET SESSION always win).
                    from presto_tpu.exec import membudget as MB
                    from presto_tpu.exec.executor import _row_bytes

                    ex = self.executor
                    per_chip = ex._budget() // getattr(ex, "D", 1)
                    opts["broadcast_bytes"] = (
                        per_chip // MB.PAGE_SHARE_DIV
                    )
                    opts["row_bytes_of"] = lambda n: _row_bytes(
                        ex.output_types(n)
                    )
        if "gather_capacity" not in opts:
            opts["gather_capacity"] = self.session.get(
                "agg_gather_capacity"
            )
        return opts

    def plan(self, sql: str) -> P.Output:
        stmt = parse(sql)
        if isinstance(stmt, N.Explain):
            stmt = stmt.query
        if isinstance(stmt, N.CreateTableAs):
            stmt = stmt.query
        return self._plan_statement_query(stmt)

    def _resolve_catalog(self, parts) -> Tuple[str, str]:
        """(catalog, object-name) for a possibly-qualified name — the
        one resolution rule shared by writes and views."""
        if len(parts) >= 2 and parts[0] in self.catalogs:
            return parts[0], parts[-1]
        return self._current_catalog(), parts[-1]

    def _resolve_write_target(self, parts):
        catalog, table = self._resolve_catalog(parts)
        conn = self.catalogs.get(catalog)
        if conn is None or not hasattr(conn, "create_table"):
            raise ValueError(
                f"catalog {catalog!r} does not support writes"
            )
        return conn, catalog, table

    def apply_session(self) -> None:
        """Session properties -> live executor knobs. The ONE wiring
        site (reference: SystemSessionProperties consumption) — every
        driver of the executor (execute() below, the DCN worker/
        coordinator, the bench tools) must call this rather than copy
        the mapping, so the knob set cannot drift between drivers."""
        ex = self.executor
        ex.use_jit = bool(self.session.get("tpu_offload_enabled"))
        limit = int(self.session.get("query_max_memory_bytes"))
        ex.max_memory_bytes = limit or None
        ex.spill_bytes = (
            int(self.session.get("spill_threshold_bytes")) or None
        )
        ex.host_spill_bytes = (
            int(self.session.get("host_spill_bytes")) or None
        )
        ex.disk_spill_bytes = (
            int(self.session.get("disk_spill_bytes")) or None
        )
        ex.spill_path = self.session.get("spill_path") or None
        ex.join_skew_rebalance = bool(
            self.session.get("join_skew_rebalance")
        )
        ex.max_build_rows = (
            int(self.session.get("max_join_build_rows")) or None
        )
        ex.device_memory_budget = int(
            self.session.get("device_memory_budget")
        )
        # pre-compile plan verification (exec/plan_check.py): "auto"
        # resolves inside the executor (on under pytest / prewarm)
        ex.plan_check = self.session.get("plan_check")
        # devices receiving repartitioned rows (0 = whole mesh);
        # consumed by DistExecutor._route_devices — harmless no-op on
        # the single-stream executor
        ex.hash_partitions = int(
            self.session.get("hash_partition_count"))
        # fault tolerance (ISSUE 5): task_retry_attempts also bounds
        # the executor's device-OOM re-entries (the same retry
        # discipline extended inward); query_max_run_time anchors a
        # fresh absolute deadline at apply-time — execute() calls this
        # per query, so the deadline measures from query start
        ex.device_oom_attempts = int(
            self.session.get("task_retry_attempts")
        )
        _deadline_ms = int(self.session.get("query_max_run_time"))
        import time as _time

        ex.query_deadline = (
            _time.monotonic() + _deadline_ms / 1000.0
            if _deadline_ms else None
        )
        pj = self.session.get("pallas_join_enabled")
        ex.pallas_join = {"auto": "auto", "true": "force",
                          "false": "off"}[pj]
        # device-resident data plane (ISSUE 13): on-device exchange
        # partitioning + lazy spools, and buffer donation for the
        # merge-accumulator programs — both tri-state, auto = TPU
        # only (the pallas_join policy; executors resolve)
        ex.device_exchange = self.session.get(
            "device_exchange_enabled")
        ex.buffer_donation = self.session.get(
            "buffer_donation_enabled")
        # only an EXPLICIT session override wins over the constructor's
        # page_rows (the property default must not clobber
        # LocalRunner(page_rows=...) users); restore the constructor
        # value otherwise — the serial server path re-sessions one
        # runner, and a previous session's override must not leak
        if self.session.is_set("page_rows"):
            ex.page_rows = int(self.session.get("page_rows"))
        else:
            ex.page_rows = self._ctor_page_rows
        ex.collect_k = int(self.session.get("array_agg_max_elements"))
        ex.agg_optimistic_rows = int(
            self.session.get("agg_optimistic_rows"))
        ex.agg_compact = bool(
            self.session.get("agg_compact_enabled"))
        ex.generated_join = bool(
            self.session.get("generated_join_enabled"))
        ex.late_mat = {
            "auto": "auto", "true": True, "false": False,
        }[self.session.get("late_materialization_enabled")]
        ex.agg_fusion = {
            "auto": "auto", "true": True, "false": False,
        }[self.session.get("fused_partial_agg_enabled")]
        sb = self.session.get("split_batch_size")
        # "auto" resolves per backend inside the executor (the
        # pallas_join_enabled policy); a digit forces that max batch
        ex.split_batch = (
            int(sb) if sb.isdigit()
            else ("auto" if sb == "auto" else 0)
        )
        # cross-query launch batching (ISSUE 17): "auto" engages
        # whenever a LaunchBatcher is attached — attachment IS the
        # concurrent-server condition, so raw Executors and the
        # serial path resolve to solo launches with zero checks
        ex.cross_query_batching = {
            "auto": "auto", "true": True, "false": False,
        }[self.session.get("cross_query_batching")]
        ex.cross_query_batch_wait_ms = int(
            self.session.get("cross_query_batch_wait_ms"))
        # persistent compile cache (process-global jax config, so the
        # wiring is idempotent; compilecache.py): programs compile once
        # per canonical shape per machine, not per process
        cache_dir = self.session.get("compile_cache_dir")
        if cache_dir:
            from presto_tpu import compilecache

            compilecache.enable_persistent_cache(cache_dir)
        # observed-stats profile store (obs/profile.py): repeated
        # queries seed their starting capacity bucket from persisted
        # profiles instead of climbing the overflow-retry ladder
        profile_dir = self.session.get("stats_profile_dir")
        if profile_dir:
            from presto_tpu.obs.profile import ProfileStore

            ex.profile_store = ProfileStore.at(profile_dir)
        else:
            ex.profile_store = None
        # result cache (ISSUE 10, presto_tpu/cache/): ONE process-
        # shared store behind every enabled session — that sharing is
        # what collapses repeated dashboard statements across the
        # QueryManager's concurrent per-query runners. Budget/TTL are
        # session-governed, last writer wins (the store is shared;
        # shrinking the budget evicts immediately).
        if bool(self.session.get("result_cache_enabled")):
            from presto_tpu.cache import shared_cache

            rc = shared_cache()
            rc.configure(
                budget_bytes=int(
                    self.session.get("result_cache_bytes")),
                ttl_ms=int(self.session.get("result_cache_ttl_ms")),
                spill_dir=self.session.get("spill_path") or None,
                persist_dir=self.session.get(
                    "result_cache_persist_dir"),
            )
            # warm-start pass (ISSUE 19): once per persister binding,
            # re-admit persisted entries whose snapshot tokens still
            # match the live connectors; the persister's own guard
            # makes repeat sessions free
            if self.session.get("result_cache_persist_dir"):
                loaded, drops = rc.warm_load(self.catalogs)
                ex.count_warm_load(loaded, drops)
            ex.result_cache = rc
        else:
            ex.result_cache = None
        ex.cache_subsumption = bool(
            self.session.get("result_cache_subsumption"))

    def prewarm(self, sql: str) -> Dict:
        """Compile a query's program set ahead of timing: plan + execute
        once (results discarded) and report the compile-cost delta, so
        subsequent timed runs measure steady state, not compile. With
        compile_cache_dir set, one prewarm per machine serves every
        later process (the SF100 story: pay the 40-minute partitioned-
        join compile once, off the timed path)."""
        import time as _time

        from presto_tpu import compilecache

        t0 = _time.perf_counter()
        base = compilecache.snapshot()
        self.execute(sql)
        out = compilecache.delta(base)
        out["wall_s"] = round(_time.perf_counter() - t0, 3)
        out["cache_dir"] = compilecache.cache_dir()
        return out

    def estimate_memory(self, sql: str) -> int:
        """Crude peak-HBM estimate for admission control (reference:
        the coordinator-side memory accounting ClusterMemoryManager
        consults): sum of join-build and aggregation-state
        materializations plus one streamed page per scan. Statements
        that don't plan as queries (DDL/SET/...) get a small floor."""
        from presto_tpu.exec.executor import _row_bytes

        floor = 1 << 24
        try:
            plan = self.plan(sql)
        except Exception:  # noqa: BLE001 - non-query statements
            return floor   # (DDL/SET/...) estimate at the floor
        ex = self.executor
        total = 0

        # fragment-level cache-aware admission (ISSUE 19): a subtree
        # whose fragment cache entry is RESIDENT replays host pages —
        # it materializes no join build / agg state / sort buffer, so
        # the arbiter should not reserve HBM for it. Advisory like
        # statement_cache_probe: peek_pages takes no tally and the
        # execute path re-probes, so a racing eviction just runs (and
        # sizes) the query for real under the executor's own budget.
        hit_roots = set()
        if bool(self.session.get("result_cache_enabled")):
            from presto_tpu.cache import shared_cache_if_exists

            rc = shared_cache_if_exists()
            if rc is not None:
                try:
                    from presto_tpu.cache.rules import \
                        select_cache_points

                    salt = f"k{ex.collect_k}.p{ex.page_rows}"
                    for key, node, _t, _s, _f in \
                            select_cache_points(
                                plan, self.catalogs).values():
                        if rc.peek_pages(f"{key}:{salt}"):
                            hit_roots.add(id(node))
                except Exception:  # noqa: BLE001 - advisory discount
                    pass

        def walk(n):
            nonlocal total
            if id(n) in hit_roots:
                # replayed fragment: one streamed page of its output
                # is the peak footprint, same charge as a scan
                total += min(
                    ex.estimate_rows(n), self.executor.page_rows
                ) * _row_bytes(ex.output_types(n))
                return
            if isinstance(n, P.HashJoin):
                total += ex.estimate_rows(n.right) * _row_bytes(
                    ex.output_types(n.right)
                )
            if isinstance(n, P.Aggregation) and n.group_channels:
                total += min(
                    ex.estimate_rows(n), n.capacity
                ) * _row_bytes(ex.output_types(n))
            if isinstance(n, (P.Sort, P.Window, P.MarkDistinct)):
                total += ex.estimate_rows(n) * _row_bytes(
                    ex.output_types(n)
                )
            if isinstance(n, P.TableScan):
                rows = min(
                    ex.estimate_rows(n), self.executor.page_rows
                )
                total += rows * _row_bytes(ex.output_types(n))
            for c in n.children():
                walk(c)

        walk(plan)
        return max(total, floor)

    def statement_cache_probe(self, sql: str) -> bool:
        """Whether this statement would be served whole from the
        full-statement result cache RIGHT NOW — pure host work (parse
        + plan + key probe, no execution), used by the server's
        cache-aware admission (ISSUE 17): a near-zero-cost hit should
        not occupy a resource-group concurrency slot or reserve HBM.
        Advisory by design — the admitted execute path re-probes, so
        a racing eviction between probe and serve just runs the query
        for real."""
        try:
            stmt = parse(sql)
            if not isinstance(stmt, N.Query):
                return False
            self.apply_session()
            if self.executor.result_cache is None:
                return False
            out = self._plan_statement_query(stmt)
            keyed = self._statement_cache_key(out)
            if keyed is None:
                return False
            # tally-free peek: the probe must not distort the
            # hit/miss counters the serving path maintains
            return self.executor.result_cache.peek_rows(keyed[0])
        except Exception:  # noqa: BLE001 - admission probe is
            # advisory: anything unparseable/unplannable here fails
            # loudly on the normal execute path instead
            return False

    def execute(self, sql: str) -> QueryResult:
        stmt = parse(sql)
        # session properties gate the accelerator path per query
        # (reference: SystemSessionProperties; north-star's
        # tpu_offload_enabled -> compiled XLA vs eager fallback)
        self.apply_session()
        self.access_control.check_can_execute_query(
            self.session.user, sql
        )
        # query-lifecycle tracing (ISSUE 9, presto_tpu/obs/): one
        # trace per query when enabled — the executor records attempt/
        # operator spans into it, /v1/query serves it live, and
        # query_trace_dir exports a Chrome-trace file at the end.
        # last_trace keeps the finished trace reachable for tools and
        # the HTTP server's QueryInfo snapshot.
        from presto_tpu import obs as OBS

        trace = OBS.maybe_trace(self.session, sql=sql)
        if trace is not None:
            OBS.attach(self.executor, trace)
        token = _ACTIVE_SESSION.set(self.session)
        try:
            return self._execute_stmt(stmt)
        finally:
            _ACTIVE_SESSION.reset(token)
            if trace is not None:
                if trace.span_count > 1:
                    OBS.finalize(self.executor, trace,
                                 self.session.get("query_trace_dir"))
                    self.last_trace = trace
                else:
                    # control statements (SET SESSION, PREPARE, ...)
                    # never reached the executor: discard the empty
                    # trace — no junk file, and last_trace keeps the
                    # previous REAL query's timeline
                    self.executor.trace = None
            else:
                self.last_trace = None  # this query was not traced

    def _execute_stmt(self, stmt: N.Node) -> QueryResult:
        if isinstance(stmt, N.CreateView):
            catalog, name = self._qualified_view(stmt.parts)
            self.access_control.check_can_create_view(
                self.session.user, catalog, name
            )
            if (catalog, name) in self.views and not stmt.replace:
                raise ValueError(f"view already exists: {name}")
            # validate now, like the reference's analyzer (names/types
            # against current metadata); planning alone has no side
            # effects
            self._planner().plan_statement(parse(stmt.query_sql))
            self.views[(catalog, name)] = stmt.query_sql
            return QueryResult([], [], update_type="CREATE VIEW")
        if isinstance(stmt, N.DropView):
            catalog, name = self._qualified_view(stmt.parts)
            self.access_control.check_can_drop_view(
                self.session.user, catalog, name
            )
            if self.views.pop((catalog, name), None) is None:
                raise ValueError(f"view not found: {name}")
            return QueryResult([], [], update_type="DROP VIEW")
        if isinstance(stmt, N.Prepare):
            # validate now so a bad statement fails at PREPARE, not at
            # first EXECUTE (and so the text passed the execute-query
            # access check above as part of the PREPARE statement)
            parse(stmt.statement_sql)
            mine = self.prepared.setdefault(self.session.user, {})
            mine[stmt.name] = stmt.statement_sql
            return QueryResult([], [], update_type="PREPARE")
        if isinstance(stmt, N.Deallocate):
            mine = self.prepared.get(self.session.user, {})
            if mine.pop(stmt.name, None) is None:
                raise ValueError(
                    f"prepared statement not found: {stmt.name}"
                )
            return QueryResult([], [], update_type="DEALLOCATE")
        if isinstance(stmt, N.ExecutePrepared):
            text = self.prepared.get(self.session.user, {}).get(stmt.name)
            if text is None:
                raise ValueError(
                    f"prepared statement not found: {stmt.name}"
                )
            inner = parse(text)
            if isinstance(inner, (N.Delete, N.Update)):
                # DML predicates/assignments ride as raw SQL slices the
                # AST rewrite cannot reach; substitute the EXECUTE
                # arguments' raw source text into the ? placeholders
                # positionally (quote-aware, so '?' inside string
                # literals is data, not a parameter)
                inner, used = _bind_dml_parameters(inner, stmt.arg_sqls)
                if used != len(stmt.args):
                    raise ValueError(
                        f"incorrect number of parameters: statement "
                        f"expects {used}, EXECUTE supplies "
                        f"{len(stmt.args)}"
                    )
                return self._execute_stmt(inner)
            want = _count_parameters(inner)
            if len(stmt.args) != want:
                raise ValueError(
                    f"incorrect number of parameters: statement "
                    f"expects {want}, EXECUTE supplies {len(stmt.args)}"
                )
            return self._execute_stmt(_bind_parameters(inner, stmt.args))
        if isinstance(stmt, N.SetSession):
            self.access_control.check_can_set_session(
                self.session.user, stmt.name
            )
            self.session.set(stmt.name, stmt.value)
            return QueryResult([], [], update_type="SET SESSION")
        if isinstance(stmt, N.ShowSession):
            return QueryResult(
                ["name", "value", "default", "type", "description"],
                self.session.rows(),
            )
        if isinstance(stmt, N.ShowTables):
            cat = stmt.catalog or self._current_catalog()
            conn = self.catalogs.get(cat)
            if conn is None:
                raise ValueError(f"unknown catalog: {cat}")
            return QueryResult(
                ["table"], [(t,) for t in conn.tables()]
            )
        if isinstance(stmt, N.DropTable):
            conn, cat, table = self._resolve_write_target(stmt.parts)
            self.access_control.check_can_drop_table(
                self.session.user, cat, table
            )
            conn.drop_table(table)
            self._invalidate_caches(cat, table)
            return QueryResult([], [], update_type="DROP TABLE")
        if isinstance(stmt, (N.Delete, N.Update)):
            _conn, cat, table = self._resolve_write_target(stmt.parts)
            check = (
                self.access_control.check_can_delete
                if isinstance(stmt, N.Delete)
                else self.access_control.check_can_update
            )
            check(self.session.user, cat, table)
            return self._execute_dml(stmt)
        if isinstance(stmt, (N.CreateTableAs, N.InsertInto)):
            conn, cat, table = self._resolve_write_target(stmt.parts)
            if isinstance(stmt, N.CreateTableAs):
                self.access_control.check_can_create_table(
                    self.session.user, cat, table
                )
            else:
                self.access_control.check_can_insert(
                    self.session.user, cat, table
                )
            inner_plan = self._plan_statement_query(stmt.query)
            types = self.executor.output_types(inner_plan)
            names, rows = self.executor.execute(inner_plan)
            if isinstance(stmt, N.CreateTableAs):
                n = conn.create_table(table, names or [], types, rows)
                self._invalidate_caches(cat, table)
                return QueryResult(
                    ["rows"], [(n,)], update_type="CREATE TABLE AS",
                    column_types=["bigint"],
                )
            n = conn.insert(table, rows)
            # append-only stream connectors ADVANCE instead of
            # invalidate: watermarked (pinned-prefix / IVM) entries
            # stay servable, live-head entries reclaim (ISSUE 14)
            self._invalidate_caches(
                cat, table,
                append=getattr(conn, "append_only", False),
            )
            return QueryResult(["rows"], [(n,)], update_type="INSERT",
                               column_types=["bigint"])
        if isinstance(stmt, N.Explain):
            out = self._plan_statement_query(stmt.query)
            if stmt.analyze:
                _names, _rows, stats = (
                    self.executor.execute_with_stats(out)
                )
                text = explain_text(out, stats=stats)
            else:
                text = explain_text(out)
            return QueryResult(["Query Plan"],
                               [(line,) for line in text.splitlines()])
        # plain query: the full-statement result cache short-circuits
        # everything past planning for an identical (canonical AST,
        # catalog/schema, result-affecting props, snapshot versions)
        # repeat (presto_tpu/cache/; level 2 of the result cache —
        # level 1, the fragment cache, engages inside execute())
        out = self._plan_statement_query(stmt)
        keyed = self._statement_cache_key(out)
        if keyed is not None:
            hit = self.executor.result_cache.get_rows(keyed[0])
            if hit is not None:
                names, rows, types = hit
                ex = self.executor
                ex.result_cache_hits += 1
                # the executor never ran: every per-query gauge must
                # describe THIS query (zero launches, zero spills,
                # zero boosts), not whatever executed last on this
                # runner — _begin_attempt resets the per-attempt set,
                # the per-query gauges execute() resets follow
                ex._begin_attempt()
                for gauge in ("peak_memory_bytes",
                              "spill_partitions_used",
                              "host_spill_pages", "disk_spill_pages",
                              "skew_chunks_used", "device_oom_retries",
                              "capacity_boost_retries",
                              "profile_store_hits"):
                    setattr(ex, gauge, 0)
                # a replayed statement crosses the host<->device
                # boundary ZERO times (ISSUE 12 acceptance pin)
                ex._reset_transfer_gauges()
                return QueryResult(names, rows, column_types=types)
        names, rows = self.executor.execute(out)
        types = [str(t) for t in self.executor.output_types(out)]
        if keyed is not None:
            key, tables = keyed
            self.executor.result_cache_evictions += (
                self.executor.result_cache.put_rows(
                    key, list(names or []), rows, types, tables
                )
            )
        return QueryResult(list(names or []), rows, column_types=types)

    def _qualified_view(self, parts) -> tuple:
        return self._resolve_catalog(parts)

    def _statement_cache_key(self, plan):
        """(key, scanned tables) for the full-statement cache, or None
        when this statement cannot cache: no cache wired, a
        non-deterministic / snapshot-less plan, or a plan-time scalar
        subquery that was itself uncacheable (its result is baked into
        the plan as a literal — a volatile or system-reading scalar
        would make the whole statement unreplayable). Key material:
        the canonical fingerprint of the PLANNED statement — after
        view expansion and parameter binding, so whitespace/case
        differences still hit while CREATE OR REPLACE VIEW moves the
        key (keying the raw AST would serve the OLD view's rows) —
        plus the resolved catalog/schema, the result-affecting session
        properties, and every scanned table's snapshot version (main
        plan AND scalar subplans; a baked-in scalar literal is covered
        twice: its value changes the plan fingerprint, its source's
        snapshot rides in the key)."""
        from presto_tpu.cache import (
            RESULT_AFFECTING_PROPS,
            cacheable,
            scan_tables,
            snapshot_tokens,
        )
        from presto_tpu.obs.profile import (
            plan_fingerprint,
            structural_fingerprint,
        )

        if self.executor.result_cache is None:
            return None
        if not cacheable(plan, self.catalogs):
            return None
        tables = scan_tables(plan)
        for sub in self._scalar_subplans:
            if not cacheable(sub, self.catalogs):
                return None
            tables |= scan_tables(sub)
        snap = snapshot_tokens(tables, self.catalogs)
        if snap is None:
            return None
        props = tuple(
            (p, str(self.session.get(p)))
            for p in RESULT_AFFECTING_PROPS
        )
        fp = structural_fingerprint((
            plan_fingerprint(plan, self.catalogs),
            self._current_catalog(), self.session.schema, props, snap,
        ))
        return f"stmt:{fp}", frozenset(tables)

    def _invalidate_caches(self, catalog: str, table: str,
                           append: bool = False) -> None:
        """THE write-path invalidation hub: after any DML/CTAS/DROP
        through this runner, (a) eagerly reclaim result-cache entries
        that read the written table (their keys are already
        unreachable — snapshot_version moved — this frees the bytes
        now), and (b) drop a wrapping page cache's stale lists
        (connectors/cached.py registers via invalidate()/drop_cache()).
        Counted on the result_cache_invalidations registry counter.

        ``append`` (INSERT into an append-only stream, ISSUE 14)
        switches (a) to the ADVANCE model: only live-head entries
        reclaim — watermarked pinned-prefix and IVM-view entries
        still describe exactly the prefix they cover and survive the
        write (cache/store.advance_tables)."""
        from presto_tpu.cache import shared_cache_if_exists

        n = 0
        rc = shared_cache_if_exists()
        if rc is not None:
            if append:
                n += rc.advance_tables({(catalog, table)})
            else:
                n += rc.invalidate_tables({(catalog, table)})
        if append:
            # streaming observability: the engine saw one append batch
            self.executor.count_stream_append()
        conn = self.catalogs.get(catalog)
        inv = getattr(conn, "invalidate", None)
        if inv is not None:
            n += int(inv(table) or 0)
        elif hasattr(conn, "drop_cache"):
            conn.drop_cache()
        if n:
            self.executor.count_cache_invalidations(n)

    def _execute_dml(self, stmt) -> QueryResult:
        """DELETE/UPDATE as rewrite-through-SELECT + table replace
        (reference: DeleteNode/TableWriter; columnar stores rewrite
        rather than mutate — ours replaces the memory-connector table
        with the surviving/updated row set)."""

        def q(ident: str) -> str:
            # regenerated SQL must survive re-tokenizing: quote every
            # identifier (unquoted names lowercase on re-parse)
            return '"' + ident.replace('"', '""') + '"'

        conn, catalog, table = self._resolve_write_target(stmt.parts)
        try:
            schema = conn.table_schema(table)
        except KeyError:
            raise ValueError(f"table not found: {table}")
        cols = schema.column_names()
        w = getattr(stmt, "where_sql", None)
        if w is not None and _sql_has_subquery(w):
            # the guarded rewrite buries the predicate where the
            # planner's subquery decorrelation cannot reach it
            raise ValueError(
                "DELETE/UPDATE predicates with subqueries are not "
                "supported yet; stage keys via CREATE TABLE AS first"
            )
        tref = f"{q(catalog)}.{q(table)}"
        # coalesce((w), false): NULL-predicate rows are NOT matched
        # (SQL three-valued logic — a NULL WHERE neither deletes nor
        # updates the row). The newline terminates any trailing line
        # comment riding in the raw source slice.
        guarded = f"coalesce(({w}\n), false)" if w else "true"
        n_before = conn.row_count(table)
        if isinstance(stmt, N.Delete):
            keep_sql = f"select * from {tref} where not {guarded}"
            plan = self._plan_statement_query(parse(keep_sql))
            types = self.executor.output_types(plan)
            _names, rows = self.executor.execute(plan)
            conn.create_table(table, cols, types, rows, replace=True)
            self._invalidate_caches(catalog, table)
            return QueryResult(
                ["rows"], [(n_before - len(rows),)],
                update_type="DELETE", column_types=["bigint"],
            )
        # UPDATE: assigned columns become guarded CASE projections cast
        # back to the declared column type (schema survives); the guard
        # itself rides as one extra boolean column so the matched count
        # comes from the same single scan
        sets = dict(stmt.assignments)
        if len(sets) != len(stmt.assignments):
            raise ValueError(
                "UPDATE assigns the same column more than once"
            )
        unknown = set(sets) - set(cols)
        if unknown:
            raise ValueError(
                f"no such column(s) in {table!r}: {sorted(unknown)}"
            )
        sel = []
        for c in cols:
            if c in sets:
                t = schema.column_type(c)
                sel.append(
                    f"case when {guarded} then "
                    f"cast(({sets[c]}\n) as {t}) else {q(c)} end "
                    f"as {q(c)}"
                )
            else:
                sel.append(q(c))
        sel.append(f'{guarded} as "__upd_matched__"')
        upd_sql = f"select {', '.join(sel)} from {tref}"
        plan = self._plan_statement_query(parse(upd_sql))
        _names, rows = self.executor.execute(plan)
        matched = sum(1 for r in rows if r[-1])
        rows = [r[:-1] for r in rows]
        conn.create_table(
            table, cols, [schema.column_type(c) for c in cols], rows,
            replace=True,
        )
        self._invalidate_caches(catalog, table)
        return QueryResult(
            ["rows"], [(matched,)],
            update_type="UPDATE", column_types=["bigint"],
        )

    def _plan_statement_query(self, query: N.Query) -> P.Output:
        from presto_tpu.exec.pushdown import push_scan_constraints

        # fresh scalar-subquery record per plan pass (the statement
        # cache reads it right after planning the outermost statement)
        self._scalar_subplans = []
        out = self._planner().plan_statement(query)
        self._check_plan_access(out)
        out = prune_plan(out, self.catalogs)
        out = push_scan_constraints(out)
        if self.mesh is not None:
            from presto_tpu.dist.fragmenter import add_exchanges

            out, _dist = add_exchanges(
                out, self.catalogs, **self._session_dist_options()
            )
        return out

    def _check_plan_access(self, plan) -> None:
        """checkCanSelect over every scanned table (reference:
        AccessControlManager consulted by the analyzer; ours walks the
        planned scans — the set the query actually reads, after view
        expansion)."""
        ac = self.access_control
        user = self.session.user

        def walk(n):
            if isinstance(n, P.TableScan):
                ac.check_can_select(user, n.catalog, n.table, n.columns)
            for c in n.children():
                walk(c)

        walk(plan)


def _sql_has_subquery(expr_sql: str) -> bool:
    """True when a raw expression fragment contains a subquery (walks
    the parsed AST for nested Query nodes)."""
    import dataclasses as _dc

    from presto_tpu.sql.parser import Parser, tokenize

    node = Parser(tokenize(expr_sql), source=expr_sql).parse_expr()

    def walk(x) -> bool:
        if isinstance(x, N.Query):
            return True
        if isinstance(x, (list, tuple)):
            return any(walk(i) for i in x)  # nested tuples (CASE whens)
        if _dc.is_dataclass(x) and isinstance(x, N.Node):
            return any(
                walk(getattr(x, f.name)) for f in _dc.fields(x)
            )
        return False

    return walk(node)


def explain_text(node: P.PhysicalNode, indent: int = 0, stats=None) -> str:
    """Plan rendering (reference: sql/planner/planPrinter/PlanPrinter);
    with stats (EXPLAIN ANALYZE) each line carries per-node wall time,
    page count, and output rows from the actual run."""
    pad = "    " * indent
    if isinstance(node, P.Output):
        line = f"{pad}Output[{', '.join(node.names)}]"
    elif isinstance(node, P.TableScan):
        line = (f"{pad}TableScan[{node.catalog}.{node.table} "
                f"cols={list(node.columns)}]")
    elif isinstance(node, P.Filter):
        line = f"{pad}Filter[{node.predicate!r}]"
    elif isinstance(node, P.Project):
        line = f"{pad}Project[{len(node.exprs)} cols]"
    elif isinstance(node, P.Aggregation):
        fns = ", ".join(
            f"{s.function}({'' if s.channel is None else '#%d' % s.channel})"
            for s in node.aggregates
        )
        step = "" if node.step == "single" else f" step={node.step}"
        line = (f"{pad}Aggregate[keys={list(node.group_channels)} "
                f"aggs=[{fns}]{step}]")
    elif isinstance(node, P.Window):
        fns = ", ".join(f.function for f in node.functions)
        line = (f"{pad}Window[partition={list(node.partition_channels)} "
                f"fns=[{fns}]]")
    elif isinstance(node, P.Exchange):
        keys = f" keys={list(node.keys)}" if node.keys else ""
        line = f"{pad}Exchange[{node.kind}{keys}]"
    elif isinstance(node, P.HashJoin):
        line = (f"{pad}{node.join_type.capitalize()}Join"
                f"[probe={list(node.left_keys)} "
                f"build={list(node.right_keys)}]")
    elif isinstance(node, P.CrossJoin):
        line = f"{pad}CrossJoin"
    elif isinstance(node, P.MarkDistinct):
        line = (f"{pad}MarkDistinct"
                f"[{[list(s) for s in node.mark_channel_sets]}]")
    elif isinstance(node, P.TopN):
        line = f"{pad}TopN[{node.limit} by {list(node.keys)}]"
    elif isinstance(node, P.Sort):
        line = f"{pad}Sort[{list(node.keys)}]"
    elif isinstance(node, P.Limit):
        line = f"{pad}Limit[{node.count}]"
    elif isinstance(node, P.UniqueId):
        line = f"{pad}AssignUniqueId"
    elif isinstance(node, P.Union):
        line = f"{pad}Union"
    elif isinstance(node, P.Values):
        line = f"{pad}Values[{len(node.rows)} rows]"
    else:
        line = f"{pad}{type(node).__name__}"
    if stats is not None:
        st = stats.get(id(node))
        if st is not None:
            line += (f"   [wall {st.wall_s*1e3:.1f}ms, {st.pages} pages, "
                     f"{st.rows:,} rows]")
    parts = [line]
    for child in node.children():
        parts.append(explain_text(child, indent + 1, stats=stats))
    if indent == 0 and stats is not None and stats.get("counters"):
        # query-level execution counters (late-materialization gather
        # accounting, pipeline-fusion engagement) — reference analog:
        # QueryStats' operator summaries in EXPLAIN ANALYZE output
        ctr = stats["counters"]
        parts.append("Counters: " + ", ".join(
            f"{k}={ctr[k]}" for k in sorted(ctr)
        ))
    return "\n".join(parts)


# the session of the query being executed on this thread/context —
# system.session_properties resolves through this so shared providers
# see the querying session, not the runner they were registered on
import contextvars

_ACTIVE_SESSION: contextvars.ContextVar = contextvars.ContextVar(
    "presto_tpu_active_session", default=None
)


def current_session():
    return _ACTIVE_SESSION.get()


def _subst_sql_params(sql: str, args, pos: int):
    """Replace top-level ? placeholders in a raw SQL slice with the
    argument texts starting at args[pos]. '?' inside single-quoted
    string literals, double-quoted identifiers, or -- and /* */
    comments is data, matching the tokenizer's lexical rules.
    Returns (new_sql, next_pos)."""

    def quoted_span(i: int, quote: str) -> int:
        # end index (exclusive) of a quoted span starting at i; doubled
        # quotes escape
        j = i + 1
        while j < len(sql):
            if sql[j] == quote:
                if j + 1 < len(sql) and sql[j + 1] == quote:
                    j += 2
                    continue
                return j + 1
            j += 1
        return j

    out = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch in ("'", '"'):
            j = quoted_span(i, ch)
            out.append(sql[i:j])
            i = j
            continue
        if ch == "-" and sql[i:i + 2] == "--":
            j = sql.find("\n", i)
            j = n if j < 0 else j + 1
            out.append(sql[i:j])
            i = j
            continue
        if ch == "/" and sql[i:i + 2] == "/*":
            j = sql.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(sql[i:j])
            i = j
            continue
        if ch == "?":
            if pos >= len(args):
                raise ValueError(
                    f"query needs {pos + 1}+ parameters, EXECUTE "
                    f"supplies {len(args)}"
                )
            out.append("(" + args[pos] + ")")
            pos += 1
            i += 1
            continue
        out.append(ch)
        i += 1
    return "".join(out), pos


def _bind_dml_parameters(stmt, arg_sqls):
    """Positional ? substitution across a Delete/Update statement's raw
    SQL slices (assignments left-to-right, then WHERE — source order).
    Returns (bound statement, parameters consumed)."""
    pos = 0
    if isinstance(stmt, N.Update):
        assigns = []
        for col, expr_sql in stmt.assignments:
            bound, pos = _subst_sql_params(expr_sql, arg_sqls, pos)
            assigns.append((col, bound))
        where = stmt.where_sql
        if where is not None:
            where, pos = _subst_sql_params(where, arg_sqls, pos)
        return N.Update(stmt.parts, tuple(assigns), where), pos
    where = stmt.where_sql
    if where is not None:
        where, pos = _subst_sql_params(where, arg_sqls, pos)
    return N.Delete(stmt.parts, where), pos


def _count_parameters(node) -> int:
    """Number of ? placeholders in a statement AST."""
    if isinstance(node, N.Parameter):
        return 1
    if isinstance(node, tuple):
        return sum(_count_parameters(x) for x in node)
    if dataclasses.is_dataclass(node) and isinstance(node, N.Node):
        return sum(
            _count_parameters(getattr(node, f.name))
            for f in dataclasses.fields(node)
        )
    return 0


def _bind_parameters(node, args):
    """Substitute EXECUTE ... USING argument ASTs for ? placeholders
    (reference: sql/analyzer ParameterRewriter). Structural rewrite over
    the frozen AST; arguments may be any constant expression."""
    if isinstance(node, N.Parameter):
        if node.index >= len(args):
            raise ValueError(
                f"query needs {node.index + 1}+ parameters, "
                f"{len(args)} given"
            )
        return args[node.index]
    if isinstance(node, tuple):
        new = tuple(_bind_parameters(x, args) for x in node)
        return (
            new if any(a is not b for a, b in zip(new, node)) else node
        )
    if dataclasses.is_dataclass(node) and isinstance(node, N.Node):
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = _bind_parameters(v, args)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(node, **changes) if changes else node
    return node
