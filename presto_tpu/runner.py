"""LocalRunner: full parse → plan → execute pipeline in one process.

Reference: presto-main testing/LocalQueryRunner.java — the single-JVM
engine harness with no HTTP and no scheduler, used by planner tests and
benchmarks. Ours is additionally the building block the coordinator wraps.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu.connectors.base import Connector
from presto_tpu.exec import plan as P
from presto_tpu.exec.executor import Executor
from presto_tpu.exec.prune import prune_plan
from presto_tpu.sql import ast_nodes as N
from presto_tpu.sql.parser import parse
from presto_tpu.sql.planner import Planner


@dataclasses.dataclass
class QueryResult:
    column_names: List[str]
    rows: List[tuple]


class LocalRunner:
    """mesh=None runs single-stream; passing a jax.sharding.Mesh turns
    this into the distributed runner (reference analog: LocalQueryRunner
    vs DistributedQueryRunner — same engine, exchanges become real)."""

    def __init__(
        self,
        catalogs: Dict[str, Connector],
        default_catalog: str = "tpch",
        page_rows: int = 1 << 18,
        mesh=None,
        dist_options: Optional[Dict] = None,
    ):
        self.catalogs = catalogs
        self.default_catalog = default_catalog
        self.mesh = mesh
        self.dist_options = dist_options or {}
        if mesh is None:
            self.executor = Executor(catalogs, page_rows=page_rows)
        else:
            from presto_tpu.dist.executor import DistExecutor

            self.executor = DistExecutor(
                catalogs, mesh, page_rows=page_rows
            )

    def _planner(self) -> Planner:
        def scalar_exec(node):
            # plan-time scalar subqueries must also be fragmented before
            # they hit a distributed executor
            if self.mesh is not None:
                from presto_tpu.dist.fragmenter import add_exchanges

                node, _ = add_exchanges(
                    node, self.catalogs, **self.dist_options
                )
            return self.executor.execute(node)[1]

        return Planner(
            self.catalogs,
            self.default_catalog,
            scalar_executor=scalar_exec,
        )

    def plan(self, sql: str) -> P.Output:
        stmt = parse(sql)
        if isinstance(stmt, N.Explain):
            stmt = stmt.query
        out = self._planner().plan_statement(stmt)
        out = prune_plan(out, self.catalogs)
        if self.mesh is not None:
            from presto_tpu.dist.fragmenter import add_exchanges

            out, _dist = add_exchanges(
                out, self.catalogs, **self.dist_options
            )
        return out

    def execute(self, sql: str) -> QueryResult:
        stmt = parse(sql)
        if isinstance(stmt, N.Explain):
            out = self.plan(sql)
            text = explain_text(out)
            return QueryResult(["Query Plan"],
                               [(line,) for line in text.splitlines()])
        out = self.plan(sql)
        names, rows = self.executor.execute(out)
        return QueryResult(list(names or []), rows)


def explain_text(node: P.PhysicalNode, indent: int = 0) -> str:
    """Plan rendering (reference: sql/planner/planPrinter/PlanPrinter)."""
    pad = "    " * indent
    if isinstance(node, P.Output):
        line = f"{pad}Output[{', '.join(node.names)}]"
    elif isinstance(node, P.TableScan):
        line = (f"{pad}TableScan[{node.catalog}.{node.table} "
                f"cols={list(node.columns)}]")
    elif isinstance(node, P.Filter):
        line = f"{pad}Filter[{node.predicate!r}]"
    elif isinstance(node, P.Project):
        line = f"{pad}Project[{len(node.exprs)} cols]"
    elif isinstance(node, P.Aggregation):
        fns = ", ".join(
            f"{s.function}({'' if s.channel is None else '#%d' % s.channel})"
            for s in node.aggregates
        )
        step = "" if node.step == "single" else f" step={node.step}"
        line = (f"{pad}Aggregate[keys={list(node.group_channels)} "
                f"aggs=[{fns}]{step}]")
    elif isinstance(node, P.Exchange):
        keys = f" keys={list(node.keys)}" if node.keys else ""
        line = f"{pad}Exchange[{node.kind}{keys}]"
    elif isinstance(node, P.HashJoin):
        line = (f"{pad}{node.join_type.capitalize()}Join"
                f"[probe={list(node.left_keys)} "
                f"build={list(node.right_keys)}]")
    elif isinstance(node, P.CrossJoin):
        line = f"{pad}CrossJoin"
    elif isinstance(node, P.TopN):
        line = f"{pad}TopN[{node.limit} by {list(node.keys)}]"
    elif isinstance(node, P.Sort):
        line = f"{pad}Sort[{list(node.keys)}]"
    elif isinstance(node, P.Limit):
        line = f"{pad}Limit[{node.count}]"
    elif isinstance(node, P.UniqueId):
        line = f"{pad}AssignUniqueId"
    elif isinstance(node, P.Union):
        line = f"{pad}Union"
    elif isinstance(node, P.Values):
        line = f"{pad}Values[{len(node.rows)} rows]"
    else:
        line = f"{pad}{type(node).__name__}"
    parts = [line]
    for child in node.children():
        parts.append(explain_text(child, indent + 1))
    return "\n".join(parts)
