"""SQL type system mapped onto device dtypes.

Reference: presto-spi src/main/java/com/facebook/presto/spi/type/* (Type
interface, BigintType, VarcharType, DecimalType, ...) and presto-main
type/TypeRegistry.java. The reference's Type both describes values and reads /
writes Blocks; here a SqlType describes values and knows its *device
representation* (jnp dtype or dictionary encoding) — block IO lives in
presto_tpu.page.

Device representation decisions (TPU-first):
  - BIGINT/INTEGER/SMALLINT/TINYINT -> int64/int32/int16/int8 arrays.
  - DOUBLE -> float64 (x64 enabled); REAL -> float32.
  - BOOLEAN -> bool arrays.
  - DATE -> int32 days since 1970-01-01 (same as the reference).
  - TIMESTAMP -> int64 epoch micros (reference uses millis; micros is the
    modern choice and documented here).
  - DECIMAL(p, s): p <= 18 -> int64 scaled by 10**s ("short decimal", same
    split as the reference's Slice-backed long decimals at p > 18);
    p > 18 -> two int64 limbs (hi, lo) little-endian, two's complement.
  - VARCHAR/CHAR -> dictionary encoding: int32 codes on device plus a
    host-side Dictionary (presto_tpu.page.Dictionary). TPUs do not branch
    per byte; all string comparison/LIKE run on codes or host-side over the
    dictionary, which is tiny for analytic workloads.
  - VARBINARY -> host-side payloads; on-device only as int32 row handles.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SqlType:
    """Base class for SQL types. Frozen + hashable: types are static pytree
    aux data, so they must compare by value for jit cache hits."""

    name: str = dataclasses.field(init=False, default="unknown")

    @property
    def is_comparable(self) -> bool:
        return True

    @property
    def is_orderable(self) -> bool:
        return True

    # --- device representation -------------------------------------------
    @property
    def device_dtype(self):
        """jnp dtype of the primary device array for this type."""
        raise NotImplementedError(self)

    @property
    def is_dictionary_encoded(self) -> bool:
        return False

    @property
    def numpy_dtype(self):
        return np.dtype(self.device_dtype)

    def display(self) -> str:
        return self.name

    def __str__(self) -> str:  # pragma: no cover - convenience
        return self.display()


@dataclasses.dataclass(frozen=True)
class FixedWidthType(SqlType):
    pass


@dataclasses.dataclass(frozen=True)
class BigintType(FixedWidthType):
    name: str = dataclasses.field(init=False, default="bigint")

    @property
    def device_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class IntegerType(FixedWidthType):
    name: str = dataclasses.field(init=False, default="integer")

    @property
    def device_dtype(self):
        return jnp.int32


@dataclasses.dataclass(frozen=True)
class SmallintType(FixedWidthType):
    name: str = dataclasses.field(init=False, default="smallint")

    @property
    def device_dtype(self):
        return jnp.int16


@dataclasses.dataclass(frozen=True)
class TinyintType(FixedWidthType):
    name: str = dataclasses.field(init=False, default="tinyint")

    @property
    def device_dtype(self):
        return jnp.int8


@dataclasses.dataclass(frozen=True)
class DoubleType(FixedWidthType):
    name: str = dataclasses.field(init=False, default="double")

    @property
    def device_dtype(self):
        return jnp.float64


@dataclasses.dataclass(frozen=True)
class RealType(FixedWidthType):
    name: str = dataclasses.field(init=False, default="real")

    @property
    def device_dtype(self):
        return jnp.float32


@dataclasses.dataclass(frozen=True)
class BooleanType(FixedWidthType):
    name: str = dataclasses.field(init=False, default="boolean")

    @property
    def device_dtype(self):
        return jnp.bool_


@dataclasses.dataclass(frozen=True)
class DateType(FixedWidthType):
    """Days since the 1970-01-01 epoch, int32 (reference: spi/type/DateType)."""

    name: str = dataclasses.field(init=False, default="date")

    @property
    def device_dtype(self):
        return jnp.int32


@dataclasses.dataclass(frozen=True)
class TimestampType(FixedWidthType):
    """Epoch microseconds, int64."""

    name: str = dataclasses.field(init=False, default="timestamp")

    @property
    def device_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class DecimalType(FixedWidthType):
    """DECIMAL(precision, scale).

    Reference: spi/type/DecimalType.java + DecimalShortType/LongDecimalType and
    spi/type/UnscaledDecimal128Arithmetic.java for p > 18. Values are exact
    scaled integers — never floats (money must checksum exactly; TPU f64 is
    slow anyway). p <= 18 fits int64; p > 18 uses 2x int64 limbs.
    """

    precision: int = 38
    scale: int = 0
    name: str = dataclasses.field(init=False, default="decimal")

    def __post_init__(self):
        if not (1 <= self.precision <= 38):
            raise ValueError(f"decimal precision out of range: {self.precision}")
        if not (0 <= self.scale <= self.precision):
            raise ValueError(f"decimal scale out of range: {self.scale}")

    @property
    def is_short(self) -> bool:
        return self.precision <= 18

    @property
    def device_dtype(self):
        return jnp.int64

    def display(self) -> str:
        return f"decimal({self.precision},{self.scale})"


@dataclasses.dataclass(frozen=True)
class VarcharType(SqlType):
    """VARCHAR(n). Dictionary-encoded on device (int32 codes)."""

    length: Optional[int] = None  # None = unbounded
    name: str = dataclasses.field(init=False, default="varchar")

    @property
    def device_dtype(self):
        return jnp.int32

    @property
    def is_dictionary_encoded(self) -> bool:
        return True

    def display(self) -> str:
        return "varchar" if self.length is None else f"varchar({self.length})"


@dataclasses.dataclass(frozen=True)
class CharType(SqlType):
    """CHAR(n) — space-padded semantics on comparison (host-side)."""

    length: int = 1
    name: str = dataclasses.field(init=False, default="char")

    @property
    def device_dtype(self):
        return jnp.int32

    @property
    def is_dictionary_encoded(self) -> bool:
        return True

    def display(self) -> str:
        return f"char({self.length})"


@dataclasses.dataclass(frozen=True)
class VarbinaryType(SqlType):
    name: str = dataclasses.field(init=False, default="varbinary")

    @property
    def device_dtype(self):
        return jnp.int32  # row handle into host-side payload store

    @property
    def is_dictionary_encoded(self) -> bool:
        return True

    @property
    def is_orderable(self) -> bool:
        return True


@dataclasses.dataclass(frozen=True)
class IntervalDayTimeType(FixedWidthType):
    """INTERVAL DAY TO SECOND — epoch-free duration in microseconds, int64
    (reference: spi/type/ (airlift units) IntervalDayTimeType, millis)."""

    name: str = dataclasses.field(init=False, default="interval day to second")

    @property
    def device_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class IntervalYearMonthType(FixedWidthType):
    """INTERVAL YEAR TO MONTH — whole months, int32."""

    name: str = dataclasses.field(init=False, default="interval year to month")

    @property
    def device_dtype(self):
        return jnp.int32


@dataclasses.dataclass(frozen=True)
class UnknownType(SqlType):
    """Type of NULL literals before coercion (reference: spi UnknownType)."""

    name: str = dataclasses.field(init=False, default="unknown")

    @property
    def device_dtype(self):
        return jnp.bool_


@dataclasses.dataclass(frozen=True)
class ArrayType(SqlType):
    """ARRAY(element). Device representation: dictionary-coded i32 —
    the distinct array VALUES (Python tuples) live in a host-side
    Dictionary, rows carry codes (reference: spi/block/ArrayBlock's
    offsets+elements, re-expressed for static shapes: per-value work
    happens once per distinct array on the host at trace time, row
    work is vectorized gathers — same scheme as strings)."""

    element: SqlType = dataclasses.field(default_factory=UnknownType)
    name: str = dataclasses.field(init=False, default="array")

    @property
    def device_dtype(self):
        return jnp.int32

    @property
    def is_dictionary_encoded(self) -> bool:
        return True

    def display(self) -> str:
        return f"array({self.element.display()})"


@dataclasses.dataclass(frozen=True)
class MapType(SqlType):
    """MAP(key, value): dictionary-coded like ARRAY; each distinct map
    value is a Python tuple of (key, value) pairs (reference:
    spi/block/ MapBlock / SingleMapBlock)."""

    key: SqlType = dataclasses.field(default_factory=UnknownType)
    value: SqlType = dataclasses.field(default_factory=UnknownType)
    name: str = dataclasses.field(init=False, default="map")

    @property
    def device_dtype(self):
        return jnp.int32

    @property
    def is_dictionary_encoded(self) -> bool:
        return True

    def display(self) -> str:
        return f"map({self.key.display()}, {self.value.display()})"


@dataclasses.dataclass(frozen=True)
class RowType(SqlType):
    """ROW(fields...): dictionary-coded; each distinct row value is a
    Python tuple (reference: spi/block/RowBlock). Field access via
    element_at(row, ordinal)."""

    fields: tuple = ()
    field_names: tuple = ()
    name: str = dataclasses.field(init=False, default="row")

    @property
    def device_dtype(self):
        return jnp.int32

    @property
    def is_dictionary_encoded(self) -> bool:
        return True

    def display(self) -> str:
        inner = ", ".join(f.display() for f in self.fields)
        return f"row({inner})"


@dataclasses.dataclass(frozen=True)
class HllStateType(SqlType):
    """Internal HyperLogLog accumulator state: a tuple-data Block of
    ops/hll.WORDS packed i64 register words per row (reference:
    spi/type/ HyperLogLogType carrying airlift-stats HLL slices; the
    TPU translation keeps registers as fixed-width columns so state
    pages stay pytrees)."""

    name: str = dataclasses.field(init=False, default="hyperloglog")

    @property
    def device_dtype(self):
        return jnp.int64  # per word

    @property
    def is_comparable(self) -> bool:
        return False

    @property
    def is_orderable(self) -> bool:
        return False


@dataclasses.dataclass(frozen=True)
class CollectStateType(SqlType):
    """Internal bounded-collection accumulator state (array_agg /
    map_agg / approx_percentile): Block data is a [cap, K] int64 slot
    matrix; a sibling BIGINT count column says how many slots each
    group uses (reference: operator/aggregation/ArrayAggregation-
    Function's grouped BlockBuilder state). Values encode into int64
    (doubles via the order-preserving arithmetic sign/exponent/mantissa
    pack in exec/executor._collect_encode — NO 64-bit bitcast, which
    the axon compile service cannot lower; dictionary-coded types by
    code, the dictionary riding the Block); K is the
    array_agg_max_elements session property."""

    element: SqlType = dataclasses.field(default_factory=UnknownType)
    K: int = 1024
    name: str = dataclasses.field(init=False, default="collect_state")

    @property
    def device_dtype(self):
        return jnp.int64

    @property
    def is_comparable(self) -> bool:
        return False

    @property
    def is_orderable(self) -> bool:
        return False

    def display(self) -> str:
        return f"collect_state({self.element.display()}, {self.K})"


# --- singletons (reference: static INSTANCE fields on each Type) ---------
BIGINT = BigintType()
INTEGER = IntegerType()
SMALLINT = SmallintType()
TINYINT = TinyintType()
DOUBLE = DoubleType()
REAL = RealType()
BOOLEAN = BooleanType()
DATE = DateType()
TIMESTAMP = TimestampType()
VARBINARY = VarbinaryType()
UNKNOWN = UnknownType()
VARCHAR = VarcharType()
INTERVAL_DAY_TIME = IntervalDayTimeType()
INTERVAL_YEAR_MONTH = IntervalYearMonthType()
HLL_STATE = HllStateType()

_INTEGRAL = (BigintType, IntegerType, SmallintType, TinyintType)
_FLOATING = (DoubleType, RealType)


def is_integral(t: SqlType) -> bool:
    return isinstance(t, _INTEGRAL)


def is_floating(t: SqlType) -> bool:
    return isinstance(t, _FLOATING)


def is_numeric(t: SqlType) -> bool:
    return is_integral(t) or is_floating(t) or isinstance(t, DecimalType)


def is_string(t: SqlType) -> bool:
    return isinstance(t, (VarcharType, CharType))


def parse_type(text: str) -> SqlType:
    """Parse a type name like ``decimal(12,2)`` or ``varchar`` into a SqlType.

    Reference: presto-main type/TypeRegistry.java parametric type resolution.
    """
    s = text.strip().lower()
    base, args = s, []
    if "(" in s:
        if not s.endswith(")"):
            raise ValueError(f"malformed type: {text!r}")
        base, rest = s.split("(", 1)
        base = base.strip()
        # split on top-level commas only (nested parametric types:
        # map(bigint, array(varchar)))
        args, depth, cur = [], 0, []
        for ch in rest[:-1]:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            if ch == "," and depth == 0:
                args.append("".join(cur).strip())
                cur = []
            else:
                cur.append(ch)
        if cur and "".join(cur).strip():
            args.append("".join(cur).strip())
    simple = {
        "bigint": BIGINT,
        "integer": INTEGER,
        "int": INTEGER,
        "smallint": SMALLINT,
        "tinyint": TINYINT,
        "double": DOUBLE,
        "double precision": DOUBLE,
        "real": REAL,
        "float": REAL,
        "boolean": BOOLEAN,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "varbinary": VARBINARY,
        "unknown": UNKNOWN,
        "hyperloglog": HLL_STATE,
        "interval day to second": INTERVAL_DAY_TIME,
        "interval year to month": INTERVAL_YEAR_MONTH,
    }
    if base in simple:
        if args:
            raise ValueError(f"type {base} takes no parameters: {text!r}")
        return simple[base]
    if base == "varchar":
        return VarcharType(int(args[0])) if args else VarcharType()
    if base == "char":
        return CharType(int(args[0])) if args else CharType(1)
    if base in ("decimal", "numeric"):
        if len(args) == 2:
            return DecimalType(int(args[0]), int(args[1]))
        if len(args) == 1:
            return DecimalType(int(args[0]), 0)
        return DecimalType(38, 0)
    if base == "array":
        return ArrayType(parse_type(args[0]) if args else UNKNOWN)
    if base == "map":
        return MapType(
            parse_type(args[0]) if args else UNKNOWN,
            parse_type(args[1]) if len(args) > 1 else UNKNOWN,
        )
    if base == "row":
        return RowType(tuple(parse_type(a) for a in args))
    if base in _PLUGIN_TYPES:
        if args:
            raise ValueError(f"type {base} takes no parameters: {text!r}")
        return _PLUGIN_TYPES[base]
    raise ValueError(f"unknown type: {text!r}")


# type plugin SPI (reference: spi/Plugin.getTypes + TypeRegistry.addType):
# plugins contribute named types that then resolve in CAST expressions
# and DDL like any builtin
_PLUGIN_TYPES: dict = {}


def register_type(name: str, t: SqlType) -> None:
    key = name.strip().lower()
    if key in _PLUGIN_TYPES and _PLUGIN_TYPES[key] != t:
        raise ValueError(f"type already registered: {name}")
    if key not in _PLUGIN_TYPES:
        try:
            parse_type(key)
        except ValueError:
            pass
        else:
            # parse_type resolves builtins first, so a shadowing
            # registration would be silently unreachable — reject it
            raise ValueError(f"type name shadows a builtin: {name}")
    _PLUGIN_TYPES[key] = t


def common_super_type(a: SqlType, b: SqlType) -> Optional[SqlType]:
    """Least common type two operands coerce to, or None.

    Reference: presto-main type/TypeCoercion / FunctionRegistry
    getCommonSuperType. Implements the numeric tower
    tinyint < smallint < integer < bigint < decimal < real < double and
    varchar/char widening.
    """
    if a == b:
        return a
    if isinstance(a, UnknownType):
        return b
    if isinstance(b, UnknownType):
        return a
    order = {TinyintType: 0, SmallintType: 1, IntegerType: 2, BigintType: 3}
    if type(a) in order and type(b) in order:
        return a if order[type(a)] >= order[type(b)] else b
    if is_numeric(a) and is_numeric(b):
        if isinstance(a, DoubleType) or isinstance(b, DoubleType):
            return DOUBLE
        if isinstance(a, RealType) or isinstance(b, RealType):
            # decimal + real -> real in Presto
            return REAL
        # at least one decimal; precision capped at 18 — computed decimals
        # are physically scaled i64 (see expr/functions._short_decimal)
        da = _to_decimal(a)
        db = _to_decimal(b)
        scale = max(da.scale, db.scale)
        int_digits = max(da.precision - da.scale, db.precision - db.scale)
        return DecimalType(max(min(18, int_digits + scale), scale, 1), scale)
    if is_string(a) and is_string(b):
        la = a.length
        lb = b.length
        if la is None or lb is None:
            return VarcharType()
        return VarcharType(max(la, lb))
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return TIMESTAMP
    if isinstance(a, TimestampType) and isinstance(b, DateType):
        return TIMESTAMP
    return None


def _to_decimal(t: SqlType) -> DecimalType:
    if isinstance(t, DecimalType):
        return t
    widths = {
        TinyintType: 3,
        SmallintType: 5,
        IntegerType: 10,
        BigintType: 19,
    }
    return DecimalType(widths[type(t)], 0)
