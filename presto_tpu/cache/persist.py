"""Persistent warm-start tier for the result cache (ISSUE 19) and the
shared generation-numbered manifest layer (ISSUE 20).

Reference: the compile-cache story applied to RESULTS — presto's
materialized-artifact reuse survives process restarts because the
artifact carries enough identity to prove it still matches its
inputs. The result cache's disk tier already holds serializable host
pytrees; this module adds the missing identity layer: a versioned
manifest (entry key, snapshot tokens, stream watermark, byte size,
wire-serde fingerprint) published next to one payload file per entry
(the spool wire format: dist/serde frames under dist/spool
length-prefix framing — the SAME bytes the exchange plane ships, so
there is exactly one page serialization in the engine).

Manifest format (ISSUE 20 — ROADMAP item 3(ii)): a long-lived persist
dir used to rewrite the WHOLE manifest JSON on every publish, an
O(entries) wall per publish that grows forever. ``ManifestStore`` now
keeps generation-numbered JSON-lines files:

    <stem>.g000001.jsonl
        {"version": V, "gen": 1, ...extra header fields}
        {"k": "<key>", "v": {...meta...}}     one line per publish
        {"k": "<key>", "v": null}             one line per removal

A publish APPENDS one line (O(1)); when a generation accumulates more
than ``compact_threshold`` record lines the store compacts: the live
entry map is written as the next generation (tmp + atomic rename) and
older generations are unlinked. Recovery is loud at every seam: a
torn trailing line (append interrupted by a crash) drops that line
and keeps the prefix; an unreadable newest generation (partial
compaction on a corrupt disk) falls back to the previous generation;
a version or fingerprint skew drops every record — all counted and
logged, none able to crash the boot or serve stale state. The SAME
implementation backs the result-cache warm tier here and the
coordinator checkpoint journal (dist/checkpoint.py), so both planes
ride one tested manifest lifecycle.

Concurrency: the store lock guards only the in-memory entry map and
the pending-append queue. ALL file I/O happens outside the lock on a
drain loop: take the pending batch under the lock (marking the writer
busy), append/compact outside it, re-check for records that arrived
while writing — a racing publish simply extends the drain (concheck:
no blocking I/O under a registered lock).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import struct
import tempfile
from typing import Callable, Dict, List, Optional, Tuple

from presto_tpu.obs.sanitizer import make_lock, register_owner

log = logging.getLogger("presto_tpu.cache")

MANIFEST_VERSION = 2
# record lines per generation before the store compacts into a fresh
# generation snapshot (size governance for long-lived persist dirs)
COMPACT_THRESHOLD = 256

_GEN_RE = re.compile(r"\.g(\d{6,})\.jsonl$")


def _entry_file(key: str) -> str:
    return hashlib.sha256(key.encode()).hexdigest()[:24] + ".pages"


def _unpack_frames(blob: bytes) -> List[bytes]:
    """Inverse of dist/spool.pack_frames over an in-memory payload
    file; raises ValueError on any truncation/corruption (the caller
    counts the drop)."""
    out: List[bytes] = []
    off = 0
    n = len(blob)
    while off < n:
        if off + 8 > n:
            raise ValueError("truncated frame header")
        (ln,) = struct.unpack_from("<q", blob, off)
        off += 8
        if ln < 0 or off + ln > n:
            raise ValueError(f"corrupt frame length {ln}")
        out.append(blob[off:off + ln])
        off += ln
    return out


def manifest_files(directory: str, stem: str = "manifest"):
    """(gen, path) pairs for every generation file of ``stem`` in
    ``directory``, newest first. Shared with tests/tools so nothing
    re-derives the file-name convention."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if not name.startswith(stem + ".g"):
            continue
        m = _GEN_RE.search(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def read_manifest_doc(directory: str,
                      stem: str = "manifest") -> Optional[Dict]:
    """Parse the newest generation file into the classic whole-doc
    shape ({header fields..., "entries": {...}}) for tests and
    tooling. Returns None when no generation file exists; raises
    ValueError on a file this engine cannot parse at all."""
    files = manifest_files(directory, stem)
    if not files:
        return None
    gen, path = files[0]
    with open(path, "rb") as f:
        lines = f.read().decode("utf-8").splitlines()
    if not lines:
        raise ValueError(f"empty manifest generation file {path}")
    doc = json.loads(lines[0])
    entries: Dict[str, Dict] = {}
    for line in lines[1:]:
        if not line.strip():
            continue
        rec = json.loads(line)
        if rec.get("v") is None:
            entries.pop(rec["k"], None)
        else:
            entries[rec["k"]] = rec["v"]
    doc["entries"] = entries
    doc["path"] = path
    return doc


def rewrite_manifest_doc(directory: str, doc: Dict,
                         stem: str = "manifest") -> None:
    """Write ``doc`` (the read_manifest_doc shape) back as the newest
    generation's content — the corruption-injection hook the manifest
    tests use to skew version/fingerprint headers in place."""
    files = manifest_files(directory, stem)
    if not files:
        raise ValueError(f"no manifest generation under {directory}")
    _, path = files[0]
    header = {k: v for k, v in doc.items()
              if k not in ("entries", "path")}
    lines = [json.dumps(header)]
    lines.extend(json.dumps({"k": k, "v": v})
                 for k, v in doc.get("entries", {}).items())
    with open(path, "wb") as f:
        f.write(("\n".join(lines) + "\n").encode("utf-8"))


class ManifestStore:
    """One generation-numbered manifest: in-memory entry map + durable
    JSON-lines journal with threshold compaction. Shared by the
    result-cache warm tier (CachePersister) and the coordinator
    checkpoint journal (dist/checkpoint.CheckpointJournal) — one
    tested manifest lifecycle for both planes."""

    # lock discipline (tools/lint `locks` rule): the entry map and the
    # pending-append queue are mutated by concurrent publishers, the
    # drain loop, and once-per-process claim flags
    _shared_attrs = ("_entries", "_pending", "_io_busy", "_gen",
                     "_gen_records", "_claimed")

    def __init__(self, directory: str, *, stem: str = "manifest",
                 version: int = MANIFEST_VERSION,
                 header_extra: Optional[Dict] = None,
                 header_check: Optional[Callable[[Dict],
                                                 Optional[str]]] = None,
                 compact_threshold: int = COMPACT_THRESHOLD):
        self.directory = directory
        self.stem = stem
        self.version = version
        self._header_extra = dict(header_extra or {})
        self._header_check = header_check
        self.compact_threshold = int(compact_threshold)
        self._lock = make_lock("cache.persist.ManifestStore._lock")
        self._entries: Dict[str, Dict] = {}
        self._pending: List[Dict] = []
        self._io_busy = False
        self._gen = 0
        self._gen_records = 0
        self._claimed: set = set()
        # load outcome, settled at construction (single-threaded: the
        # instance is not shared until the constructor returns); the
        # owning plane reports these as loud drops
        self.broken_reasons: List[str] = []
        self.broken_count = 0
        os.makedirs(directory, exist_ok=True)
        self._load()
        register_owner(self)

    # ------------------------------------------------------------ load
    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.directory,
                            f"{self.stem}.g{gen:06d}.jsonl")

    def _parse_gen(self, path: str) -> Tuple[Dict[str, Dict], int, int]:
        """(entries, record_lines, torn_tail_drops) for one generation
        file. Raises ValueError/OSError when the file is unusable as a
        whole (missing/corrupt header, version or fingerprint skew) —
        the caller falls back to an older generation, loudly."""
        with open(path, "rb") as f:
            blob = f.read()
        lines = blob.decode("utf-8", errors="replace").splitlines()
        if not lines or not lines[0].strip():
            raise ValueError("empty generation file")
        header = json.loads(lines[0])
        if int(header.get("version", -1)) != self.version:
            raise ValueError(
                f"manifest version {header.get('version')!r} "
                f"(this engine writes {self.version})")
        if self._header_check is not None:
            why = self._header_check(header)
            if why:
                # undecodable records behind a skewed header: count
                # every record line so the drop is sized honestly
                nrec = sum(1 for ln in lines[1:] if ln.strip())
                raise ValueError(f"{why}: {nrec} records dropped")
        entries: Dict[str, Dict] = {}
        nrec = 0
        torn = 0
        for ln in lines[1:]:
            if not ln.strip():
                continue
            try:
                rec = json.loads(ln)
                key = rec["k"]
            except (ValueError, KeyError, TypeError):
                # torn trailing append (crash mid-write): keep the
                # parsed prefix, drop the tail loudly
                torn += 1
                break
            nrec += 1
            if rec.get("v") is None:
                entries.pop(key, None)
            else:
                entries[key] = rec["v"]
        return entries, nrec, torn

    def _load(self) -> None:
        files = manifest_files(self.directory, self.stem)
        for gen, path in files:
            try:
                entries, nrec, torn = self._parse_gen(path)
            except (OSError, ValueError, KeyError, TypeError) as e:
                # unreadable generation (partial compaction / skew):
                # record the loud drop and fall back one generation
                self.broken_reasons.append(  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
                    f"{os.path.basename(path)}: {e}")
                self.broken_count += self._drop_weight(e)  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
                continue
            self._entries = entries  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
            self._gen = gen  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
            self._gen_records = nrec  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
            if torn:
                self.broken_reasons.append(  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
                    f"{os.path.basename(path)}: torn trailing "
                    f"record dropped")
                self.broken_count += torn  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
                # the file ends mid-record with no newline: appending
                # would concatenate onto the torn bytes and the new
                # record would be lost on the NEXT load — force the
                # first flush to compact into a fresh generation
                # (atomic tmp+rename) instead of appending
                self._gen_records = self.compact_threshold  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
            return
        if files:
            # every generation was unreadable: start a fresh one ABOVE
            # the corpses so their stale content can never win a
            # newest-generation race later
            self._gen = files[0][0] + 1  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns

    @staticmethod
    def _drop_weight(e: BaseException) -> int:
        """Honest drop sizing: a skew message carrying 'N records
        dropped' counts N; anything else counts 1."""
        m = re.search(r"(\d+) records dropped", str(e))
        return int(m.group(1)) if m else 1

    # --------------------------------------------------------- publish
    def publish(self, key: str, meta: Dict) -> None:
        """Upsert one entry: O(1) append to the current generation
        (compaction amortizes the rewrite)."""
        with self._lock:
            self._entries[key] = meta
            self._pending.append({"k": key, "v": meta})
        self._flush()

    def remove(self, keys) -> Dict[str, Dict]:
        """Drop entries; returns the removed key -> meta map so the
        owner can delete payload files outside any lock."""
        removed: Dict[str, Dict] = {}
        with self._lock:
            for k in keys:
                meta = self._entries.pop(k, None)
                if meta is not None:
                    removed[k] = meta
                    self._pending.append({"k": k, "v": None})
        if removed:
            self._flush()
        return removed

    def entries_snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._entries)

    def claim_once(self, tag: str) -> bool:
        """True exactly once per (store, tag) — the warm-load /
        re-attach single-shot gate."""
        with self._lock:
            if tag in self._claimed:
                return False
            self._claimed.add(tag)
            return True

    def _header_line(self, gen: int) -> bytes:
        doc = {"version": self.version, "gen": gen}
        doc.update(self._header_extra)
        return (json.dumps(doc) + "\n").encode("utf-8")

    def _flush(self) -> None:
        """Drain pending records to disk — append lines, or compact
        into the next generation past the threshold. See module
        docstring for the lock discipline."""
        while True:
            with self._lock:
                if self._io_busy or not self._pending:
                    return
                batch = self._pending
                self._pending = []
                self._io_busy = True
                self._gen_records += len(batch)
                compact = self._gen_records >= self.compact_threshold
                snapshot = dict(self._entries) if compact else None
                gen = self._gen
            try:
                if compact:
                    self._compact(gen, snapshot)
                else:
                    self._append(gen, batch)
            finally:
                with self._lock:
                    self._io_busy = False

    def _append(self, gen: int, batch: List[Dict]) -> None:
        path = self._gen_path(gen)
        payload = b"".join(
            (json.dumps(rec) + "\n").encode("utf-8") for rec in batch)
        try:
            if not os.path.exists(path):
                # first record of a fresh store: seed the header with
                # the same tmp+rename publish compaction uses, then
                # append — a crash between the two leaves a valid
                # empty generation
                fd, tmp = tempfile.mkstemp(
                    prefix=self.stem + ".tmp", dir=self.directory)
                with os.fdopen(fd, "wb") as f:
                    f.write(self._header_line(gen))
                os.replace(tmp, path)
            # one O_APPEND write per drain: complete lines, so a
            # concurrent reader (or a crash) sees a parseable prefix
            # plus at most one torn tail the loader drops loudly
            fd = os.open(path, os.O_WRONLY | os.O_APPEND)
            try:
                os.write(fd, payload)
            finally:
                os.close(fd)
        except OSError as e:
            log.warning("manifest append failed in %s: %r",
                        self.directory, e)

    def _compact(self, gen: int, snapshot: Dict[str, Dict]) -> None:
        """Write the live map as generation gen+1 (tmp + atomic
        rename), then unlink older generations. A crash anywhere
        leaves either the old generation intact or both — the loader's
        newest-readable-wins scan recovers either way."""
        new_gen = gen + 1
        path = self._gen_path(new_gen)
        fd, tmp = tempfile.mkstemp(prefix=self.stem + ".tmp",
                                   dir=self.directory)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(self._header_line(new_gen))
                for k, v in snapshot.items():
                    f.write(
                        (json.dumps({"k": k, "v": v}) + "\n")
                        .encode("utf-8"))
            os.replace(tmp, path)
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            log.warning("manifest compaction failed in %s: %r",
                        self.directory, e)
            return  # disk trouble: persistence is best-effort
        with self._lock:
            self._gen = new_gen
            self._gen_records = len(snapshot)
        for old_gen, old_path in manifest_files(self.directory,
                                                self.stem):
            if old_gen < new_gen:
                try:
                    os.unlink(old_path)
                except OSError:
                    pass


def _serde_header_check(header: Dict) -> Optional[str]:
    from presto_tpu.dist.serde import wire_fingerprint

    if header.get("serde") != wire_fingerprint():
        return (f"serde fingerprint {header.get('serde')!r} != "
                f"{wire_fingerprint()!r}")
    return None


class CachePersister:
    """Manifest + payload-file lifecycle for one persist directory.
    One instance per configured directory, owned by the ResultCache
    (store.configure re-binds on a directory change, the same
    last-writer-wins governance every other store knob follows).
    All shared state lives in the ManifestStore; this class only
    composes payload-file I/O around it."""

    def __init__(self, directory: str,
                 compact_threshold: int = COMPACT_THRESHOLD):
        from presto_tpu.dist.serde import wire_fingerprint

        self.directory = directory
        self._store = ManifestStore(
            directory, stem="manifest", version=MANIFEST_VERSION,
            header_extra={"serde": wire_fingerprint()},
            header_check=_serde_header_check,
            compact_threshold=compact_threshold,
        )

    # -------------------------------------------------------- publish
    def persist(self, key: str, host_pages, tables, snap,
                watermark: Optional[int],
                family: Optional[tuple]) -> None:
        """Write one entry's payload file + manifest record. Called by
        the store AFTER it released its own lock (file I/O and the
        per-page serialization never run under the store lock)."""
        from presto_tpu.dist.serde import serialize_page
        from presto_tpu.dist.spool import pack_frames

        try:
            blob = pack_frames([serialize_page(p) for p in host_pages])
        except Exception as e:  # noqa: BLE001 - best-effort tier:
            # an unserializable page type stays memory-only
            log.warning("result-cache persist skipped for %s: %r",
                        key, e)
            return
        fname = _entry_file(key)
        fd, tmp = tempfile.mkstemp(prefix=fname + ".tmp",
                                   dir=self.directory)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(self.directory, fname))
        except OSError as e:
            log.warning("result-cache persist failed for %s: %r",
                        key, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self._store.publish(key, {
            "file": fname,
            "nbytes": len(blob),
            "tables": sorted(list(t) for t in tables),
            "snap": [list(s) for s in snap],
            "watermark": watermark,
            "family": ([family[0], family[1]]
                       if family is not None else None),
        })

    def forget(self, keys) -> None:
        """Drop entries from the manifest (DML invalidation / stream
        advance made them stale-by-construction) and delete their
        payload files; called outside the store lock."""
        removed = self._store.remove(keys)
        for meta in removed.values():
            try:
                os.unlink(os.path.join(self.directory, meta["file"]))
            except OSError:
                pass

    # ------------------------------------------------------ warm load
    def warm_load(self, cache, catalogs) -> Tuple[int, int]:
        """Re-admit every still-valid manifest entry into ``cache``;
        returns (entries loaded, entries dropped). Runs at most once
        per persister instance — store.configure() re-binds a fresh
        persister on a directory change, which is what a restarted
        process's first enabled session does."""
        from presto_tpu.cache.rules import snapshot_of
        from presto_tpu.dist.serde import PageWireError, \
            deserialize_page

        if not self._store.claim_once("warm_load"):
            return (0, 0)
        snapshot = self._store.entries_snapshot()
        loaded = 0
        drops = 0
        if self._store.broken_count:
            drops += self._store.broken_count
            for why in self._store.broken_reasons:
                log.warning("result-cache warm load: %s", why)
        dead: List[Tuple[str, bool]] = []  # (key, delete_file)
        for key, meta in snapshot.items():
            try:
                tables = frozenset(
                    (c, t) for c, t in meta["tables"])
                snap = tuple(
                    (c, t, v) for c, t, v in meta["snap"])
                watermark = meta["watermark"]
                family = (tuple(meta["family"])
                          if meta.get("family") else None)
                fname = meta["file"]
            except (KeyError, TypeError, ValueError):
                drops += 1
                dead.append((key, False))
                log.warning("result-cache warm load: malformed "
                            "manifest row for %s dropped", key)
                continue
            stale = None
            proven = False
            for c, t, ver in snap:
                cur = snapshot_of(catalogs.get(c), t)
                if cur is None:
                    stale = (f"{c}.{t} has no live snapshot "
                             f"(connector absent or versionless)")
                    break
                if cur != ver:
                    stale = (f"{c}.{t} snapshot moved "
                             f"{ver!r} -> {cur!r}")
                    proven = True
                    break
            if stale is not None:
                drops += 1
                dead.append((key, proven))
                log.warning("result-cache warm load: %s dropped "
                            "(%s)", key, stale)
                continue
            path = os.path.join(self.directory, fname)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                pages = [deserialize_page(b)
                         for b in _unpack_frames(blob)]
            except (OSError, ValueError, PageWireError) as e:
                drops += 1
                dead.append((key, True))
                log.warning("result-cache warm load: payload for %s "
                            "unreadable (%r) — dropped", key, e)
                continue
            cache.put_pages(key, pages, tables, watermark=watermark,
                            snap=snap, family=family, persist=False)
            loaded += 1
        if dead:
            self._store.remove(k for k, _ in dead)
            for key, delete in dead:
                if delete:
                    try:
                        os.unlink(os.path.join(
                            self.directory, _entry_file(key)))
                    except OSError:
                        pass
        if loaded or drops:
            log.info("result-cache warm load from %s: %d entries "
                     "loaded, %d dropped", self.directory, loaded,
                     drops)
        return (loaded, drops)
