"""Persistent warm-start tier for the result cache (ISSUE 19).

Reference: the compile-cache story applied to RESULTS — presto's
materialized-artifact reuse survives process restarts because the
artifact carries enough identity to prove it still matches its
inputs. The result cache's disk tier already holds serializable host
pytrees; this module adds the missing identity layer: a versioned
JSON manifest (entry key, snapshot tokens, stream watermark, byte
size, wire-serde fingerprint) published atomically next to one
payload file per entry (the spool wire format: dist/serde frames
under dist/spool length-prefix framing — the SAME bytes the exchange
plane ships, so there is exactly one page serialization in the
engine).

Warm load runs once per process when a session configures
``result_cache_persist_dir`` (the ``shared_cache()`` boot pass):
every manifest entry whose snapshot tokens still match the live
connectors is re-admitted through the ordinary ``put_pages`` path
(budget, LRU, demotion all apply); everything else drops LOUDLY —
counted on ``cache_manifest_drops``, logged with the reason, and a
PROVEN-stale payload (the connector answered with a different token)
is deleted from disk so the next boot does not re-litigate it. A
truncated manifest, a missing payload file, or a serde-fingerprint
mismatch each load zero entries and count drops; none of them can
crash the boot or serve stale rows (validation happens before any
byte is decoded into the store).

Concurrency: ``CachePersister._lock`` guards only the in-memory
manifest map and its sequence number. ALL file I/O happens outside
the lock on a seq-loop: snapshot the manifest under the lock, write
tmp + atomic rename outside it, then re-check the sequence — a racing
publish simply triggers one more rewrite (concheck: no blocking I/O
under a registered lock).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import tempfile
from typing import Dict, List, Optional, Tuple

from presto_tpu.obs.sanitizer import make_lock, register_owner

log = logging.getLogger("presto_tpu.cache")

MANIFEST_VERSION = 1
_MANIFEST = "manifest.json"


def _entry_file(key: str) -> str:
    return hashlib.sha256(key.encode()).hexdigest()[:24] + ".pages"


def _unpack_frames(blob: bytes) -> List[bytes]:
    """Inverse of dist/spool.pack_frames over an in-memory payload
    file; raises ValueError on any truncation/corruption (the caller
    counts the drop)."""
    out: List[bytes] = []
    off = 0
    n = len(blob)
    while off < n:
        if off + 8 > n:
            raise ValueError("truncated frame header")
        (ln,) = struct.unpack_from("<q", blob, off)
        off += 8
        if ln < 0 or off + ln > n:
            raise ValueError(f"corrupt frame length {ln}")
        out.append(blob[off:off + ln])
        off += ln
    return out


class CachePersister:
    """Manifest + payload-file lifecycle for one persist directory.
    One instance per configured directory, owned by the ResultCache
    (store.configure re-binds on a directory change, the same
    last-writer-wins governance every other store knob follows)."""

    # lock discipline (tools/lint `locks` rule): the manifest map and
    # its publish sequence are mutated by concurrent per-query
    # publishers and the warm-load pass
    _shared_attrs = ("_entries", "_seq", "_loaded", "_written_seq")

    def __init__(self, directory: str):
        self.directory = directory
        self._lock = make_lock("cache.persist.CachePersister._lock")
        self._entries: Dict[str, Dict] = {}
        self._seq = 0
        self._written_seq = 0
        self._loaded = False
        # manifest parse outcome, settled at construction (single-
        # threaded: the instance is not shared until configure
        # returns); warm_load reports it as a loud drop
        self._broken: Optional[str] = None
        os.makedirs(directory, exist_ok=True)
        self._read_manifest()
        register_owner(self)

    # ------------------------------------------------------- manifest
    def _read_manifest(self) -> None:
        from presto_tpu.dist.serde import wire_fingerprint

        path = os.path.join(self.directory, _MANIFEST)
        if not os.path.exists(path):
            return
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
            if int(doc.get("version", -1)) != MANIFEST_VERSION:
                raise ValueError(
                    f"manifest version {doc.get('version')!r} "
                    f"(this engine writes {MANIFEST_VERSION})")
            entries = doc["entries"]
            if not isinstance(entries, dict):
                raise ValueError("manifest entries not a map")
            if doc.get("serde") != wire_fingerprint():
                # every payload predates this serde format: the
                # entries are undecodable here, so the in-memory
                # manifest starts empty (files stay on disk for a
                # rolled-back engine; a re-publish of the same key
                # overwrites its payload file in place)
                self._broken = (
                    f"serde fingerprint {doc.get('serde')!r} != "
                    f"{wire_fingerprint()!r}: {len(entries)} "
                    f"entries dropped")
                self._broken_count = len(entries)
                return
            self._entries = dict(entries)  # lint: unlocked-ok - __init__-only path: the instance is not shared until the constructor returns
        except (OSError, ValueError, KeyError, TypeError) as e:
            self._broken = f"unreadable manifest: {e}"
            self._broken_count = 1

    _broken_count = 0

    def _write_manifest(self) -> None:
        """Atomic manifest publish on a seq-loop — see module
        docstring for the lock discipline."""
        from presto_tpu.dist.serde import wire_fingerprint

        path = os.path.join(self.directory, _MANIFEST)
        while True:
            with self._lock:
                if self._written_seq == self._seq:
                    return
                seq = self._seq
                doc = {
                    "version": MANIFEST_VERSION,
                    "serde": wire_fingerprint(),
                    "entries": dict(self._entries),
                }
            blob = json.dumps(doc).encode("utf-8")
            fd, tmp = tempfile.mkstemp(
                prefix=_MANIFEST + ".tmp", dir=self.directory)
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return  # disk trouble: persistence is best-effort
            with self._lock:
                if self._written_seq < seq:
                    self._written_seq = seq

    # -------------------------------------------------------- publish
    def persist(self, key: str, host_pages, tables, snap,
                watermark: Optional[int],
                family: Optional[tuple]) -> None:
        """Write one entry's payload file + manifest row. Called by
        the store AFTER it released its own lock (file I/O and the
        per-page serialization never run under the store lock)."""
        from presto_tpu.dist.serde import serialize_page
        from presto_tpu.dist.spool import pack_frames

        try:
            blob = pack_frames([serialize_page(p) for p in host_pages])
        except Exception as e:  # noqa: BLE001 - best-effort tier:
            # an unserializable page type stays memory-only
            log.warning("result-cache persist skipped for %s: %r",
                        key, e)
            return
        fname = _entry_file(key)
        fd, tmp = tempfile.mkstemp(prefix=fname + ".tmp",
                                   dir=self.directory)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(self.directory, fname))
        except OSError as e:
            log.warning("result-cache persist failed for %s: %r",
                        key, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        meta = {
            "file": fname,
            "nbytes": len(blob),
            "tables": sorted(list(t) for t in tables),
            "snap": [list(s) for s in snap],
            "watermark": watermark,
            "family": ([family[0], family[1]]
                       if family is not None else None),
        }
        with self._lock:
            self._entries[key] = meta
            self._seq += 1
        self._write_manifest()

    def forget(self, keys) -> None:
        """Drop entries from the manifest (DML invalidation / stream
        advance made them stale-by-construction) and delete their
        payload files; called outside the store lock."""
        doomed: List[str] = []
        with self._lock:
            for k in keys:
                meta = self._entries.pop(k, None)
                if meta is not None:
                    doomed.append(meta["file"])
                    self._seq += 1
        for fname in doomed:
            try:
                os.unlink(os.path.join(self.directory, fname))
            except OSError:
                pass
        if doomed:
            self._write_manifest()

    # ------------------------------------------------------ warm load
    def warm_load(self, cache, catalogs) -> Tuple[int, int]:
        """Re-admit every still-valid manifest entry into ``cache``;
        returns (entries loaded, entries dropped). Runs at most once
        per persister instance — store.configure() re-binds a fresh
        persister on a directory change, which is what a restarted
        process's first enabled session does."""
        from presto_tpu.cache.rules import snapshot_of
        from presto_tpu.dist.serde import PageWireError, \
            deserialize_page

        with self._lock:
            if self._loaded:
                return (0, 0)
            self._loaded = True
            snapshot = dict(self._entries)
        loaded = 0
        drops = 0
        if self._broken is not None:
            drops += max(1, int(self._broken_count))
            log.warning("result-cache warm load: %s", self._broken)
        dead: List[Tuple[str, bool]] = []  # (key, delete_file)
        for key, meta in snapshot.items():
            try:
                tables = frozenset(
                    (c, t) for c, t in meta["tables"])
                snap = tuple(
                    (c, t, v) for c, t, v in meta["snap"])
                watermark = meta["watermark"]
                family = (tuple(meta["family"])
                          if meta.get("family") else None)
                fname = meta["file"]
            except (KeyError, TypeError, ValueError):
                drops += 1
                dead.append((key, False))
                log.warning("result-cache warm load: malformed "
                            "manifest row for %s dropped", key)
                continue
            stale = None
            proven = False
            for c, t, ver in snap:
                cur = snapshot_of(catalogs.get(c), t)
                if cur is None:
                    stale = (f"{c}.{t} has no live snapshot "
                             f"(connector absent or versionless)")
                    break
                if cur != ver:
                    stale = (f"{c}.{t} snapshot moved "
                             f"{ver!r} -> {cur!r}")
                    proven = True
                    break
            if stale is not None:
                drops += 1
                dead.append((key, proven))
                log.warning("result-cache warm load: %s dropped "
                            "(%s)", key, stale)
                continue
            path = os.path.join(self.directory, fname)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                pages = [deserialize_page(b)
                         for b in _unpack_frames(blob)]
            except (OSError, ValueError, PageWireError) as e:
                drops += 1
                dead.append((key, True))
                log.warning("result-cache warm load: payload for %s "
                            "unreadable (%r) — dropped", key, e)
                continue
            cache.put_pages(key, pages, tables, watermark=watermark,
                            snap=snap, family=family, persist=False)
            loaded += 1
        if dead:
            with self._lock:
                for key, _ in dead:
                    if self._entries.pop(key, None) is not None:
                        self._seq += 1
            for key, delete in dead:
                if delete:
                    try:
                        os.unlink(os.path.join(
                            self.directory, _entry_file(key)))
                    except OSError:
                        pass
            self._write_manifest()
        if loaded or drops:
            log.info("result-cache warm load from %s: %d entries "
                     "loaded, %d dropped", self.directory, loaded,
                     drops)
        return (loaded, drops)
