"""The process-shared, byte-budgeted result-cache store.

Reference: presto-main's coordinator-side result reuse direction
(compiled-artifact reuse extended to RESULTS) and presto-memory's
MemoryPagesStore (a scan of unchanged data is a memory read, not a
recomputation). One lock-disciplined store per process holds two entry
kinds under ONE LRU/byte budget:

  - fragment entries: the host-side page pytrees of one cacheable plan
    subtree, held in a PageStore (host tier while the resident budget
    allows; demoted entry-by-entry to the DISK tier — the same spill
    files and pid-tagged dir lifecycle every other engine
    materialization uses — when the host budget is exceeded);
  - statement entries: the finished (names, rows, types) of one full
    statement, host-RAM only (row tuples have no useful disk form at
    this scale; under pressure they simply evict).

Governance: ``result_cache_bytes`` is the HOST-resident budget; disk-
demoted bytes are bounded at ``_DISK_BUDGET_FACTOR`` x that budget,
past which LRU entries evict outright. ``result_cache_ttl_ms`` > 0
ages entries out on access. Every key embeds connector snapshot
versions (cache/rules.py), so invalidation-by-write needs no flush —
``invalidate_tables`` exists to reclaim memory eagerly on the writable
connectors' DML path and to serve wrapped page caches
(connectors/cached.py ``drop_cache``).

Concurrency: the QueryManager's per-query runners share one instance
(``shared_cache()``); all map/byte-accounting mutations happen under
``self._lock``. Page payloads are immutable after publication (readers
take a list snapshot under the lock; host pytrees are never mutated),
so replay needs no lock. An entry that alone exceeds the budget is not
admitted (one oversized result must not flush the whole working set).
"""

from __future__ import annotations

import sys
import time
from collections import OrderedDict
from typing import Dict, FrozenSet, List, Optional, Tuple

from presto_tpu.obs.sanitizer import make_lock, register_owner

DEFAULT_BUDGET_BYTES = 1 << 28  # 256 MiB host-resident
_DISK_BUDGET_FACTOR = 4


class _Entry:
    __slots__ = ("key", "kind", "nbytes", "tables", "created",
                 "store", "payload", "watermark", "snap", "family")

    def __init__(self, key: str, kind: str, nbytes: int,
                 tables: FrozenSet[Tuple[str, str]], created: float,
                 store=None, payload=None, watermark=None,
                 snap=None, family=None):
        self.key = key
        self.kind = kind          # "pages" | "rows"
        self.nbytes = nbytes
        self.tables = tables
        self.created = created
        self.store = store        # PageStore (pages kind)
        self.payload = payload    # (names, rows, types) (rows kind)
        # append-log offset this entry's content covers (ISSUE 14):
        # None for ordinary entries AND for live-head stream scans
        # (offset-keyed, reclaimed by advance_tables); an int for
        # PINNED-prefix readers and IVM view results, which a stream
        # append extends rather than invalidates
        self.watermark = watermark
        # (catalog, table, version) snapshot tokens the key embeds —
        # carried explicitly (ISSUE 19) so the persistent manifest can
        # re-validate the entry against LIVE connectors at warm load
        self.snap = snap
        # (family_key, filter_descriptor) for subsumable Filter
        # fragments (cache/rules.family_key), else None
        self.family = family

    @property
    def on_disk(self) -> bool:
        return self.store is not None and self.store.tier == "disk"


def _rows_bytes(names, rows, types) -> int:
    """Cheap, stable size estimate for a statement entry: sampled
    per-row getsizeof (tuple + cells) extrapolated over the row count.
    An estimate is fine — the budget is a governor, not an allocator."""
    base = 256 + 64 * (len(names) + len(types))
    if not rows:
        return base
    sample = rows[:64]
    per_row = sum(
        sys.getsizeof(r) + sum(sys.getsizeof(v) for v in r)
        for r in sample
    ) / len(sample)
    return base + int(per_row * len(rows))


class ResultCache:
    """Two-level result cache; see module docstring. All four
    observability tallies mirror the executor-family registry counters
    (exec/counters.QUERY_COUNTERS) as PROCESS totals — the /metrics
    and system.metrics surfaces render these, while EXPLAIN ANALYZE
    renders the querying executor's own counts."""

    # lock discipline (tools/lint `locks` rule): everything the
    # concurrent per-query runners mutate through one shared instance
    _shared_attrs = ("_entries", "budget_bytes", "ttl_ms", "spill_dir",
                     "hits", "misses", "evictions", "invalidations",
                     "warm_loads", "remote_hits", "subsumed_hits",
                     "manifest_drops", "_families", "_persister")

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES,
                 ttl_ms: int = 0, spill_dir: Optional[str] = None):
        self._lock = make_lock("cache.store.ResultCache._lock")
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self.budget_bytes = int(budget_bytes) or DEFAULT_BUDGET_BYTES
        self.ttl_ms = int(ttl_ms)
        self.spill_dir = spill_dir
        # process-total tallies (see class docstring)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        # fleet tallies (ISSUE 19)
        self.warm_loads = 0
        self.remote_hits = 0
        self.subsumed_hits = 0
        self.manifest_drops = 0
        # family_key -> {entry_key: filter_descriptor} for the
        # subsumption probe (cache/rules.descriptor_contains)
        self._families: Dict[str, Dict[str, dict]] = {}
        # cache/persist.CachePersister when a session configured
        # result_cache_persist_dir; None keeps PR-10 behavior exactly
        self._persister = None
        register_owner(self)

    # ------------------------------------------------------ configure
    def configure(self, budget_bytes: Optional[int] = None,
                  ttl_ms: Optional[int] = None,
                  spill_dir: Optional[str] = None,
                  persist_dir: Optional[str] = None) -> None:
        """Re-apply session-level governance (last writer wins — the
        store is process-shared, so the newest session's budget/TTL
        governs; shrinking the budget evicts immediately).
        ``persist_dir``: None = no change, "" = detach persistence, a
        path = (re)bind a CachePersister on that directory."""
        persister = None
        if persist_dir:
            cur = self._persister
            if cur is not None and cur.directory == persist_dir:
                persister = cur
            else:
                # construct OUTSIDE the lock: the persister reads the
                # manifest file at init (concheck: no file I/O under
                # a registered lock)
                from presto_tpu.cache.persist import CachePersister

                persister = CachePersister(persist_dir)
        with self._lock:
            if budget_bytes is not None and int(budget_bytes) > 0:
                self.budget_bytes = int(budget_bytes)
            if ttl_ms is not None:
                self.ttl_ms = int(ttl_ms)
            if spill_dir is not None:
                self.spill_dir = spill_dir or None
            if persist_dir is not None:
                self._persister = persister
            self._maintain_locked()

    # ----------------------------------------------------- inspection
    def counters(self) -> Dict[str, int]:
        """Process-total tallies under the registry counter names."""
        return {
            "result_cache_hits": self.hits,
            "result_cache_misses": self.misses,
            "result_cache_evictions": self.evictions,
            "result_cache_invalidations": self.invalidations,
            "cache_warm_loads": self.warm_loads,
            "cache_remote_hits": self.remote_hits,
            "cache_subsumed_hits": self.subsumed_hits,
            "cache_manifest_drops": self.manifest_drops,
        }

    @property
    def entry_count(self) -> int:
        return len(self._entries)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values()
                       if not e.on_disk)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    # ----------------------------------------------------- pages kind
    def get_pages(self, key: str) -> Optional[List]:
        """Host-side page pytrees for a fragment key, or None. The
        returned list is a safe snapshot: host entries hand back their
        (immutable, GC-protected) page list; disk entries load their
        spill files under the lock so eviction can never race a
        reader's file access."""
        with self._lock:
            e = self._expire_locked(key)
            if e is None or e.kind != "pages":
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return list(e.store.host_pages())

    def put_pages(self, key: str, pages, tables,
                  watermark: Optional[int] = None,
                  snap=None, family=None, persist: bool = True) -> int:
        """Publish one fragment's completed page stream. ``pages`` may
        be device or host pytrees (PageStore.put stages host-side
        either way — callers publish AFTER the attempt completes, so
        the D2H read happens off the deferred-sync hot path).
        ``watermark`` marks a pinned-prefix stream entry (see _Entry);
        ``snap`` carries the key's snapshot tokens for the persistent
        manifest; ``family`` is the (family_key, descriptor) pair for
        subsumable Filter fragments; ``persist=False`` is the warm-load
        re-admission path (the entry is ALREADY on disk). Returns the
        number of entries evicted to admit it."""
        from presto_tpu.exec.pagestore import PageStore

        store = PageStore(tier="host")
        for p in pages:
            store.put(p)
        # host-materialize BEFORE the lock: the persister serializes
        # these same pytrees off-lock after publication
        host_pages = list(store.host_pages())
        with self._lock:
            persister = self._persister
            if store.bytes > self.budget_bytes:
                store.close()  # oversized: never admitted (see above)
                return 0
            self._drop_locked(key)
            self._entries[key] = _Entry(
                key, "pages", store.bytes, frozenset(tables),
                time.monotonic(), store=store, watermark=watermark,
                snap=snap, family=family,
            )
            if family is not None:
                self._families.setdefault(
                    family[0], {})[key] = family[1]
            evicted = self._maintain_locked()
        if persist and persister is not None and snap is not None:
            persister.persist(key, host_pages, tables, snap,
                              watermark, family)
        return evicted

    def peek_pages(self, key: str) -> bool:
        """Tally-free presence probe for a fragment key — the remote
        cache probe (dist/cacheprobe.py) and fragment-level admission
        discounts ask "would this hit?" without distorting the
        hit/miss tallies or LRU order (same contract as peek_rows)."""
        with self._lock:
            e = self._expire_locked(key)
            return e is not None and e.kind == "pages"

    def pages_keys(self) -> List[str]:
        """Every live fragment key (tally-free) — feeds the worker's
        bloom-style cache summary shipped on /v1/info heartbeats."""
        with self._lock:
            return [k for k, e in self._entries.items()
                    if e.kind == "pages"]

    def probe_family(self, family_key: str, wanted) -> Optional[
            Tuple[str, dict]]:
        """Subsumption probe: the first cached sibling in ``family_key``
        whose filter descriptor CONTAINS ``wanted`` (cache/rules.
        descriptor_contains — pure dict comparison, fine under the
        lock). Returns (entry_key, cached_descriptor) or None."""
        from presto_tpu.cache.rules import descriptor_contains

        with self._lock:
            sibs = self._families.get(family_key)
            if not sibs:
                return None
            for ekey, desc in sibs.items():
                if ekey in self._entries and \
                        descriptor_contains(desc, wanted):
                    return (ekey, desc)
            return None

    # ------------------------------------------------- fleet tallies
    def count_remote(self, n: int = 1) -> None:
        with self._lock:
            self.remote_hits += n

    def count_subsumed(self, n: int = 1) -> None:
        with self._lock:
            self.subsumed_hits += n

    def note_warm(self, loaded: int, drops: int) -> None:
        with self._lock:
            self.warm_loads += loaded
            self.manifest_drops += drops

    def warm_load(self, catalogs) -> Tuple[int, int]:
        """One-shot warm-start pass (ISSUE 19): re-admit every still-
        valid persisted entry against the LIVE connector snapshots.
        The persister itself guards the once-per-instance semantics;
        returns (loaded, dropped) and folds both into the tallies."""
        persister = self._persister
        if persister is None:
            return (0, 0)
        loaded, drops = persister.warm_load(self, catalogs)
        if loaded or drops:
            self.note_warm(loaded, drops)
        return (loaded, drops)

    def peek_rows(self, key: str) -> bool:
        """Tally-free presence probe for a statement key — the
        server's cache-aware admission (ISSUE 17) asks "would this
        statement hit?" before spending a resource-group slot on it,
        and an advisory peek must not distort the hit/miss tallies or
        the LRU order the real serving path maintains."""
        with self._lock:
            e = self._expire_locked(key)
            return e is not None and e.kind == "rows"

    # ------------------------------------------------------ rows kind
    def get_rows(self, key: str):
        """(names, rows, types) for a statement key, or None. Lists
        are copied so callers can own their QueryResult."""
        with self._lock:
            e = self._expire_locked(key)
            if e is None or e.kind != "rows":
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            names, rows, types = e.payload
            return list(names), list(rows), list(types)

    def put_rows(self, key: str, names, rows, types, tables,
                 watermark: Optional[int] = None) -> int:
        """Publish (or ADVANCE — re-putting a watermarked key replaces
        payload and watermark in place, the IVM refresh contract) one
        statement/view row set."""
        nbytes = _rows_bytes(names, rows, types)
        with self._lock:
            if nbytes > self.budget_bytes:
                return 0
            self._drop_locked(key)
            self._entries[key] = _Entry(
                key, "rows", nbytes, frozenset(tables),
                time.monotonic(),
                payload=(list(names), list(rows), list(types)),
                watermark=watermark,
            )
            return self._maintain_locked()

    def entry_watermark(self, key: str) -> Optional[int]:
        """The offset watermark riding on one entry (None when the
        entry is absent or unwatermarked) — introspection for the
        advance-on-write contract."""
        with self._lock:
            e = self._entries.get(key)
            return None if e is None else e.watermark

    def advance_tables(self, tables) -> int:
        """Append-path reclaim for append-only stream tables (ISSUE
        14 — "advance on write"): entries keyed to the LIVE log head
        became structurally unreachable the moment the offset moved,
        so drop them now (counted as invalidations, the PR-10 eager-
        reclaim behavior); entries carrying an offset WATERMARK
        (pinned-prefix readers, IVM view results) still describe
        exactly the prefix they were built from — an append only
        extends the suffix — and are KEPT. Returns the dropped
        count."""
        tset = set(tables)
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if e.tables & tset and e.watermark is None]
            for k in doomed:
                self._drop_locked(k)
            self.invalidations += len(doomed)
            persister = self._persister
        if doomed and persister is not None:
            persister.forget(doomed)  # file I/O outside the lock
        return len(doomed)

    # --------------------------------------------------- invalidation
    def invalidate_tables(self, tables) -> int:
        """Drop every entry that read any of the given (catalog,
        table) pairs — the eager-reclaim path the runner drives after
        DML/CTAS writes (snapshot-keyed entries were already
        unreachable; this frees their bytes now). Returns the count."""
        tset = set(tables)
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if e.tables & tset]
            for k in doomed:
                self._drop_locked(k)
            self.invalidations += len(doomed)
            persister = self._persister
        if doomed and persister is not None:
            persister.forget(doomed)  # file I/O outside the lock
        return len(doomed)

    def clear(self) -> int:
        """Drop every IN-MEMORY entry. Persisted files are deliberately
        kept: clear models a process going away (its memory vanishes,
        its manifest survives for the next boot's warm load)."""
        with self._lock:
            n = len(self._entries)
            for k in list(self._entries):
                self._drop_locked(k)
            return n

    # ------------------------------------------------- internals
    def _drop_locked(self, key: str) -> None:
        e = self._entries.pop(key, None)
        if e is not None and e.store is not None:
            e.store.close()
        if e is not None and e.family is not None:
            sibs = self._families.get(e.family[0])
            if sibs is not None:
                sibs.pop(key, None)
                if not sibs:
                    self._families.pop(e.family[0], None)

    def _expire_locked(self, key: str) -> Optional[_Entry]:
        """TTL-aware lookup (caller holds the lock): an entry older
        than result_cache_ttl_ms drops and reads as a miss — counted
        as an eviction (age-based reclaim, not a write invalidation)."""
        e = self._entries.get(key)
        if e is None:
            return None
        if self.ttl_ms > 0 and \
                (time.monotonic() - e.created) * 1000.0 > self.ttl_ms:
            self._drop_locked(key)
            self.evictions += 1
            return None
        return e

    def _maintain_locked(self) -> int:
        """Enforce the budgets (caller holds the lock): demote LRU
        host-resident page entries to the disk tier past the resident
        budget, evict LRU entries outright past the disk factor.
        Returns the number of evictions."""
        resident = sum(e.nbytes for e in self._entries.values()
                       if not e.on_disk)
        if resident > self.budget_bytes:
            from presto_tpu.exec.pagestore import PageStore

            for k in list(self._entries):
                if resident <= self.budget_bytes:
                    break
                e = self._entries[k]
                if e.kind != "pages" or e.on_disk:
                    continue  # rows entries evict below, never demote
                disk = PageStore(tier="disk", spill_dir=self.spill_dir)
                # put_host, not put: the pages are already host pytrees
                # and this runs under self._lock — a jax.device_get
                # here would serialize every cache reader behind a
                # device sync (the concheck blocking-under-lock find)
                for p in e.store.host_pages():
                    disk.put_host(p)
                e.store.close()
                e.store = disk
                resident -= e.nbytes
        evicted = 0
        total = sum(e.nbytes for e in self._entries.values())
        cap = self.budget_bytes * _DISK_BUDGET_FACTOR
        resident = sum(e.nbytes for e in self._entries.values()
                       if not e.on_disk)
        for k in list(self._entries):
            if total <= cap and resident <= self.budget_bytes:
                break
            e = self._entries[k]
            total -= e.nbytes
            if not e.on_disk:
                resident -= e.nbytes
            self._drop_locked(k)
            evicted += 1
        self.evictions += evicted
        return evicted


# ------------------------------------------------- the shared instance
_shared_lock = make_lock("cache.store._shared_lock")
_shared: Optional[ResultCache] = None


def shared_cache() -> ResultCache:
    """THE process-shared store (one per server process, like the
    compiled-kernel cache): every per-query runner the QueryManager
    spins up sees the same entries, which is what makes dashboard-
    style repeated traffic collapse across concurrent clients."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = ResultCache()
        return _shared


def shared_cache_if_exists() -> Optional[ResultCache]:
    """The shared store iff some session already created it — metric
    surfaces use this so scraping /metrics never allocates a cache."""
    return _shared
