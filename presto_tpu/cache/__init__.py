"""presto_tpu/cache — the two-level result-cache subsystem (ISSUE 10).

Reference: the reuse ladder presto built one rung at a time — compiled
expressions (ExpressionCompiler cache), compiled artifacts, and the
result-set reuse that dashboard traffic actually needs. This package
is the RESULT rung, built on prerequisites already in-tree:

  level 1 — fragment-result cache (exec/executor.py hooks): cacheable
      plan subtrees (cache/rules.py: deterministic, snapshot-keyable)
      are keyed by (canonical plan fingerprint, connector snapshot
      versions) and their page streams stored through the byte-
      budgeted store below; a hit replays pages and skips
      compile+launch entirely (``program_launches`` stays 0).
  level 2 — full-statement cache (runner.py): identical (canonical
      statement AST, catalog/schema, result-affecting session props,
      snapshot versions) statements return the finished row set
      without planning or executing.

Invalidation is structural: the Connector SPI's ``snapshot_version``
(connectors/base.py; the writable memory connector bumps an explicit
write counter) rides in every key, so a write makes stale entries
unreachable; ``invalidate_tables`` reclaims their bytes eagerly on the
runner's write path. Governed by session properties
``result_cache_enabled`` / ``result_cache_bytes`` /
``result_cache_ttl_ms``; observable via the four ``result_cache_*``
registry counters (exec/counters.py) and ``cache`` spans in the trace
plane (obs/).

Streaming extension (ISSUE 14): entries over APPEND-ONLY stream
connectors (connectors/stream.py) whose scans are pinned to an offset
carry that offset as a WATERMARK — a write to the stream ADVANCES the
log past them without touching their content, so the append path
reclaims only live-head (unwatermarked) entries
(``ResultCache.advance_tables``) and an IVM refresh replaces a view's
watermarked entry in place ("advance on write" instead of "discard on
write"; streaming/ivm.py).
"""

from presto_tpu.cache.rules import (  # noqa: F401
    RESULT_AFFECTING_PROPS,
    VOLATILE_FUNCTIONS,
    append_only_tables,
    cacheable,
    descriptor_contains,
    family_key,
    filter_descriptor,
    scan_tables,
    select_cache_points,
    snapshot_tokens,
    stream_watermark,
    subtree_key,
    uncacheable_reason,
)
from presto_tpu.cache.store import (  # noqa: F401
    ResultCache,
    shared_cache,
    shared_cache_if_exists,
)
