"""Cacheability rules + cache-point selection for the result cache.

Reference: presto-main's materialized-view/result-set staleness model —
a cached result is servable exactly when (a) the computation is
deterministic and (b) the data it read is provably unchanged. Ours
expresses (a) as a structural walk over the physical plan (no system-
catalog scans, no volatile expressions, no remote sources, no
query-unique row ids) and (b) as the connector-SPI ``snapshot_version``
token folded into every cache key — a write to any scanned table moves
the token, so stale entries become structurally unreachable rather
than needing a coordinated flush (the memory connector bumps an
explicit write counter; read-only generator connectors derive a
row-count token for free).

Key material is built on the same identity-free structural walker the
observed-stats profile store uses (`obs/profile.structural_encode`),
so two processes — or two per-query runners inside one server — key
the same plan identically.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Optional, Set, Tuple

from presto_tpu.exec import plan as P
from presto_tpu.expr.ir import Call, RowExpression
from presto_tpu.obs.profile import plan_fingerprint, structural_fingerprint

# SQL functions whose value depends on when/where they run, not on
# their inputs — a plan containing one can never be cached. The
# current registry implements none of these; the gate exists so adding
# one later cannot silently poison the cache.
VOLATILE_FUNCTIONS: FrozenSet[str] = frozenset({
    "random", "rand", "shuffle", "uuid",
    "now", "current_timestamp", "current_time", "current_date",
    "localtime", "localtimestamp",
})

# session properties whose value can change a successful query's
# RESULT (not just its speed): they ride in every full-statement cache
# key. array_agg_max_elements bounds collect-state aggregates;
# page_rows moves split boundaries and therefore unordered row order.
RESULT_AFFECTING_PROPS: Tuple[str, ...] = (
    "array_agg_max_elements", "page_rows",
)

# subtree roots worth caching: operators that materialize/recompute
# state (a bare scan replays as cheaply as its cache entry would —
# scan == generate for the generator connectors, and the caching
# CONNECTOR already covers host-page scans)
_WORTH_CACHING = (
    P.Aggregation, P.HashJoin, P.CrossJoin, P.Sort, P.TopN,
    P.Window, P.MarkDistinct, P.GroupId, P.Unnest,
)


def _volatile_call(x) -> Optional[str]:
    """First volatile function name reachable from any RowExpression
    field of a plan node (walked structurally, like the encoder)."""
    if isinstance(x, RowExpression):
        if isinstance(x, Call) and x.name in VOLATILE_FUNCTIONS:
            return x.name
        for c in x.children():
            hit = _volatile_call(c)
            if hit:
                return hit
        return None
    if isinstance(x, (tuple, list)):
        for v in x:
            hit = _volatile_call(v)
            if hit:
                return hit
        return None
    if dataclasses.is_dataclass(x) and not isinstance(x, type) and \
            not isinstance(x, P.PhysicalNode):
        for f in dataclasses.fields(x):
            hit = _volatile_call(getattr(x, f.name))
            if hit:
                return hit
    return None


def scan_tables(node: P.PhysicalNode) -> Set[Tuple[str, str]]:
    """Every (catalog, table) the subtree scans."""
    out: Set[Tuple[str, str]] = set()

    def walk(n):
        if isinstance(n, P.TableScan):
            out.add((n.catalog, n.table))
        for c in n.children():
            walk(c)

    walk(node)
    return out


def uncacheable_reason(node: P.PhysicalNode,
                       catalogs) -> Optional[str]:
    """None when the subtree is deterministic and snapshot-keyable;
    otherwise a short human-readable reason (surfaced by tests and
    tools, never raised)."""
    if isinstance(node, P.RemoteSource):
        return "remote source (pages come from runtime task state)"
    if isinstance(node, P.UniqueId):
        return "query-unique row ids"
    if isinstance(node, P.TableScan):
        if node.catalog == "system":
            return "system-catalog scan (live engine state)"
        conn = catalogs.get(node.catalog)
        if conn is None:
            return f"unknown catalog {node.catalog!r}"
        if snapshot_of(conn, node.table) is None:
            return (f"{node.catalog}.{node.table} has no snapshot "
                    f"version (connector cannot prove staleness)")
    elif dataclasses.is_dataclass(node):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, P.PhysicalNode):
                continue  # children walk below
            hit = _volatile_call(v)
            if hit:
                return f"volatile function {hit}()"
    for c in node.children():
        reason = uncacheable_reason(c, catalogs)
        if reason:
            return reason
    return None


def cacheable(node: P.PhysicalNode, catalogs) -> bool:
    return uncacheable_reason(node, catalogs) is None


def snapshot_of(conn, table: str) -> Optional[str]:
    """The connector's snapshot token for one table, None when the
    connector cannot provide one (-> uncacheable). Tolerates legacy
    connectors without the SPI method."""
    fn = getattr(conn, "snapshot_version", None)
    if fn is None:
        return None
    try:
        v = fn(table)
    except Exception:  # noqa: BLE001 - a failing snapshot probe means
        return None    # "cannot prove staleness", never a query error
    return None if v is None else str(v)


def stream_watermark(tables, catalogs) -> Optional[int]:
    """Offset watermark for a cache entry whose scans include append-
    only stream tables (connectors/stream.py): the max PINNED offset
    when every stream scan is offset-pinned (a StreamWindowConnector
    reader), None otherwise — None for non-stream entries AND for
    live-head stream scans, whose keys embed the MOVING offset token
    and are reclaimed by the store's append-path advance
    (store.advance_tables). A watermark on the entry is what lets a
    reader pinned at offset N keep hitting its prefix entry while the
    log grows past N — the monotone-offset-token fix: the token
    identifies the prefix, the append only extends the suffix."""
    marks = []
    for catalog, table in tables:
        conn = catalogs.get(catalog)
        if conn is None or not getattr(conn, "append_only", False):
            continue
        pin = getattr(conn, "pinned_offset", None)
        off = pin(table) if pin is not None else None
        if off is None:
            return None  # live-head scan: offset-keyed, reclaimable
        marks.append(int(off))
    return max(marks) if marks else None


def append_only_tables(tables, catalogs) -> FrozenSet[Tuple[str, str]]:
    """The subset of (catalog, table) pairs whose connector is an
    append-only stream — the tables whose writes ADVANCE cache
    entries (store.advance_tables) instead of discarding them."""
    return frozenset(
        (c, t) for c, t in tables
        if getattr(catalogs.get(c), "append_only", False)
    )


def snapshot_tokens(tables, catalogs) -> Optional[Tuple]:
    """Sorted ((catalog, table, version), ...) for a table set; None
    when any table has no snapshot (the whole key is then unbuildable
    and the caller skips caching)."""
    out = []
    for catalog, table in sorted(tables):
        conn = catalogs.get(catalog)
        v = snapshot_of(conn, table) if conn is not None else None
        if v is None:
            return None
        out.append((catalog, table, v))
    return tuple(out)


def subtree_key(node: P.PhysicalNode, catalogs):
    """(cache key, scanned tables) for one cacheable subtree, or None.
    The key folds the canonical plan fingerprint (which already embeds
    per-scan row-count tokens) with every scanned table's
    snapshot_version — a write to any input moves the key, so a stale
    entry can never be addressed again."""
    tables = frozenset(scan_tables(node))
    snap = snapshot_tokens(tables, catalogs)
    if snap is None:
        return None
    fp = plan_fingerprint(node, catalogs)
    return (f"frag:{fp}:{structural_fingerprint(snap)}", tables)


def _worth_caching(node: P.PhysicalNode) -> bool:
    if isinstance(node, _WORTH_CACHING):
        return True
    return any(_worth_caching(c) for c in node.children())


def select_cache_points(root: P.PhysicalNode, catalogs, *,
                        allow=None) -> Dict[int, tuple]:
    """Choose the subtrees whose page streams this query caches:
    the MAXIMAL cacheable subtrees that contain at least one
    materializing operator. A fully cacheable plan gets exactly one
    point (its root); a plan with one volatile/system branch still
    caches every clean expensive branch under it. Returns
    {id(subnode): (key, subnode, tables)} — node references are held
    in the values so ids stay stable for the query's lifetime.

    ``allow`` (optional predicate) gates which subtrees may become
    points at all — the distributed executor passes its distribution
    test so only REPLICATED subtrees cache (their pages are ordinary
    single-stream Pages a host replay can reproduce; mesh-SHARDED
    mid-plan pages could not — the ISSUE 15 mesh-path residency
    rule, replacing the old all-or-root restriction)."""
    points: Dict[int, tuple] = {}

    def consider(node) -> bool:
        """True when ``node`` was made a cache point (callers then
        skip its subtree)."""
        if not _worth_caching(node):
            return False
        if allow is not None and not allow(node):
            return False
        if uncacheable_reason(node, catalogs) is None:
            keyed = subtree_key(node, catalogs)
            if keyed is not None:
                key, tables = keyed
                points[id(node)] = (key, node, tables)
                return True
        return False

    if consider(root):
        return points

    def descend(node):
        for c in node.children():
            if not consider(c):
                descend(c)

    descend(root)
    return points
