"""Cacheability rules + cache-point selection for the result cache.

Reference: presto-main's materialized-view/result-set staleness model —
a cached result is servable exactly when (a) the computation is
deterministic and (b) the data it read is provably unchanged. Ours
expresses (a) as a structural walk over the physical plan (no system-
catalog scans, no volatile expressions, no remote sources, no
query-unique row ids) and (b) as the connector-SPI ``snapshot_version``
token folded into every cache key — a write to any scanned table moves
the token, so stale entries become structurally unreachable rather
than needing a coordinated flush (the memory connector bumps an
explicit write counter; read-only generator connectors derive a
row-count token for free).

Key material is built on the same identity-free structural walker the
observed-stats profile store uses (`obs/profile.structural_encode`),
so two processes — or two per-query runners inside one server — key
the same plan identically.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Optional, Set, Tuple

from presto_tpu.exec import plan as P
from presto_tpu.expr.ir import (
    AND,
    BETWEEN,
    IN,
    Call,
    Constant,
    InputRef,
    RowExpression,
    SpecialForm,
)
from presto_tpu.obs.profile import plan_fingerprint, structural_fingerprint

# SQL functions whose value depends on when/where they run, not on
# their inputs — a plan containing one can never be cached. The
# current registry implements none of these; the gate exists so adding
# one later cannot silently poison the cache.
VOLATILE_FUNCTIONS: FrozenSet[str] = frozenset({
    "random", "rand", "shuffle", "uuid",
    "now", "current_timestamp", "current_time", "current_date",
    "localtime", "localtimestamp",
})

# session properties whose value can change a successful query's
# RESULT (not just its speed): they ride in every full-statement cache
# key. array_agg_max_elements bounds collect-state aggregates;
# page_rows moves split boundaries and therefore unordered row order.
RESULT_AFFECTING_PROPS: Tuple[str, ...] = (
    "array_agg_max_elements", "page_rows",
)

# subtree roots worth caching: operators that materialize/recompute
# state (a bare scan replays as cheaply as its cache entry would —
# scan == generate for the generator connectors, and the caching
# CONNECTOR already covers host-page scans)
_WORTH_CACHING = (
    P.Aggregation, P.HashJoin, P.CrossJoin, P.Sort, P.TopN,
    P.Window, P.MarkDistinct, P.GroupId, P.Unnest,
)


def _volatile_call(x) -> Optional[str]:
    """First volatile function name reachable from any RowExpression
    field of a plan node (walked structurally, like the encoder)."""
    if isinstance(x, RowExpression):
        if isinstance(x, Call) and x.name in VOLATILE_FUNCTIONS:
            return x.name
        for c in x.children():
            hit = _volatile_call(c)
            if hit:
                return hit
        return None
    if isinstance(x, (tuple, list)):
        for v in x:
            hit = _volatile_call(v)
            if hit:
                return hit
        return None
    if dataclasses.is_dataclass(x) and not isinstance(x, type) and \
            not isinstance(x, P.PhysicalNode):
        for f in dataclasses.fields(x):
            hit = _volatile_call(getattr(x, f.name))
            if hit:
                return hit
    return None


def scan_tables(node: P.PhysicalNode) -> Set[Tuple[str, str]]:
    """Every (catalog, table) the subtree scans."""
    out: Set[Tuple[str, str]] = set()

    def walk(n):
        if isinstance(n, P.TableScan):
            out.add((n.catalog, n.table))
        for c in n.children():
            walk(c)

    walk(node)
    return out


def uncacheable_reason(node: P.PhysicalNode,
                       catalogs) -> Optional[str]:
    """None when the subtree is deterministic and snapshot-keyable;
    otherwise a short human-readable reason (surfaced by tests and
    tools, never raised)."""
    if isinstance(node, P.RemoteSource):
        return "remote source (pages come from runtime task state)"
    if isinstance(node, P.UniqueId):
        return "query-unique row ids"
    if isinstance(node, P.TableScan):
        if node.catalog == "system":
            return "system-catalog scan (live engine state)"
        conn = catalogs.get(node.catalog)
        if conn is None:
            return f"unknown catalog {node.catalog!r}"
        if snapshot_of(conn, node.table) is None:
            return (f"{node.catalog}.{node.table} has no snapshot "
                    f"version (connector cannot prove staleness)")
    elif dataclasses.is_dataclass(node):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, P.PhysicalNode):
                continue  # children walk below
            hit = _volatile_call(v)
            if hit:
                return f"volatile function {hit}()"
    for c in node.children():
        reason = uncacheable_reason(c, catalogs)
        if reason:
            return reason
    return None


def cacheable(node: P.PhysicalNode, catalogs) -> bool:
    return uncacheable_reason(node, catalogs) is None


def snapshot_of(conn, table: str) -> Optional[str]:
    """The connector's snapshot token for one table, None when the
    connector cannot provide one (-> uncacheable). Tolerates legacy
    connectors without the SPI method."""
    fn = getattr(conn, "snapshot_version", None)
    if fn is None:
        return None
    try:
        v = fn(table)
    except Exception:  # noqa: BLE001 - a failing snapshot probe means
        return None    # "cannot prove staleness", never a query error
    return None if v is None else str(v)


def stream_watermark(tables, catalogs) -> Optional[int]:
    """Offset watermark for a cache entry whose scans include append-
    only stream tables (connectors/stream.py): the max PINNED offset
    when every stream scan is offset-pinned (a StreamWindowConnector
    reader), None otherwise — None for non-stream entries AND for
    live-head stream scans, whose keys embed the MOVING offset token
    and are reclaimed by the store's append-path advance
    (store.advance_tables). A watermark on the entry is what lets a
    reader pinned at offset N keep hitting its prefix entry while the
    log grows past N — the monotone-offset-token fix: the token
    identifies the prefix, the append only extends the suffix."""
    marks = []
    for catalog, table in tables:
        conn = catalogs.get(catalog)
        if conn is None or not getattr(conn, "append_only", False):
            continue
        pin = getattr(conn, "pinned_offset", None)
        off = pin(table) if pin is not None else None
        if off is None:
            return None  # live-head scan: offset-keyed, reclaimable
        marks.append(int(off))
    return max(marks) if marks else None


def append_only_tables(tables, catalogs) -> FrozenSet[Tuple[str, str]]:
    """The subset of (catalog, table) pairs whose connector is an
    append-only stream — the tables whose writes ADVANCE cache
    entries (store.advance_tables) instead of discarding them."""
    return frozenset(
        (c, t) for c, t in tables
        if getattr(catalogs.get(c), "append_only", False)
    )


def snapshot_tokens(tables, catalogs) -> Optional[Tuple]:
    """Sorted ((catalog, table, version), ...) for a table set; None
    when any table has no snapshot (the whole key is then unbuildable
    and the caller skips caching)."""
    out = []
    for catalog, table in sorted(tables):
        conn = catalogs.get(catalog)
        v = snapshot_of(conn, table) if conn is not None else None
        if v is None:
            return None
        out.append((catalog, table, v))
    return tuple(out)


def subtree_key(node: P.PhysicalNode, catalogs):
    """(cache key, scanned tables) for one cacheable subtree, or None.
    The key folds the canonical plan fingerprint (which already embeds
    per-scan row-count tokens) with every scanned table's
    snapshot_version — a write to any input moves the key, so a stale
    entry can never be addressed again."""
    tables = frozenset(scan_tables(node))
    snap = snapshot_tokens(tables, catalogs)
    if snap is None:
        return None
    fp = plan_fingerprint(node, catalogs)
    return (f"frag:{fp}:{structural_fingerprint(snap)}", tables)


def _worth_caching(node: P.PhysicalNode) -> bool:
    if isinstance(node, _WORTH_CACHING):
        return True
    return any(_worth_caching(c) for c in node.children())


# ---------------------------------------------------------------------
# Overlapping subsumption (ISSUE 19): containment over single-column
# range/IN predicates. A cached `WHERE d < 10` fragment answers
# `WHERE d < 5` by replaying its pages through the narrower predicate
# (the residual re-filter) — the materialized-view-rewrite direction's
# row-expression domain machinery, restricted to the shapes the
# containment test can PROVE: one column, closed-form range or IN list,
# over the same scan + projection chain. Anything else stays
# exact-match.

_CMP_OPS = frozenset({"lt", "le", "gt", "ge", "eq"})


def _scalar_const(x) -> Optional[tuple]:
    """("v", value) for an orderable literal, None otherwise. Bools
    are excluded (True < 10 is well-defined in Python but nonsense as
    a range bound); the wrapper keeps a literal None distinguishable
    from "not a constant"."""
    if isinstance(x, Constant) and not isinstance(x.value, bool) \
            and isinstance(x.value, (int, float, str)):
        return ("v", x.value)
    return None


def _range_desc(channel: int, lo=None, hi=None, lo_strict=False,
                hi_strict=False) -> Dict:
    return {"channel": channel, "lo": lo, "hi": hi,
            "lo_strict": lo_strict, "hi_strict": hi_strict}


def _merge_ranges(a: Dict, b: Dict) -> Optional[Dict]:
    """Conjunction of two range descriptors on the SAME channel —
    the tighter bound on each side wins."""
    if a["channel"] != b["channel"]:
        return None
    if "in" in a or "in" in b:
        return None  # AND over IN lists stays exact-match
    out = dict(a)
    try:
        for side, strict, keep in (("lo", "lo_strict", max),
                                   ("hi", "hi_strict", min)):
            av, bv = a[side], b[side]
            if bv is None:
                continue
            if av is None or \
                    (keep(av[1], bv[1]) == bv[1] and av != bv):
                out[side], out[strict] = bv, b[strict]
            elif av[1] == bv[1]:
                out[strict] = a[strict] or b[strict]
    except TypeError:
        return None  # incomparable literal types (d < 1 AND d < 'x')
    return out


def filter_descriptor(pred: RowExpression) -> Optional[Dict]:
    """Canonical containment descriptor for a predicate, or None when
    it is not a single-column range/IN shape. Ranges carry optional
    ("v", value) bounds with strictness; IN lists carry the literal
    set. Comparable literal types only (int/float/str)."""
    if isinstance(pred, Call) and pred.name in _CMP_OPS \
            and len(pred.args) == 2:
        a, b = pred.args
        op = pred.name
        if isinstance(b, InputRef) and isinstance(a, Constant):
            # 10 > d  ==  d < 10: flip operand order
            a, b = b, a
            op = {"lt": "gt", "le": "ge", "gt": "lt",
                  "ge": "le", "eq": "eq"}[op]
        if not isinstance(a, InputRef):
            return None
        v = _scalar_const(b)
        if v is None:
            return None
        c = a.channel
        if op == "lt":
            return _range_desc(c, hi=v, hi_strict=True)
        if op == "le":
            return _range_desc(c, hi=v)
        if op == "gt":
            return _range_desc(c, lo=v, lo_strict=True)
        if op == "ge":
            return _range_desc(c, lo=v)
        return _range_desc(c, lo=v, hi=v)  # eq
    if isinstance(pred, SpecialForm):
        if pred.form == AND:
            descs = [filter_descriptor(a) for a in pred.args]
            if any(d is None for d in descs):
                return None
            out = descs[0]
            for d in descs[1:]:
                out = _merge_ranges(out, d)
                if out is None:
                    return None
            return out
        if pred.form == BETWEEN and len(pred.args) == 3:
            v, lo, hi = pred.args
            lov, hiv = _scalar_const(lo), _scalar_const(hi)
            if isinstance(v, InputRef) and lov is not None \
                    and hiv is not None:
                return _range_desc(v.channel, lo=lov, hi=hiv)
            return None
        if pred.form == IN and len(pred.args) >= 2:
            v, cands = pred.args[0], pred.args[1:]
            vals = [_scalar_const(c) for c in cands]
            if isinstance(v, InputRef) and all(x is not None
                                               for x in vals):
                return {"channel": v.channel,
                        "in": sorted({x[1] for x in vals}, key=repr)}
            return None
    return None


def _bound_covers_lo(cached: Dict, wanted: Dict) -> bool:
    cl = cached["lo"]
    if cl is None:
        return True
    wl = wanted["lo"]
    if wl is None:
        return False
    try:
        if cl[1] < wl[1]:
            return True
        if cl[1] > wl[1]:
            return False
    except TypeError:
        return False  # incomparable literal types
    # equal bound: a strict cached bound excludes the endpoint a
    # non-strict wanted bound includes
    return not (cached["lo_strict"] and not wanted["lo_strict"])


def _bound_covers_hi(cached: Dict, wanted: Dict) -> bool:
    ch = cached["hi"]
    if ch is None:
        return True
    wh = wanted["hi"]
    if wh is None:
        return False
    try:
        if ch[1] > wh[1]:
            return True
        if ch[1] < wh[1]:
            return False
    except TypeError:
        return False
    return not (cached["hi_strict"] and not wanted["hi_strict"])


def _in_range(v, desc: Dict) -> bool:
    try:
        if desc["lo"] is not None:
            if v < desc["lo"][1]:
                return False
            if v == desc["lo"][1] and desc["lo_strict"]:
                return False
        if desc["hi"] is not None:
            if v > desc["hi"][1]:
                return False
            if v == desc["hi"][1] and desc["hi_strict"]:
                return False
    except TypeError:
        return False
    return True


def descriptor_contains(cached: Dict, wanted: Dict) -> bool:
    """Whether every row the WANTED predicate keeps is provably kept
    by the CACHED predicate too — the condition under which replaying
    the cached pages through the wanted predicate yields exactly the
    wanted fragment. False on any doubt."""
    if cached is None or wanted is None:
        return False
    if cached["channel"] != wanted["channel"]:
        return False
    if "in" in cached:
        if "in" in wanted:
            return set(wanted["in"]) <= set(cached["in"])
        # a range only fits an IN list when it degenerates to equality
        lo, hi = wanted["lo"], wanted["hi"]
        return (lo is not None and hi is not None and lo == hi
                and not wanted["lo_strict"] and not wanted["hi_strict"]
                and lo[1] in set(cached["in"]))
    if "in" in wanted:
        return all(_in_range(v, cached) for v in wanted["in"])
    return _bound_covers_lo(cached, wanted) and \
        _bound_covers_hi(cached, wanted)


_FAM_CHAIN = (P.Project,)  # interior ops allowed under a family filter


def family_key(node: P.PhysicalNode, catalogs) -> Optional[tuple]:
    """(family key, descriptor, tables) for a Filter whose predicate
    parses to a containment descriptor over a bare scan + projection
    chain, else None. The family key is the subtree's canonical
    fingerprint with the predicate MASKED to a sentinel constant —
    every member of one family differs ONLY in its predicate (the
    descriptor carries the channel, so one family can hold entries
    over different columns without ambiguity), and the snapshot tokens
    still ride in the key so a write retires the whole family."""
    if not isinstance(node, P.Filter):
        return None
    desc = filter_descriptor(node.predicate)
    if desc is None:
        return None
    below = node.source
    while isinstance(below, _FAM_CHAIN):
        below = below.source
    if not isinstance(below, P.TableScan):
        return None
    if uncacheable_reason(node, catalogs) is not None:
        return None
    tables = frozenset(scan_tables(node))
    snap = snapshot_tokens(tables, catalogs)
    if snap is None:
        return None
    masked = dataclasses.replace(node, predicate=Constant("__fam__"))
    fp = plan_fingerprint(masked, catalogs)
    return (f"fam:{fp}:{structural_fingerprint(snap)}", desc, tables)


def select_cache_points(root: P.PhysicalNode, catalogs, *,
                        allow=None,
                        subsumable: bool = False) -> Dict[int, tuple]:
    """Choose the subtrees whose page streams this query caches:
    the MAXIMAL cacheable subtrees that contain at least one
    materializing operator. A fully cacheable plan gets exactly one
    point (its root); a plan with one volatile/system branch still
    caches every clean expensive branch under it. Returns
    {id(subnode): (key, subnode, tables, snap, fam)} — node references
    are held in the values so ids stay stable for the query's
    lifetime; ``snap`` is the snapshot-token tuple the key was built
    from (persistence validates it at warm load), and ``fam`` is
    (family key, descriptor) for subsumable Filter points, None
    otherwise.

    ``allow`` (optional predicate) gates which subtrees may become
    points at all — the distributed executor passes its distribution
    test so only REPLICATED subtrees cache (their pages are ordinary
    single-stream Pages a host replay can reproduce; mesh-SHARDED
    mid-plan pages could not — the ISSUE 15 mesh-path residency
    rule, replacing the old all-or-root restriction).

    ``subsumable`` additionally selects every qualifying
    single-predicate Filter-over-scan node (see family_key) as a
    point, INSIDE already-selected subtrees too — those points are
    what the overlapping-subsumption rewrite probes on an exact
    miss."""
    points: Dict[int, tuple] = {}

    def consider(node) -> bool:
        """True when ``node`` was made a cache point (callers then
        skip its subtree)."""
        if not _worth_caching(node):
            return False
        if allow is not None and not allow(node):
            return False
        if uncacheable_reason(node, catalogs) is None:
            keyed = subtree_key(node, catalogs)
            if keyed is not None:
                key, tables = keyed
                snap = snapshot_tokens(tables, catalogs)
                points[id(node)] = (key, node, tables, snap, None)
                return True
        return False

    def descend(node):
        for c in node.children():
            if not consider(c):
                descend(c)

    if not consider(root):
        descend(root)

    if subsumable:
        def families(node):
            if id(node) not in points and \
                    (allow is None or allow(node)):
                fam = family_key(node, catalogs)
                if fam is not None:
                    fkey, desc, tables = fam
                    keyed = subtree_key(node, catalogs)
                    if keyed is not None:
                        key, _ = keyed
                        snap = snapshot_tokens(tables, catalogs)
                        points[id(node)] = (key, node, tables, snap,
                                            (fkey, desc))
            for c in node.children():
                families(c)

        families(root)

    return points
