"""Persisted observed-stats profiles: what a query's execution actually
measured, keyed so the NEXT run of the same shape can start there.

Reference: the history-based optimization loop presto/Tardigrade sketch
(and PAPER.md's adaptive-execution direction): per-(canonical plan,
connector snapshot) records of observed cardinalities and the settled
capacity bucket. ROADMAP item 4 replans from these; the first consumer
(ISSUE 9) is capacity seeding — a repeated query starts at its settled
`capacity_boost` instead of climbing the overflow-retry ladder again
(`capacity_boost_retries` -> 0 on the second run, counter-pinned).

Keying: a structural fingerprint of the physical plan (dataclass walk,
no object identities — the same SQL over the same catalogs hashes
identically across processes) combined with a connector-snapshot token
(per-scanned-table row counts — a rewritten memory-connector table or
a different scale factor changes the key, so stale profiles are never
applied to different data). Stored as one small JSON file per key
under the `stats_profile_dir` session property (etc key
`stats-profile.dir`); writes are atomic (tmp + rename) so concurrent
queries can share a directory.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Optional

from presto_tpu.obs.sanitizer import make_lock, register_owner


def structural_encode(x, scan_token=None):
    """THE identity-free structural walker: dataclasses encode as
    (classname, field values), containers recurse, anything exotic
    degrades to its type name — so two structurally identical objects
    built in different processes encode byte-identically (no id(), no
    dict ordering, no repr of opaque objects). Shared by the profile
    fingerprint below, the result-cache keys (presto_tpu/cache/), and
    the caching connector's constraint key (connectors/cached.py).

    ``scan_token(scan) -> value``, when given, appends a per-TableScan
    token (the profile store passes the table's current row count —
    its connector-snapshot component)."""
    from presto_tpu.exec import plan as P

    def enc(x):
        if scan_token is not None and isinstance(x, P.TableScan):
            return ("TableScan", x.catalog, x.table,
                    tuple(x.columns), scan_token(x),
                    tuple(sorted((f.name, enc(getattr(x, f.name)))
                                 for f in dataclasses.fields(x)
                                 if f.name not in ("catalog", "table",
                                                   "columns"))))
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            return (type(x).__name__,
                    tuple((f.name, enc(getattr(x, f.name)))
                          for f in dataclasses.fields(x)))
        if isinstance(x, (tuple, list)):
            return tuple(enc(v) for v in x)
        if isinstance(x, dict):
            return tuple(sorted((str(k), enc(v)) for k, v in x.items()))
        if isinstance(x, (str, int, float, bool)) or x is None:
            return x
        return type(x).__name__  # callables/arrays: structure only
    return enc(x)


def structural_fingerprint(x, scan_token=None) -> str:
    """sha256 of the structural encoding, truncated like
    plan_fingerprint (the shared key-material hash)."""
    blob = repr(structural_encode(x, scan_token=scan_token)).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


def plan_fingerprint(plan, catalogs) -> str:
    """Stable structural hash of a physical plan + the snapshot token
    of every table it scans. Deliberately identity-free: dataclasses
    encode as (classname, field values), scans append their current
    row_count, anything exotic degrades to its type name."""

    def rc_token(scan):
        try:
            return catalogs[scan.catalog].row_count(scan.table)
        except Exception:  # noqa: BLE001 - a connector without
            return -1  # counts still fingerprints structurally

    return structural_fingerprint(plan, scan_token=rc_token)


class ProfileStore:
    """Directory-backed profile store with a small in-memory cache.
    `ProfileStore.at(dir)` shares one instance per directory per
    process so concurrent per-query runners reuse the cache."""

    # lock discipline (tools/lint `locks` rule): the in-memory profile
    # cache is shared across the concurrent per-query runners
    _shared_attrs = ("_cache",)

    _instances: Dict[str, "ProfileStore"] = {}
    _instances_lock = make_lock(
        "obs.profile.ProfileStore._instances_lock")

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._cache: Dict[str, dict] = {}
        self._lock = make_lock("obs.profile.ProfileStore._lock")
        register_owner(self)

    @classmethod
    def at(cls, directory: str) -> "ProfileStore":
        directory = os.path.abspath(directory)
        with cls._instances_lock:
            store = cls._instances.get(directory)
        if store is not None:
            return store
        # construct OUTSIDE the instance-map lock: __init__ touches the
        # filesystem (makedirs), which must not stall every other
        # directory's lookup behind one slow mount. Racing creators
        # both build; the map insert below picks one winner.
        store = cls(directory)
        with cls._instances_lock:
            return cls._instances.setdefault(directory, store)

    def key(self, plan, catalogs) -> str:
        return plan_fingerprint(plan, catalogs)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"profile_{key}.json")

    def lookup(self, key: str) -> Optional[dict]:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        try:
            with open(self._path(key)) as f:
                prof = json.load(f)
        except (OSError, ValueError):
            return None
        with self._lock:
            self._cache[key] = prof
        return prof

    def record(self, key: str, profile: dict) -> None:
        """Atomic write (tmp + rename): concurrent recorders of the
        same key race benignly — last writer wins with a complete
        file, never a torn one."""
        with self._lock:
            self._cache[key] = dict(profile)
        tmp = self._path(key) + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(profile, f, sort_keys=True)
            os.replace(tmp, self._path(key))
        except OSError:
            # a read-only/absent dir degrades to in-memory profiles
            try:
                os.unlink(tmp)
            except OSError:
                pass
