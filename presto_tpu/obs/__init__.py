"""THE query-lifecycle observability package (ISSUE 9).

Reference: presto-main's stats plane — the QueryInfo/StageInfo/TaskInfo
trees served by /v1/query, OperatorStats feeding them, QueryMonitor
building EventListener payloads, and the airlift TimeStat/Distribution
histograms behind JMX. Ours is one package with three surfaces:

  trace.py    the span recorder: query -> stage -> task -> attempt ->
              operator spans on ONE monotonic clock with ONE wall
              anchor per query, exported as a live QueryInfo tree
              (/v1/query/{id}, system.runtime_tasks), a Chrome-trace
              (Perfetto-loadable) JSON file, and a critical-path
              summary (tools/analyze_rung.py).
  histo.py    log-bucketed latency histograms with Prometheus
              exposition — the p50/p95/p99 surface the concurrent-load
              benchmark (ROADMAP item 1) reads from /metrics.
  profile.py  the persisted observed-stats profile store keyed by
              (canonical plan fingerprint, connector snapshot):
              settled capacity bucket + observed cardinalities, the
              input adaptive execution (ROADMAP item 4) replans from.

SPAN_KINDS below is the span analog of exec/counters.QUERY_COUNTERS:
every span kind emitted anywhere in the engine is declared here, and
tools/lint's `spans` rule fails the build when an emission site uses
an undeclared kind (or a declared kind has no emission site) — so the
trace vocabulary cannot drift between the recorder, the QueryInfo
tree, and the tools that read them.

Tracing is strictly off the jit path: spans are recorded at page /
attempt / stage boundaries by driver code only (never inside traced
functions), canonical jit keys carry no trace state, and with tracing
off the only cost is one `is None` check per driver loop
(`trace_spans` counter pins that at zero).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from presto_tpu.obs.trace import QueryTrace, critical_path  # noqa: F401

# span kind -> help text (rendered nowhere yet; the declaration is the
# contract the lint enforces, exactly like QUERY_COUNTERS' help column)
SPAN_KINDS: Dict[str, str] = {
    "query": "the whole query: wall anchor + every child span",
    "execute": "one local executor run of a plan (the overflow-ladder "
               "driver; the coordinator's root fragment and every "
               "LocalRunner query get one)",
    "attempt": "one overflow-ladder attempt (attrs: capacity boost; "
               "a query with N-1 boosted retries has N of these)",
    "operator": "per-plan-node wall/rows/pages from the EXPLAIN "
                "ANALYZE accounting, anchored at its attempt's start",
    "stage": "one stage-DAG wave dispatched by dist/scheduler.py",
    "task": "one logical task of a stage (coordinator view; attrs: "
            "uri, retries, pages; worker-side spans nest inside)",
    "dispatch": "one task-submit POST to a worker",
    "queue": "worker-side: task created -> fragment execution started",
    "run": "worker-side: fragment execution (attrs: pages, spooled)",
    "fetch": "coordinator-side page drain of one task's results",
    "retry": "one task re-dispatch (attrs: from/to uri, cause) — the "
             "fault-tolerance paths' trace annotation",
    "speculate": "one straggler-speculation copy dispatched (attrs: "
                 "uri); win/loss lands on the task span",
    "replan": "one adaptive re-plan evaluated at a stage boundary "
              "(presto_tpu/adaptive/): attrs carry the flip/seed/"
              "skew-hint counts, or rejected=true with the "
              "verify_dag reason when the mutation rolled back — "
              "the interval is the stats-summation + re-verify wall "
              "the ROOFLINE §13 cost model prices",
    "xfer": "one metered host<->device crossing (exec/xfer.py choke "
            "points): d2h:<label> pulls pages/arrays to host (spill, "
            "exchange serialization, result decode), h2d:<label> "
            "stages host pages onto the device (restream, cache "
            "replay, remote-source ingest); attrs carry bytes, and "
            "the summed span wall equals the query's transfer_wall_s "
            "counter — the copy-time phase ROADMAP item 6 drives "
            "toward zero",
    "cache": "one result-cache point served (presto_tpu/cache/): "
             "hit:<Node> replays stored pages (attrs: pages, key) in "
             "the span's interval — compile+launch skipped; "
             "miss:<Node> marks the lookup, the real execution "
             "follows as ordinary attempt/operator spans",
    "checkpoint": "one durable coordinator-journal publish "
                  "(dist/checkpoint.py): attrs carry the record "
                  "state and serialized bytes — the barrier-write "
                  "cost the ROOFLINE §18 model prices against the "
                  "stage wall it rides on",
}


def maybe_trace(session, query_id: Optional[str] = None,
                sql: Optional[str] = None) -> Optional[QueryTrace]:
    """A QueryTrace when the session enables tracing, else None (the
    near-zero-cost off switch: every recording site guards on the
    executor's `trace is None`)."""
    if not (bool(session.get("query_trace_enabled"))
            or session.get("query_trace_dir")):
        return None
    if query_id is None:
        import uuid

        query_id = f"q-{uuid.uuid4().hex[:12]}"
    return QueryTrace(query_id, sql=sql)


def attach(executor, trace: QueryTrace) -> None:
    """Hand a trace to an executor for the next query; resets the
    per-query `trace_spans` counter the tracing-off test pins."""
    executor.trace = trace
    executor.trace_spans = 0


def finalize(executor, trace: QueryTrace,
             trace_dir: Optional[str] = None) -> None:
    """End the root span, write the Chrome-trace file when a directory
    is configured (session prop `query_trace_dir` / etc key
    `query-trace.dir`), detach, and settle the span-count counter.
    The file write degrades gracefully (same discipline as
    profile.ProfileStore.record): finalize runs inside callers'
    finally blocks, so an unwritable trace dir must neither fail a
    successful query nor mask an in-flight error."""
    trace.finish()
    executor.trace = None
    executor.trace_spans = trace.span_count
    if trace_dir:
        try:
            os.makedirs(trace_dir, exist_ok=True)
            trace.write_chrome(
                os.path.join(trace_dir,
                             f"{trace.query_id}.trace.json")
            )
        except OSError:
            pass  # observability must never fail the query
