"""Runtime lock sanitizer + THE engine concurrency registry (ISSUE 11).

Reference: the concurrency tooling the Java original leans on —
`@GuardedBy` annotations checked by error-prone, `synchronized` audits
in review, and ThreadSanitizer-style CI jobs racing the coordinator's
state machines deliberately. The Python rebuild gets the same two
layers: `tools/concheck.py` is the static side (lock inventory,
acquisition-order graph, blocking-under-lock); THIS module is the
dynamic side — an opt-in instrumented lock that records what actually
happens at runtime:

  - per-thread held-lock sets and every observed acquisition ordering
    (lock A held while acquiring lock B);
  - lock-order INVERSIONS observed live (A-then-B somewhere,
    B-then-A somewhere else — the classic two-thread deadlock shape),
    recorded with both sites;
  - re-entrant acquisition of a non-reentrant lock (a guaranteed
    self-deadlock: the sanitizer raises instead of hanging CI);
  - writes to a class's declared `_shared_attrs` without any of the
    object's registered locks held (the `tools/lint` locks-rule
    contract, enforced against real interleavings instead of the AST).

Zero-cost when off: `make_lock`/`make_condition` return plain
`threading` primitives and `register_owner` is a no-op boolean check,
so the serving path pays nothing. Armed (env
`PRESTO_TPU_LOCK_SANITIZER=1`, the tier-1 conftest, `tools/loadbench
--sanitize`, `tools/chaos.py --sanitize`), every engine lock is a
`_SanitizedLock` and every registered owner's class is swapped for an
instrumented subclass whose `__setattr__` checks the lock contract.
Violations accumulate in a process-wide list (they never raise except
for the guaranteed-deadlock case) — harnesses assert `violations()`
is empty after racing the engine.

Granularity caveats, documented not hidden: ordering is tracked by
lock NAME (one name per class attribute — two instances of the same
lock rank are not ordered against each other), and `__setattr__`
instrumentation sees attribute REBINDS only (`self._entries[k] = v`
mutates a dict in place and is invisible here — the static locks rule
covers subscript writes).

The two registries below are the `QUERY_COUNTERS`/`SPAN_KINDS`
discipline applied to concurrency: every lock/Condition the engine
creates and every `threading.Thread` target it spawns is declared
here with help text, and `tools/concheck.py` fails when a site is
undeclared or an entry is stale.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------
# THE concurrency registry. Keys are canonical site names: the dotted
# module path under presto_tpu/ plus the owning class (if any) and the
# attribute — exactly the literal each make_lock()/make_condition()
# call site passes, cross-checked by tools/concheck.py.

LOCK_REGISTRY: Dict[str, str] = {
    "cache.store.ResultCache._lock":
        "the process-shared result-cache store: entry map, byte "
        "accounting, LRU order, tallies",
    "cache.store._shared_lock":
        "creation of THE per-process shared ResultCache instance",
    "cache.persist.ManifestStore._lock":
        "the generation-numbered manifest's in-memory entry map + "
        "pending-append queue (shared by the result-cache warm tier "
        "and the coordinator checkpoint journal) — append/compaction "
        "file I/O runs OUTSIDE it on a drain loop (take batch under "
        "lock marking the writer busy, write outside, re-check)",
    "dist.checkpoint.CheckpointJournal._lock":
        "the coordinator checkpoint journal's per-query record map "
        "(protocol threads noting client tokens vs scheduler threads "
        "recording stage barriers on the same query) — durable "
        "publishes go through the ManifestStore OUTSIDE this lock",
    "dist.cacheprobe.RemoteCacheIndex._lock":
        "per-worker bloom summaries of cached fragment keys: "
        "heartbeat threads write (update_from_info), scheduler "
        "dispatch threads read (might_contain) — pure bytes ops, "
        "probes themselves go over connpool OUTSIDE the lock",
    "connectors.stream.StreamConnector._cv":
        "the append-log table map + offset advance; appends "
        "notify_all so tailing long-pollers (wait_for_offset) wake",
    "dist.connpool.ConnectionPool._lock":
        "the per-destination keep-alive connection free-lists + "
        "reuse/failover tallies (take/put are pure list ops — every "
        "connect, send, and read happens OUTSIDE the lock)",
    "compilecache._lock":
        "process-wide XLA compile/cache counters fed by jax.monitoring "
        "listeners",
    "obs.histo.Histogram._lock":
        "latency-histogram buckets (observe vs scrape)",
    "obs.profile.ProfileStore._instances_lock":
        "the per-directory ProfileStore instance map (class-level)",
    "obs.profile.ProfileStore._lock":
        "one profile store's in-memory profile cache",
    "obs.trace.QueryTrace._lock":
        "one query's span list (scheduler dispatch loop vs status "
        "polls record concurrently)",
    "server.heartbeat.HeartbeatFailureDetector._lock":
        "peer-health map shared between the ping loop and query-path "
        "readers",
    "server.http_server.MemoryArbiter._cv":
        "HBM-footprint admission: used/active accounting + waiters",
    "server.launch_batcher.LaunchBatcher._cv":
        "the cross-query batch point: pending gather-groups keyed by "
        "jit-key family; leaders gather under a bounded window, "
        "followers park for the published per-slot results — the "
        "shared device dispatch itself runs OUTSIDE this lock",
    "server.http_server.QueryManager._exec_lock":
        "the serial-path device lock (one query on the chip when no "
        "memory arbiter is configured)",
    "server.http_server.QueryManager._lock":
        "query registry + completion tallies shared between HTTP "
        "handler threads and per-query executor threads",
    "server.resource_groups.ResourceGroupManager._lock":
        "admission queues/slots/memory per resource-group path "
        "(Condition-fronted: acquire blocks on it)",
    "server.worker._runtimes_lock":
        "the same-process placement registry (uri -> TaskRuntime) the "
        "mesh-local exchange fast path reads",
    "server.worker.TaskRuntime._fault_lock":
        "fault-injection overlay + the drop/kill call counters",
    "server.worker.TaskRuntime._tasks_lock":
        "the task registry (create/expire/cancel vs data-plane "
        "lookups)",
    "server.worker._Task.lock":
        "one task's result buffers and lifecycle flags (executor "
        "thread vs fetch/status/cancel handlers)",
    "server.http_server.TailCursor._cv":
        "one tailing cursor's emitted rows / token spans / poll "
        "serialization flag (concurrent protocol GETs on one "
        "cursor); the poll's query execution runs UNLOCKED behind "
        "the _polling flag",
    "streaming.ivm.IvmRegistry._lock":
        "the materialized-view registry (register/lookup by name "
        "and by statement shape fingerprint)",
    "streaming.ivm.MaterializedView._cv":
        "one view's persisted state/watermark/last-result "
        "publication + refresh serialization flag; the refresh "
        "itself (delta scan, fold, finalize) runs UNLOCKED behind "
        "_refreshing so concurrent tailers coalesce",
    "streaming.ivm._shared_lock":
        "creation of THE per-process shared IvmRegistry instance",
}

THREAD_REGISTRY: Dict[str, str] = {
    "server.heartbeat:self._loop":
        "background peer-ping loop (daemon; stops via Event)",
    "server.http_server:self._run":
        "one thread per submitted query: admission -> execute -> "
        "completion",
    "server.http_server:self._httpd.serve_forever":
        "the coordinator's HTTP accept loop",
    "server.worker:self._run_task":
        "one thread per task: fragment execution into the spool/page "
        "buffers",
    "server.http_server:self._reattach_run":
        "one thread per journaled query on a restarted coordinator: "
        "recover via dist.checkpoint.reattach_query, verify the "
        "delivered-page digests, settle FINISHED/FAILED",
    "server.worker:self._httpd.serve_forever":
        "the worker's HTTP accept loop",
}

# ---------------------------------------------------------------------
# arming

_armed = os.environ.get("PRESTO_TPU_LOCK_SANITIZER", "") in (
    "1", "true", "on")
_THIS_FILE = os.path.abspath(__file__)
_THREADING_FILE = os.path.abspath(threading.__file__)

_tls = threading.local()
_meta = threading.Lock()  # raw on purpose: the instrumentation's own
_order: Dict[Tuple[str, str], str] = {}     # (held, acquired) -> site
_violations: List[str] = []
_subclasses: Dict[type, type] = {}


def arm() -> None:
    """Instrument locks created FROM NOW ON (creation-time choice:
    already-created plain locks stay plain)."""
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def is_armed() -> bool:
    return _armed


def reset() -> None:
    """Clear recorded violations and orderings (test isolation)."""
    with _meta:
        _violations.clear()
        _order.clear()


def violations() -> List[str]:
    with _meta:
        return list(_violations)


def violation_count() -> int:
    with _meta:
        return len(_violations)


def order_edges() -> Dict[Tuple[str, str], str]:
    """Observed (held, acquired) orderings with their first site."""
    with _meta:
        return dict(_order)


def report() -> str:
    """Human-readable violation dump (harness failure output)."""
    v = violations()
    if not v:
        return "# lock sanitizer: 0 violations"
    return "# lock sanitizer: {} violation(s)\n".format(len(v)) + \
        "\n".join(f"  - {x}" for x in v)


# ---------------------------------------------------------------------
# internals

def _held() -> List["_SanitizedLock"]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _site() -> str:
    """First caller frame outside this module and threading.py (the
    Condition wrapper calls acquire/release from threading.py)."""
    f = sys._getframe(1)
    for _ in range(12):
        if f is None:
            break
        fn = f.f_code.co_filename
        if fn not in (_THIS_FILE, _THREADING_FILE):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _violation(msg: str) -> None:
    with _meta:
        _violations.append(msg)


class _SanitizedLock:
    """Duck-typed non-reentrant lock recording held-sets/orderings.
    Works as a `threading.Condition` backing lock: Condition lifts
    acquire/release/_is_owned, so wait() keeps the held-set honest."""

    __slots__ = ("name", "_raw")

    def __init__(self, name: str):
        self.name = name
        self._raw = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held()
        for h in held:
            if h is self:
                msg = (f"re-entrant acquire of non-reentrant lock "
                       f"{self.name} at {_site()} — guaranteed "
                       f"self-deadlock")
                _violation(msg)
                raise RuntimeError(msg)
        if timeout == -1:
            got = self._raw.acquire(blocking)
        else:
            got = self._raw.acquire(blocking, timeout)
        if got:
            if held:
                site = _site()
                with _meta:
                    for h in held:
                        if h.name == self.name:
                            continue
                        pair = (h.name, self.name)
                        inverse = (self.name, h.name)
                        if inverse in _order and pair not in _order:
                            _violations.append(
                                f"lock-order inversion: {self.name} "
                                f"acquired while holding {h.name} at "
                                f"{site}, but the opposite order was "
                                f"observed at {_order[inverse]}")
                        _order.setdefault(pair, site)
            held.append(self)
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._raw.release()

    def __enter__(self) -> "_SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def _is_owned(self) -> bool:
        # lifted by threading.Condition (beats its acquire(0) probe)
        return any(h is self for h in _held())

    held_by_me = _is_owned


# ---------------------------------------------------------------------
# the factory surface engine modules create their locks through

def make_lock(name: str):
    """A lock for the canonical site ``name`` (a LOCK_REGISTRY key —
    tools/concheck.py cross-checks the literal against the site)."""
    if _armed:
        return _SanitizedLock(name)
    return threading.Lock()


def make_condition(name: Optional[str] = None, lock=None):
    """A Condition; pass ``lock=`` to front an existing engine lock
    (the ResourceGroupManager shape — holding the Condition IS holding
    the lock, so the held-set stays unified), else a dedicated backing
    lock is created under ``name``."""
    if lock is None:
        assert name is not None, "make_condition needs a name or a lock"
        lock = make_lock(name)
    return threading.Condition(lock)


def _resolve_lock(obj, attr: str) -> Optional[_SanitizedLock]:
    x = getattr(obj, attr, None)
    if isinstance(x, _SanitizedLock):
        return x
    if isinstance(x, threading.Condition) and \
            isinstance(x._lock, _SanitizedLock):
        return x._lock
    return None


def _subclass_for(cls: type, lock_attrs: Tuple[str, ...]) -> type:
    sub = _subclasses.get(cls)
    if sub is not None:
        return sub
    shared = frozenset(getattr(cls, "_shared_attrs", ()) or ())

    def __setattr__(self, name, value):
        if name in shared:
            locks = [_resolve_lock(self, a) for a in lock_attrs]
            locks = [lk for lk in locks if lk is not None]
            if locks and not any(lk._is_owned() for lk in locks):
                _violation(
                    f"unlocked shared-attr write: "
                    f"{cls.__module__}.{cls.__name__}.{name} written "
                    f"without {'/'.join(lk.name for lk in locks)} "
                    f"held at {_site()}")
        object.__setattr__(self, name, value)

    sub = type(cls.__name__, (cls,), {
        "__setattr__": __setattr__,
        "_san_instrumented": True,
        "__module__": cls.__module__,
    })
    _subclasses[cls] = sub
    return sub


def register_owner(obj, lock_attrs=("_lock",)):
    """Called at the end of a lock-owning __init__: when armed, swap
    the instance's class for an instrumented subclass that checks every
    `_shared_attrs` rebind happens under one of ``lock_attrs``. No-op
    (one bool check) when off."""
    if not _armed:
        return obj
    cls = type(obj)
    if getattr(cls, "_san_instrumented", False):
        return obj
    if not getattr(cls, "_shared_attrs", None):
        return obj
    if not any(_resolve_lock(obj, a) for a in lock_attrs):
        return obj  # plain locks (created before arming): uncheckable
    try:
        obj.__class__ = _subclass_for(cls, tuple(lock_attrs))
    except TypeError:
        pass  # __slots__/extension classes cannot be swapped; skip
    return obj
