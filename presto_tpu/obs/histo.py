"""Latency histograms with Prometheus exposition.

Reference: airlift's TimeStat/Distribution behind the JMX beans
presto-jmx exposes; ours is a fixed log-bucketed histogram rendered in
the Prometheus text format (cumulative `_bucket{le=...}` lines plus
`_sum`/`_count`), the shape every Prometheus/Grafana p50/p95/p99 query
expects — and the surface ROADMAP item 1's concurrent-load benchmark
reads query latency from.

Buckets are static (no per-observation allocation) and span 1 ms to
10 min geometrically: sub-bucket precision is irrelevant at the tails
and the fixed bounds make histograms from different processes
mergeable by simple addition.
"""

from __future__ import annotations

import bisect
from typing import List, Sequence

from presto_tpu.obs.sanitizer import make_lock, register_owner

# seconds; geometric ~2.5x ladder from 1ms to 600s
DEFAULT_BOUNDS: Sequence[float] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 60.0, 120.0, 300.0, 600.0,
)


class Histogram:
    """Thread-safe fixed-bucket histogram of seconds."""

    # lock discipline (tools/lint `locks` rule): observation state
    # shared between completion paths and /metrics scrapes
    _shared_attrs = ("counts", "total", "sum")

    def __init__(self, bounds: Sequence[float] = DEFAULT_BOUNDS):
        self.bounds: List[float] = sorted(float(b) for b in bounds)
        # counts[i] = observations <= bounds[i] exclusive-bucket form;
        # counts[-1] = the +Inf overflow bucket
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.total = 0
        self.sum = 0.0
        self._lock = make_lock("obs.histo.Histogram._lock")
        register_owner(self)

    def observe(self, seconds: float) -> None:
        v = max(float(seconds), 0.0)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.total += 1
            self.sum += v

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (p50/p95/p99). Exact
        enough for dashboards: the answer lands inside the right
        bucket and interpolates linearly within it."""
        with self._lock:
            total = self.total
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0
        for i, c in enumerate(counts):
            if seen + c >= rank and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1])
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.bounds[-1]

    def prom_lines(self, name: str) -> List[str]:
        """Prometheus histogram exposition: cumulative buckets + sum +
        count (the registry-driven /metrics block appends these)."""
        with self._lock:
            counts = list(self.counts)
            total = self.total
            s = self.sum
        lines = [f"# TYPE {name} histogram"]
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            le = f"{bound:g}"
            lines.append(f'{name}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{name}_sum {s:.6f}")
        lines.append(f"{name}_count {total}")
        return lines
