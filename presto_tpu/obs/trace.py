"""Span recorder for one query's lifecycle.

Reference: presto-main's QueryInfo/StageInfo/TaskInfo tree (server/
QueryStateMachine + execution/StageStateMachine assembling it live)
and the QueryMonitor that flattens it into EventListener payloads.

Timing model (the ISSUE 9 drift fix): every span interval is measured
on `time.monotonic()` as an offset from the trace's creation instant,
and the trace carries exactly ONE wall-clock anchor (`anchor_wall`,
taken once at creation). Cross-node ingestion never subtracts two
machines' wall clocks — worker spans arrive as offsets from the
worker's own task-creation instant and are re-based into the
coordinator's task-span window, clamped to it, so clock skew can
shift a remote span inside its parent but can never make a duration
negative or a child escape its parent.

The recorder is deliberately dumb: append-only span list, explicit
parent links, one lock. All structure (QueryInfo tree, Chrome trace,
critical path) is derived at read time — recording at page/stage
boundaries stays O(1) and allocation-light, and NOTHING here is
reachable from jit keys or traced functions (tools/lint purity rule).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional

from presto_tpu.obs.sanitizer import make_lock, register_owner


@dataclasses.dataclass
class Span:
    """One interval. t0/t1 are seconds since the trace's monotonic
    anchor; t1 is None while the span is open."""

    span_id: int
    parent_id: Optional[int]
    kind: str
    name: str
    t0: float
    t1: Optional[float] = None
    attrs: Dict[str, object] = dataclasses.field(default_factory=dict)

    def dur(self, now: float = 0.0) -> float:
        end = self.t1 if self.t1 is not None else now
        return max(end - self.t0, 0.0)


class QueryTrace:
    """One query's span tree. Thread-safe (worker status polls and the
    scheduler's dispatch loop record concurrently); reads snapshot."""

    # lock discipline (tools/lint `locks` rule): the span list and its
    # sequence counter are the shared recording surface
    _shared_attrs = ("_spans", "_seq")

    def __init__(self, query_id: str, sql: Optional[str] = None,
                 anchor_mono: Optional[float] = None,
                 anchor_wall: Optional[float] = None):
        self.query_id = query_id
        # THE one wall-clock read per query (display/correlation only;
        # never used in interval arithmetic)
        self.anchor_wall = (time.time() if anchor_wall is None
                            else anchor_wall)
        self._anchor_mono = (time.monotonic() if anchor_mono is None
                             else anchor_mono)
        self._lock = make_lock("obs.trace.QueryTrace._lock")
        self._spans: List[Span] = []
        self._seq = 0
        attrs = {"sql": sql} if sql else {}
        self.root = self._new("query", query_id, None, 0.0, None, attrs)
        register_owner(self)

    # ------------------------------------------------------- recording
    def now(self) -> float:
        return time.monotonic() - self._anchor_mono

    def _new(self, kind, name, parent, t0, t1, attrs) -> Span:
        with self._lock:
            self._seq += 1
            sp = Span(self._seq, parent, kind, name, t0, t1,
                      dict(attrs))
            self._spans.append(sp)
            return sp

    def begin(self, kind: str, name: str,
              parent: Optional[Span] = None, **attrs) -> Span:
        pid = (parent or self.root).span_id
        return self._new(kind, name, pid, self.now(), None, attrs)

    def end(self, span: Span, **attrs) -> Span:
        with self._lock:
            if span.t1 is None:
                span.t1 = time.monotonic() - self._anchor_mono
            span.attrs.update(attrs)
        return span

    def complete(self, kind: str, name: str, t0: float, t1: float,
                 parent: Optional[Span] = None, **attrs) -> Span:
        return self._new(kind, name, (parent or self.root).span_id,
                         t0, max(t1, t0), attrs)

    def ingest(self, remote: List[dict], parent: Span,
               lo: float, hi: float) -> int:
        """Nest worker-shipped spans (offsets from the worker's task
        creation) under a coordinator span, re-based at `lo` and
        CLAMPED to [lo, hi] — the skew guard: a remote interval can
        never go negative or escape its coordinator-side window."""
        n = 0
        for d in remote:
            try:
                t0 = min(max(lo + float(d["t0"]), lo), hi)
                t1 = min(max(lo + float(d["t1"]), t0), hi)
                self._new(str(d["kind"]), str(d.get("name", "")),
                          parent.span_id, t0, t1,
                          dict(d.get("attrs") or {}))
                n += 1
            except (KeyError, TypeError, ValueError):
                continue  # a malformed remote span is dropped, not fatal
        return n

    def finish(self) -> None:
        self.end(self.root)
        # close any straggler open spans at the root's end (a failed
        # query abandons its in-flight task spans)
        with self._lock:
            for sp in self._spans:
                if sp.t1 is None:
                    sp.t1 = self.root.t1

    # ----------------------------------------------------------- reads
    @property
    def span_count(self) -> int:
        with self._lock:
            return len(self._spans)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def export(self) -> List[dict]:
        """Wire form for shipping to a coordinator (worker status
        plane): non-root spans as plain dicts of anchored offsets."""
        out = []
        now = self.now()
        for sp in self.spans():
            if sp.span_id == self.root.span_id:
                continue
            out.append({
                "kind": sp.kind, "name": sp.name, "t0": sp.t0,
                "t1": sp.t1 if sp.t1 is not None else now,
                "attrs": sp.attrs,
            })
        return out

    # ------------------------------------------------- QueryInfo tree
    def to_info(self) -> dict:
        """The QueryInfo/StageInfo/TaskInfo tree (reference:
        /v1/query/{id}'s JSON). Stage-DAG queries render their real
        stages; local executions synthesize one stage ("local") whose
        single task holds the attempt/operator spans — every query
        shape serves the same tree."""
        spans = self.spans()
        now = self.now()
        children: Dict[int, List[Span]] = {}
        for sp in spans:
            if sp.parent_id is not None:
                children.setdefault(sp.parent_id, []).append(sp)

        def ms(t: float) -> int:
            return int(round(t * 1000))

        def descend(sp: Span) -> List[dict]:
            out = []
            for c in sorted(children.get(sp.span_id, ()),
                            key=lambda s: (s.t0, s.span_id)):
                out.append({
                    "kind": c.kind, "name": c.name,
                    "startMs": ms(c.t0),
                    "endMs": ms(c.t1 if c.t1 is not None else now),
                    "attrs": c.attrs,
                })
                out.extend(descend(c))
            return out

        def task_info(sp: Span, task_id: str) -> dict:
            return {
                "taskId": task_id,
                "uri": sp.attrs.get("uri"),
                "state": ("RUNNING" if sp.t1 is None else
                          str(sp.attrs.get("state", "FINISHED"))),
                "startMs": ms(sp.t0),
                "endMs": ms(sp.t1 if sp.t1 is not None else now),
                "wallMs": ms(sp.dur(now)),
                "rows": sp.attrs.get("rows"),
                "pages": sp.attrs.get("pages"),
                "retries": sp.attrs.get("retries", 0),
                "spans": descend(sp),
            }

        stages = []
        for sp in sorted((s for s in spans if s.kind == "stage"),
                         key=lambda s: (s.t0, s.span_id)):
            tasks = [task_info(c, c.name)
                     for c in children.get(sp.span_id, ())
                     if c.kind == "task"]
            stages.append({
                "stageId": sp.name,
                "state": "RUNNING" if sp.t1 is None else "FINISHED",
                "startMs": ms(sp.t0),
                "endMs": ms(sp.t1 if sp.t1 is not None else now),
                "wallMs": ms(sp.dur(now)),
                "tasks": tasks,
            })
        if not stages:
            # local execution: one synthetic stage per executor run
            execs = [s for s in spans if s.kind == "execute"]
            tasks = [task_info(sp, f"local.{i}")
                     for i, sp in enumerate(execs)]
            if tasks:
                stages = [{
                    "stageId": "local",
                    "state": ("RUNNING" if any(s.t1 is None
                                               for s in execs)
                              else "FINISHED"),
                    "startMs": ms(min(s.t0 for s in execs)),
                    "endMs": ms(max(s.t1 if s.t1 is not None else now
                                    for s in execs)),
                    "wallMs": ms(max(s.dur(now) for s in execs)),
                    "tasks": tasks,
                }]
        return {
            "queryId": self.query_id,
            "createTime": self.anchor_wall,
            "elapsedMs": ms(self.root.dur(now)),
            "spanCount": len(spans),
            "stages": stages,
        }

    # ------------------------------------------------- Chrome export
    def to_chrome(self) -> dict:
        """Chrome-trace (Perfetto-loadable) JSON: complete (`X`)
        events in microseconds since the query's wall anchor, sorted
        by ts, one tid lane per stage/task/execute container."""
        spans = self.spans()
        now = self.now()
        lane_of: Dict[int, int] = {self.root.span_id: 0}
        by_id = {sp.span_id: sp for sp in spans}
        next_lane = [0]

        def lane(sp: Span) -> int:
            if sp.span_id in lane_of:
                return lane_of[sp.span_id]
            if sp.kind in ("stage", "task", "execute"):
                next_lane[0] += 1
                lane_of[sp.span_id] = next_lane[0]
                return next_lane[0]
            parent = by_id.get(sp.parent_id)
            lane_of[sp.span_id] = lane(parent) if parent else 0
            return lane_of[sp.span_id]

        events = []
        for sp in spans:
            end = sp.t1 if sp.t1 is not None else now
            args = {k: v for k, v in sp.attrs.items() if v is not None}
            events.append({
                "name": f"{sp.kind}:{sp.name}",
                "cat": sp.kind,
                "ph": "X",
                "ts": int(round(sp.t0 * 1e6)),
                "dur": int(round(max(end - sp.t0, 0.0) * 1e6)),
                "pid": 1,
                "tid": lane(sp),
                "args": args,
            })
        events.sort(key=lambda e: (e["ts"], e["tid"]))
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "queryId": self.query_id,
                "wallAnchorUnixS": self.anchor_wall,
            },
        }

    def write_chrome(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f, indent=1, default=str)
        return path


def critical_path(trace: QueryTrace) -> dict:
    """The slowest chain through the span tree plus a per-kind wall
    split (queue-vs-run-vs-fetch for distributed queries; attempt/
    operator locally) — tools/analyze_rung.py's summary input."""
    spans = trace.spans()
    now = trace.now()
    children: Dict[int, List[Span]] = {}
    for sp in spans:
        if sp.parent_id is not None:
            children.setdefault(sp.parent_id, []).append(sp)
    chain, cur = [], trace.root
    while True:
        kids = children.get(cur.span_id)
        if not kids:
            break
        cur = max(kids, key=lambda s: s.dur(now))
        chain.append({
            "kind": cur.kind, "name": cur.name,
            "ms": int(round(cur.dur(now) * 1000)),
        })
    by_kind: Dict[str, float] = {}
    for sp in spans:
        if sp.span_id == trace.root.span_id:
            continue
        by_kind[sp.kind] = by_kind.get(sp.kind, 0.0) + sp.dur(now)
    return {
        "chain": chain,
        "by_kind_ms": {k: int(round(v * 1000))
                       for k, v in sorted(by_kind.items())},
    }
