"""DCN coordinator: multi-process query execution over localhost (or
any network) workers.

Reference: the coordinator half of distributed execution —
server/remotetask/HttpRemoteTask.java (task create + status),
operator/ExchangeClient.java + HttpPageBufferClient.java (token-acked
page fetch with retries), metadata/DiscoveryNodeManager +
failureDetector/HeartbeatFailureDetector (peer liveness).

TPU-native shape (SURVEY §6.8): ICI-scale parallelism stays INSIDE a
worker process as compiled collectives; this layer is the DCN half —
processes exchange serialized pages over HTTP exactly where the
reference does, at one of two fragment boundaries:

    PARTIAL/FINAL aggregation cut (tiny state pages; preferred):
      worker w: scan(splits w::K of fact table) -> ... -> partial agg
      coordinator: RemoteSource(all workers) -> final agg -> rest
    UNION cut (general row-local subtree; multi-join pipelines with
    no decomposable aggregation):
      worker w: row-local subtree over split share -> result pages
      coordinator: RemoteSource union -> sort/topN/window/agg -> rest

Either way the task body carries the coordinator's SERIALIZED physical
fragment (dist/plan_serde.py — the reference's TaskUpdateRequest
PlanFragment); workers execute exactly that tree, never re-planning.
Scans split round-robin or hash-co-partitioned on join keys (both big
join sides 1/N per worker; hash_fanout_source).

Failure model matches the reference: a worker death or exhausted fetch
retries fails the QUERY cleanly (no task-level recovery; SURVEY §6.3),
while the heartbeat detector tracks liveness for scheduling decisions.
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.error
import urllib.request
import uuid
from typing import Dict, List, Optional

from presto_tpu.dist import plan_serde, serde
from presto_tpu.exec import plan as P
from presto_tpu.server.heartbeat import HeartbeatFailureDetector
from presto_tpu.server.worker import (
    fanout_safe,
    find_partial_cut,
    find_union_cut,
    hash_fanout_plan,
    hash_fanout_source,
    largest_table,
)


class DcnQueryFailed(RuntimeError):
    """Query-level failure (reference: the fail-query-and-let-the-
    client-retry model — no task-level recovery)."""


def _replace_node(root, target, repl):
    """Structural replace of one subtree in a frozen plan tree."""
    if root is target:
        return repl
    changes = {}
    for f in dataclasses.fields(root):
        v = getattr(root, f.name)
        if isinstance(v, P.PhysicalNode):
            nv = _replace_node(v, target, repl)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and isinstance(
            v[0], P.PhysicalNode
        ):
            nv = tuple(_replace_node(x, target, repl) for x in v)
            if any(a is not b for a, b in zip(nv, v)):
                changes[f.name] = nv
    return dataclasses.replace(root, **changes) if changes else root


class DcnRunner:
    """Coordinator over N worker processes (single fat workers each).

    execute(sql) returns (names, rows) like LocalRunner.execute's
    underlying executor, with the heavy PARTIAL pipeline fanned out.
    """

    def __init__(self, catalogs, worker_uris: List[str], *,
                 default_catalog: Optional[str] = None,
                 page_rows: int = 1 << 16,
                 fetch_retries: int = 3,
                 session_props: Optional[Dict] = None,
                 partition_threshold: int = 1 << 17):
        from presto_tpu.runner import LocalRunner
        from presto_tpu.session import Session

        self.worker_uris = list(worker_uris)
        self.fetch_retries = fetch_retries
        self.partition_threshold = partition_threshold
        # introspection: distribution used by the last execute()
        # ("hash" partitioned join | "roundrobin" | "local")
        self.last_distribution = "local"
        self.session_props = dict(session_props or {})
        cat = default_catalog or next(iter(catalogs))
        self.runner = LocalRunner(
            catalogs,
            page_rows=page_rows,
            default_catalog=cat,
            session=Session(catalog=cat,
                            properties=self.session_props),
        )
        self.heartbeat = HeartbeatFailureDetector(
            [f"{u}" for u in self.worker_uris]
        )

    # --------------------------------------------------------- protocol
    def _post_task(self, uri: str, payload: Dict) -> Dict:
        req = urllib.request.Request(
            f"{uri}/v1/task",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read().decode())

    def _fetch_pages(self, uri: str, task_id: str):
        """Token-acked page fetch with bounded retries (the
        HttpPageBufferClient protocol: at-least-once + dedupe by
        token)."""
        token = 0
        while True:
            attempt = 0
            while True:
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/task/{task_id}/results/{token}"
                    )
                    with urllib.request.urlopen(req, timeout=60) as r:
                        if r.status == 204:
                            if r.headers.get("X-Done") == "1":
                                return
                            break  # long-poll timeout; re-ask
                        body = r.read()
                        token = int(r.headers["X-Next-Token"])
                        yield serde.deserialize_page(body)
                        break
                except (urllib.error.URLError, urllib.error.HTTPError,
                        ConnectionError, OSError) as e:
                    attempt += 1
                    if attempt > self.fetch_retries:
                        raise DcnQueryFailed(
                            f"worker {uri} task {task_id}: page fetch "
                            f"failed after {self.fetch_retries} "
                            f"retries: {e}"
                        ) from e
                    time.sleep(0.1 * attempt)

    # ---------------------------------------------------------- execute
    def execute(self, sql: str):
        plan = self.runner.plan(sql)
        ex = self.runner.executor
        cut = find_partial_cut(plan)
        partial = coord_plan = partition_cols = split_table = None
        if cut is not None:
            # best shape: PARTIAL/FINAL aggregation split — workers
            # ship tiny accumulator-state pages. PARTITIONED JOIN
            # first (the hash-repartition exchange: both big join
            # sides co-partitioned by key hash, build state 1/N per
            # worker); round-robin split-table fan-out (replicated
            # builds) is the fallback shape
            partition_cols = hash_fanout_plan(
                cut, self.runner.catalogs,
                partition_threshold=self.partition_threshold,
            )
            split_table = largest_table(cut.source,
                                        self.runner.catalogs)
            if partition_cols is not None or (
                split_table is not None
                and fanout_safe(cut, split_table)
            ):
                self.last_distribution = (
                    "hash" if partition_cols is not None
                    else "roundrobin"
                )
                partial = dataclasses.replace(cut, step="partial")
        if partial is None:
            # general shape: UNION CUT — workers execute the topmost
            # row-local subtree (multi-join pipelines, no aggregation
            # required) over their split share; the coordinator unions
            # the pages and runs everything above (sort/topN/window/
            # non-decomposable aggregation). Reference: a leaf-stage
            # fragment under a GATHER exchange.
            split_table = largest_table(plan, self.runner.catalogs)
            ucut = (find_union_cut(plan, split_table)
                    if split_table is not None else None)
            if ucut is None:
                # nothing distributable: run locally rather than wrong
                self.last_distribution = "local"
                return self.runner.execute(sql).rows
            partition_cols = hash_fanout_source(
                ucut, self.runner.catalogs,
                partition_threshold=self.partition_threshold,
            )
            self.last_distribution = (
                "union-hash" if partition_cols is not None
                else "union-roundrobin"
            )
            cut, partial = ucut, ucut
        # coordinator-side final stage honors the same session the
        # workers were sent
        self.runner.apply_session()

        # launch one task per worker; the task body carries the
        # SERIALIZED fragment (plan shipping — reference:
        # TaskUpdateRequest.fragment), not SQL to replay
        fragment = plan_serde.dumps(partial)
        qid = uuid.uuid4().hex[:12]
        tasks = []
        for w, uri in enumerate(self.worker_uris):
            payload = {
                "taskId": f"{qid}.{w}",
                "fragment": fragment,
                "splitTable": split_table,
                "splitIndex": w,
                "splitCount": len(self.worker_uris),
                "session": self.session_props,
            }
            if partition_cols is not None:
                payload["splitMode"] = "hash"
                payload["partitionColumns"] = partition_cols
            try:
                self._post_task(uri, payload)
            except (urllib.error.URLError, OSError) as e:
                raise DcnQueryFailed(
                    f"worker {uri}: task submit failed: {e}"
                ) from e
            tasks.append((uri, f"{qid}.{w}"))

        # coordinator-side plan: shipped subtree -> RemoteSource
        state_types = tuple(ex.output_types(partial))
        key = f"dcn-{qid}"
        remote = P.RemoteSource(types=state_types, key=key,
                                origin=partial)
        if partial is cut:  # union cut: consume the union as-is
            coord_plan = _replace_node(plan, cut, remote)
        else:  # aggregation cut: FINAL step over the state pages
            final = dataclasses.replace(cut, step="final",
                                        source=remote)
            coord_plan = _replace_node(plan, cut, final)

        def supplier():
            for uri, task_id in tasks:
                yield from self._fetch_pages(uri, task_id)

        ex.remote_sources[key] = supplier
        try:
            _, rows = ex.execute(coord_plan)
            return rows
        finally:
            ex.remote_sources.pop(key, None)
            # release worker-side page buffers (reference: task expiry)
            for uri, task_id in tasks:
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/task/{task_id}", method="DELETE"
                    )
                    urllib.request.urlopen(req, timeout=5).close()
                except (urllib.error.URLError, OSError):
                    pass  # dead worker: nothing to free
