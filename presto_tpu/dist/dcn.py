"""DCN coordinator: multi-process query execution over localhost (or
any network) workers.

Reference: the coordinator half of distributed execution —
server/remotetask/HttpRemoteTask.java (task create + status),
operator/ExchangeClient.java + HttpPageBufferClient.java (token-acked
page fetch with retries), metadata/DiscoveryNodeManager +
failureDetector/HeartbeatFailureDetector (peer liveness).

TPU-native shape (SURVEY §6.8): ICI-scale parallelism stays INSIDE a
worker process as compiled collectives; this layer is the DCN half —
processes exchange serialized pages over HTTP exactly where the
reference does, at one of two fragment boundaries:

    PARTIAL/FINAL aggregation cut (tiny state pages; preferred):
      worker w: scan(splits w::K of fact table) -> ... -> partial agg
      coordinator: RemoteSource(all workers) -> final agg -> rest
    UNION cut (general row-local subtree; multi-join pipelines with
    no decomposable aggregation):
      worker w: row-local subtree over split share -> result pages
      coordinator: RemoteSource union -> sort/topN/window/agg -> rest

Either way the task body carries the coordinator's SERIALIZED physical
fragment (dist/plan_serde.py — the reference's TaskUpdateRequest
PlanFragment); workers execute exactly that tree, never re-planning.
Scans split round-robin or hash-co-partitioned on join keys (both big
join sides 1/N per worker; hash_fanout_source).

Failure model: FAULT-TOLERANT task retry (reference: Project
Tardigrade's task-level retry, "A Decade of SQL Analytics at Meta"
VLDB 2023), made cheap by deterministic generation — a dead worker's
fragment re-dispatches to a surviving ALIVE worker carrying the SAME
split assignment, the survivor re-generates that split share at the
scan (gen_at/key_inverse SPI + connectors/split_filter.py), and pages
the coordinator already consumed dedupe by fetch token — the new
placement's regenerated prefix is VERIFIED byte-identical (rolling
sha256) before the fetch resumes at the consumed token, so delivery
stays effectively exactly-once and a non-deterministic sequence fails
loudly instead of silently — no spooled shuffle tier required.
Governed by session properties: `task_retry_attempts` re-dispatches per
task (0 pins the classic fail-query-cleanly model), `retry_backoff_ms`
seeds the exponential-backoff-with-jitter ladder between retries, and
`query_max_run_time` is a hard wall-clock deadline enforced in the
fetch loop and at executor page boundaries (QueryDeadlineExceeded).
The heartbeat detector is consulted BEFORE task submit so FAILED nodes
are never picked, and every recovery action is observable: the
coordinator executor's `task_retries` / `workers_excluded` counters
(EXPLAIN ANALYZE, /metrics, system.metrics) and `TaskRetryEvent` on
the EventListener SPI.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random
import time
import urllib.error
import uuid
from typing import Dict, List, Optional

from presto_tpu.dist import connpool as CONNPOOL
from presto_tpu.dist import plan_serde, serde
from presto_tpu.dist import spool as SPOOL
from presto_tpu.exec import faults as FAULTS
from presto_tpu.exec import plan as P
from presto_tpu.exec.executor import QueryDeadlineExceeded
from presto_tpu.server.heartbeat import HeartbeatFailureDetector
from presto_tpu.server.worker import (
    fanout_safe,
    find_partial_cut,
    find_union_cut,
    hash_fanout_plan,
    hash_fanout_source,
    largest_table,
)


class DcnQueryFailed(RuntimeError):
    """Query-level failure: task retries exhausted / no survivors (or,
    with task_retry_attempts=0, the classic fail-query-and-let-the-
    client-retry model with no task-level recovery)."""


class _TaskLost(RuntimeError):
    """Internal: one task placement is gone (submit failure, exhausted
    fetch retries, or — with task_error=True — a deterministic
    worker-side task failure) — the recovery path decides whether to
    re-dispatch or fail the query."""

    def __init__(self, msg: str, task_error: bool = False):
        super().__init__(msg)
        # True when the TASK failed on a healthy worker (the fragment
        # raised; X-Task-Error from the results endpoint): re-dispatch
        # is still attempted (the fault may be environmental) but the
        # node is NOT excluded — workers_excluded counts node loss only
        self.task_error = task_error


@dataclasses.dataclass
class _TaskState:
    """One logical task (= one split share of the fragment) and its
    current placement. `next_token` is the count of pages the
    coordinator has consumed and `hasher` a rolling sha256 of their
    serialized bytes — a re-dispatched task resumes fetching at
    next_token AFTER the new placement's regenerated prefix is verified
    byte-identical to what was consumed (deterministic generation makes
    that the common case; a mismatch — e.g. the survivor's device-OOM
    ladder re-chunked its page boundaries — fails the query loudly
    instead of silently skipping/duplicating rows)."""

    uri: str
    task_id: str
    payload: Dict
    next_token: int = 0
    retries_used: int = 0
    trace_t0: float = 0.0  # dispatch instant on the trace clock
    hasher: "hashlib._Hash" = dataclasses.field(
        default_factory=lambda: hashlib.sha256())


def _replace_node(root, target, repl):
    """Structural replace of one subtree in a frozen plan tree."""
    if root is target:
        return repl
    changes = {}
    for f in dataclasses.fields(root):
        v = getattr(root, f.name)
        if isinstance(v, P.PhysicalNode):
            nv = _replace_node(v, target, repl)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and isinstance(
            v[0], P.PhysicalNode
        ):
            nv = tuple(_replace_node(x, target, repl) for x in v)
            if any(a is not b for a, b in zip(nv, v)):
                changes[f.name] = nv
    return dataclasses.replace(root, **changes) if changes else root


class DcnRunner:
    """Coordinator over N worker processes (single fat workers each).

    execute(sql) returns (names, rows) like LocalRunner.execute's
    underlying executor, with the heavy PARTIAL pipeline fanned out.
    """

    def __init__(self, catalogs, worker_uris: List[str], *,
                 default_catalog: Optional[str] = None,
                 page_rows: int = 1 << 16,
                 fetch_retries: int = 3,
                 session_props: Optional[Dict] = None,
                 partition_threshold: int = 1 << 17,
                 listeners=()):
        from presto_tpu.runner import LocalRunner
        from presto_tpu.session import Session

        self.worker_uris = list(worker_uris)
        self.fetch_retries = fetch_retries
        self.partition_threshold = partition_threshold
        # introspection: distribution used by the last execute()
        # ("hash" partitioned join | "roundrobin" | "local")
        self.last_distribution = "local"
        # workers the last execute() actually submitted to (the
        # heartbeat-gated pool; FAILED nodes are never picked)
        self.last_pool: List[str] = []
        # stage-DAG introspection: the last StageScheduler (per-stage
        # pools, task placements) and an optional test/chaos hook
        # called after each completed stage (deterministic mid-query
        # fault injection)
        self.last_scheduler = None
        self._stage_hook = None
        # coordinator HA (ISSUE 20): the active query's checkpoint
        # handle (dist/checkpoint.QueryCheckpoint) — the stage
        # scheduler journals placements/root/drain through it; None =
        # checkpointing off
        self.checkpoint_handle = None
        # output column names of the last execute() (every path —
        # DAG, legacy cuts, local fallback): the serving layer needs
        # them for the protocol's columns block
        self.last_output_names: Optional[List[str]] = None
        self.session_props = dict(session_props or {})
        self.listeners = list(listeners)
        # fault-tolerance bookkeeping: nodes excluded after a mid-query
        # failure (re-admitted only on a fresh successful ping — a
        # rebooted worker on the same uri rejoins between queries, the
        # reference's node-rejoin model)
        self._excluded: set = set()
        self._rng = random.Random()
        cat = default_catalog or next(iter(catalogs))
        self.runner = LocalRunner(
            catalogs,
            page_rows=page_rows,
            default_catalog=cat,
            session=Session(catalog=cat,
                            properties=self.session_props),
        )
        self.heartbeat = HeartbeatFailureDetector(
            [f"{u}" for u in self.worker_uris]
        )
        # fleet-cache index (ISSUE 19): per-worker bloom summaries of
        # cached fragment keys, refreshed by every heartbeat ping —
        # the scheduler's pre-dispatch probe consults it so the
        # common cache miss never touches the wire
        from presto_tpu.dist.cacheprobe import RemoteCacheIndex

        self.cache_index = RemoteCacheIndex()
        self.heartbeat.on_info = self.cache_index.update_from_info
        # background detector: dead-node connect timeouts are paid on
        # the daemon thread, never on the query path (the submit gate
        # reads CACHED state; reference: NodeScheduler consulting an
        # async failure detector)
        self.heartbeat.start()
        # per-node rate limit for synchronous re-admission probes of
        # excluded nodes (a still-dead node costs its connect timeout
        # at most once per heartbeat interval, not per query)
        self._probe_at: Dict[str, float] = {}

    def close(self) -> None:
        """Stop the background heartbeat thread. DcnRunner owns it, so
        long-lived embedders (and the chaos harness) can shut it down
        instead of leaking a pinging daemon per runner."""
        self.heartbeat.stop()

    @property
    def release_skips(self) -> int:
        """DELETE-release skips on dead workers. ONE owner — the
        executor's registry counter (exec/counters.py), which
        /metrics, system.metrics, and EXPLAIN ANALYZE render — so the
        chaos harness and the fleet surfaces can never drift apart."""
        return self.runner.executor.release_skips

    # ------------------------------------------------- session-prop knobs
    def _retry_attempts(self) -> int:
        return int(self.runner.session.get("task_retry_attempts"))

    def _backoff_ms(self) -> int:
        return int(self.runner.session.get("retry_backoff_ms"))

    # --------------------------------------------------------- protocol
    def _task_spans(self, st: _TaskState) -> List[Dict]:
        """One best-effort status poll for a task's worker-side spans
        (queue/run/attempt, shipped on the status plane). Transport
        errors return [] — the timeline loses the worker detail, the
        query loses nothing."""
        try:
            with CONNPOOL.request(
                f"{st.uri}/v1/task/{st.task_id}", timeout=5
            ) as r:
                return json.loads(r.read().decode()).get("spans") or []
        except (urllib.error.URLError, ConnectionError, OSError,
                ValueError):
            return []

    def _post_task(self, uri: str, payload: Dict) -> Dict:
        # connpool never replays a POST on a reused socket — a task
        # submit must reach the worker at most once per attempt
        with CONNPOOL.request(
            f"{uri}/v1/task",
            method="POST",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            timeout=30,
        ) as resp:
            return json.loads(resp.read().decode())

    def _probe_cached_task(self, partial, split_table: str,
                           index: int, count: int, task_id: str,
                           pool) -> Optional[str]:
        """Fleet cache probe for the classic dispatch path (ISSUE
        19): ask bloom-positive pool members to serve this split
        share's fragment from their result cache. Returns the uri
        that parked the pages as pre-finished task ``task_id`` (the
        ordinary spool-fetch plane reads them), or None — every
        failure here is advisory and reads as a miss. Round-robin
        splits only: the hash split mode wraps connectors differently
        on the worker, so its keys are not what this mirror computes."""
        from presto_tpu.dist.cacheprobe import fragment_cache_key

        ex = self.runner.executor
        timeout = self._probe_budget(ex)
        if timeout is None:
            return None
        try:
            key = fragment_cache_key(
                partial, self.runner.catalogs,
                split_table=split_table, split_index=index,
                split_count=count, collect_k=ex.collect_k,
                page_rows=ex.page_rows,
            )
        except Exception:  # noqa: BLE001 - advisory probe
            return None
        if key is None:
            return None
        idx = self.cache_index
        for uri in pool:
            if uri in self._excluded or \
                    not idx.might_contain(uri, key):
                continue
            try:
                with CONNPOOL.request(
                    f"{uri}/v1/cache/task",
                    method="POST",
                    data=json.dumps(
                        {"taskId": task_id, "key": key}).encode(),
                    headers={"Content-Type": "application/json"},
                    timeout=timeout,
                ) as r:
                    out = json.loads(r.read().decode())
            except (urllib.error.URLError, ConnectionError,
                    OSError, ValueError):
                continue  # bloom false positive / slow peer: dispatch
            if out.get("hit"):
                return uri
        return None

    @staticmethod
    def _raise_if_task_error(e: BaseException, uri: str,
                             task_id: str) -> None:
        """X-Task-Error on a results response marks a DETERMINISTIC
        task failure on a healthy worker: surface the real error text
        at once instead of spinning fetch retries against a dead
        task."""
        if isinstance(e, urllib.error.HTTPError) and \
                e.headers.get("X-Task-Error"):
            try:
                msg = json.loads(e.read().decode()).get("error", "")
            except (ValueError, OSError):
                msg = ""
            # classify with the SHARED marker list (exec/faults.py):
            # a worker-side device-memory fault is environmental — the
            # retry message says so, and the coordinator's own OOM
            # ladder stays out of it (is_device_fault's exact-type
            # check rejects _TaskLost even though it quotes the text)
            note = (" [worker device-memory fault]"
                    if FAULTS.text_matches(msg) else "")
            raise _TaskLost(
                f"task {task_id} FAILED on worker {uri}: "
                f"{msg or e}{note}",
                task_error=True,
            ) from e

    @staticmethod
    def _check_deadline(deadline: Optional[float]) -> None:
        if deadline is not None and time.monotonic() > deadline:
            raise QueryDeadlineExceeded(
                "query exceeded query_max_run_time in the DCN fetch "
                "loop"
            )

    def _sleep_backoff(self, attempt: int,
                       deadline: Optional[float]) -> None:
        """Exponential backoff with jitter between retries (reference:
        HttpPageBufferClient's backoff; jitter de-synchronizes N
        coordinators hammering one recovering worker). Never sleeps
        past the query deadline."""
        base = self._backoff_ms() / 1000.0
        delay = min(base * (2 ** max(attempt - 1, 0)), 5.0)
        delay *= 0.5 + self._rng.random()  # jitter: [0.5x, 1.5x)
        if deadline is not None:
            delay = min(delay, max(deadline - time.monotonic(), 0.0))
        if delay > 0:
            time.sleep(delay)

    def _probe_budget(self, ex) -> Optional[float]:
        """Deadline-aware retry budget for the remote-cache probe
        plane (ISSUE 20 satellite): a probe against a dying holder
        must not burn wall clock the query doesn't have. Returns the
        probe timeout — capped at a fraction of the remaining
        query_max_run_time — or None (counted) when the deadline
        can't afford one; the caller falls back to normal dispatch."""
        deadline = ex.query_deadline
        if deadline is None:
            return 5.0
        remaining = deadline - time.monotonic()
        if remaining < 2.0:
            ex.probe_deadline_skips += 1
            return None
        return min(5.0, 0.25 * remaining)

    @staticmethod
    def _deadline_timeout(deadline: Optional[float],
                          cap: float = 60.0) -> float:
        """Per-request timeout bounded by the query's remaining
        deadline (ISSUE 20 satellite: a fetch against a dying worker
        must not block past query_max_run_time — the deadline check on
        the next loop iteration then fails the query on time)."""
        if deadline is None:
            return cap
        return max(1.0, min(cap, deadline - time.monotonic()))

    def _fetch_pages(self, st: _TaskState,
                     deadline: Optional[float]):
        """Token-acked page fetch with bounded, backed-off retries (the
        HttpPageBufferClient protocol: at-least-once + dedupe by
        token). Starts at st.next_token — a re-dispatched task resumes
        where the dead worker left off. Raises _TaskLost when this
        placement is unreachable; the caller decides recovery. A
        corrupt frame (PageWireError — bit rot or a fault-injected
        flip on the wire) retries the SAME token bounded times (the
        token only advances on a decoded frame), then surfaces as
        _TaskLost so the replay ladder re-pulls from a survivor — the
        PR-16 loud-fail contract: never garbage rows."""
        while True:
            attempt = 0
            while True:
                self._check_deadline(deadline)
                try:
                    # no ?part: the coordinator drains gather edges
                    # only (partition 0 / legacy byte buffers) —
                    # worker-to-worker partition fetches live in
                    # dist/spool.fetch_spool_blobs. ?max streams up
                    # to a bounded window of page frames per request
                    # (pooled keep-alive connection), decoded
                    # incrementally: the token, hasher, and yield
                    # advance one FRAME at a time, so a mid-stream
                    # transport failure resumes at the first
                    # unconsumed page with the replay hash intact.
                    with CONNPOOL.request(
                        f"{st.uri}/v1/task/{st.task_id}/results/"
                        f"{st.next_token}"
                        f"?max={SPOOL.FETCH_WINDOW_BYTES}",
                        timeout=self._deadline_timeout(deadline),
                    ) as r:
                        if r.status == 204:
                            if r.headers.get("X-Done") == "1":
                                return
                            break  # long-poll timeout; re-ask
                        for body in SPOOL.iter_response_frames(r):
                            page = serde.deserialize_page(body)
                            st.hasher.update(body)
                            st.next_token += 1
                            yield page
                        break
                except serde.PageWireError as e:
                    # decode failed BEFORE the token advanced: the
                    # re-request resumes at the first unconsumed page
                    attempt += 1
                    if attempt > self.fetch_retries:
                        raise _TaskLost(
                            f"worker {st.uri} task {st.task_id}: "
                            f"corrupt page frame at token "
                            f"{st.next_token} after "
                            f"{self.fetch_retries} retries: {e}"
                        ) from e
                    self._sleep_backoff(attempt, deadline)
                except (urllib.error.URLError, urllib.error.HTTPError,
                        ConnectionError, OSError) as e:
                    self._raise_if_task_error(e, st.uri, st.task_id)
                    attempt += 1
                    if attempt > self.fetch_retries:
                        raise _TaskLost(
                            f"worker {st.uri} task {st.task_id}: page "
                            f"fetch failed after {self.fetch_retries} "
                            f"retries: {e}"
                        ) from e
                    self._sleep_backoff(attempt, deadline)

    def _prefix_matches(self, uri: str, task_id: str, st: _TaskState,
                        deadline: Optional[float]) -> bool:
        """Verify a re-dispatched task regenerated the already-consumed
        page prefix byte-for-byte before resuming at st.next_token —
        dedupe-by-token is only sound for identical sequences.
        Deterministic generation makes a match the common case; re-
        fetching the prefix is cheap (workers buffer the full page
        list). Raises _TaskLost(task_error=True) if the new task
        failed; lets transport errors through after bounded retries so
        the recovery loop excludes this placement too."""
        h = hashlib.sha256()
        token = 0
        attempt = 0
        while token < st.next_token:
            self._check_deadline(deadline)
            try:
                with CONNPOOL.request(
                    f"{uri}/v1/task/{task_id}/results/{token}"
                    f"?max={SPOOL.FETCH_WINDOW_BYTES}", timeout=60,
                ) as r:
                    if r.status == 204:
                        if r.headers.get("X-Done") == "1":
                            return False  # fewer pages than consumed
                        continue  # long-poll timeout; re-ask
                    for body in SPOOL.iter_response_frames(r):
                        h.update(body)
                        token += 1
                        if token >= st.next_token:
                            # frames past the consumed prefix are NOT
                            # part of the hash; the response close
                            # discards the remainder
                            break
                    attempt = 0
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                self._raise_if_task_error(e, uri, task_id)
                attempt += 1
                if attempt > self.fetch_retries:
                    raise
                self._sleep_backoff(attempt, deadline)
        return h.hexdigest() == st.hasher.hexdigest()

    def _release_task(self, uri: str, task_id: str) -> None:
        """DELETE one worker task's buffers/spools (reference: task
        expiry). Scoped to transport errors ONLY — a programming error
        in the release path must surface, not vanish; dead-worker
        skips are counted, not swallowed silently — on the executor's
        registry counter (exec/counters.py), the one copy every
        surface (EXPLAIN ANALYZE, /metrics, system.metrics,
        analyze_rung, DcnRunner.release_skips) reads. THE one release
        site for both the legacy cuts and the stage-DAG scheduler."""
        try:
            with CONNPOOL.request(
                f"{uri}/v1/task/{task_id}", method="DELETE", timeout=5
            ) as r:
                r.read()
        except (urllib.error.URLError, OSError, TimeoutError):
            self.runner.executor.release_skips += 1

    # ------------------------------------------------------- fault model
    def _exclude(self, uri: str) -> None:
        if uri not in self._excluded:
            self._excluded.add(uri)
            self.runner.executor.workers_excluded += 1

    def _alive_for_submit(self) -> List[str]:
        """The heartbeat-gated worker pool for this query: nodes the
        detector marks FAILED are never picked (reference:
        NodeScheduler consulting the failure detector). State is read
        from the BACKGROUND detector's cache — no pings on the query
        path except re-admission probes of excluded nodes (a fresh
        successful, recorded probe lets a worker rebooted on the same
        uri rejoin between queries), and those are rate-limited per
        node so a still-dead node costs its connect timeout at most
        once per heartbeat interval. A node that died since the last
        heartbeat tick is caught by the submit-failure recovery path."""
        pool = []
        now = time.monotonic()
        for u in self.worker_uris:
            if u in self._excluded:
                # excluded nodes are probed REGARDLESS of cached state:
                # a worker killed mid-query is usually FAILED in the
                # cache too, and a reboot on the same uri must be able
                # to rejoin before the background loop's next tick
                last = self._probe_at.get(u)
                if last is not None and \
                        now - last < self.heartbeat.interval_s:
                    continue  # probed recently and still excluded
                self._probe_at[u] = now
                if not self.heartbeat.probe(u):
                    continue
                self._excluded.discard(u)
                self._probe_at.pop(u, None)
            elif not self.heartbeat.is_alive(u):
                continue
            pool.append(u)
        return pool

    def _recover_task(self, st: _TaskState, pool: List[str],
                      retry_attempts: int, deadline: Optional[float],
                      cause: BaseException) -> None:
        """Re-dispatch one lost task to a surviving ALIVE worker: same
        fragment, same split assignment (splitIndex/splitCount), new
        taskId — the survivor re-generates the split share
        deterministically at the scan, and the fetch loop resumes at
        st.next_token so already-consumed pages dedupe by token.
        Raises DcnQueryFailed when retries are exhausted (or pinned
        off) or no survivors remain."""
        from presto_tpu import events as E

        if not getattr(cause, "task_error", False):
            # node loss (unreachable / dead). A DETERMINISTIC task
            # failure on a healthy worker is NOT excluded — the node is
            # fine, the fragment raised; re-dispatch is still tried in
            # case the fault was environmental (e.g. device pressure)
            self._exclude(st.uri)
        while True:
            if st.retries_used >= retry_attempts:
                raise DcnQueryFailed(
                    f"worker {st.uri} task {st.task_id}: {cause} "
                    f"(task retries exhausted: "
                    f"task_retry_attempts={retry_attempts})"
                ) from cause
            # prefer a DIFFERENT worker: the failed placement's node
            # sorts last (it stays in the pool only for task_error)
            survivors = sorted(
                (u for u in pool if u not in self._excluded),
                key=lambda u: u == st.uri)
            if not survivors:
                raise DcnQueryFailed(
                    f"task {st.task_id}: no surviving workers to "
                    f"re-dispatch to (pool {pool}, all excluded)"
                ) from cause
            st.retries_used += 1
            self._sleep_backoff(st.retries_used, deadline)
            self._check_deadline(deadline)
            target = survivors[(st.retries_used - 1) % len(survivors)]
            base_id = st.payload["taskId"].split(".r", 1)[0]
            new_id = f"{base_id}.r{st.retries_used}"
            payload = dict(st.payload, taskId=new_id)
            from_uri = st.uri
            try:
                self._post_task(target, payload)
                prefix_ok = (st.next_token == 0 or self._prefix_matches(
                    target, new_id, st, deadline))
            except (urllib.error.URLError, OSError) as e:
                # the survivor failed too: exclude it and keep going
                # (each failed placement consumes one retry)
                self._exclude(target)
                st.uri, cause = target, e
                continue
            except _TaskLost as e:
                # the re-dispatched task itself failed deterministically
                # during prefix verification
                st.uri, cause = target, e
                continue
            if not prefix_ok:
                raise DcnQueryFailed(
                    f"task {new_id}: the re-dispatched placement "
                    f"regenerated a DIFFERENT page sequence for the "
                    f"already-consumed prefix ({st.next_token} pages) "
                    f"— non-deterministic task output (e.g. the "
                    f"survivor's device-OOM ladder re-chunked page "
                    f"boundaries); failing loudly instead of silently "
                    f"skipping or duplicating rows"
                ) from cause
            st.uri, st.task_id, st.payload = target, new_id, payload
            self.runner.executor.task_retries += 1
            tr = self.runner.executor.trace
            if tr is not None:
                # recovery annotation on the query timeline
                tr.complete("retry", new_id, tr.now(), tr.now(),
                            to=target, attempt=st.retries_used,
                            cause=str(cause)[:120])
                self.runner.executor.trace_spans += 1
            E.dispatch(
                self.listeners, "task_retried", E.TaskRetryEvent(
                    query_id=base_id.split(".", 1)[0],
                    task_id=new_id, from_uri=from_uri, to_uri=target,
                    attempt=st.retries_used, cause=str(cause)[:400],
                ),
                on_error=self.runner.executor.count_listener_error,
            )
            return

    # ----------------------------------------------------- stage DAG
    def _try_stage_dag(self, plan):
        """Fragment the plan into a general stage DAG (ANY shape, not
        just the three special-cased cuts). Returns a StageDag or None
        when the plan is not worth/safe to DAG-distribute."""
        from presto_tpu.dist.fragmenter import fragment_dag

        return fragment_dag(
            self.runner.executor, plan, self.runner.catalogs,
            **self.runner._session_dist_options(),
        )

    def _execute_dag(self, dag):
        """Run a fragmented DAG through the general stage scheduler
        (dist/scheduler.py): spooled exchanges, non-leaf replay,
        straggler speculation, per-stage pool recomputation."""
        import uuid as _uuid

        from presto_tpu import obs as OBS
        from presto_tpu.dist.scheduler import StageScheduler

        self.last_distribution = "stage-dag"
        qid = _uuid.uuid4().hex[:12]
        # lifecycle tracing: attach BEFORE constructing the scheduler
        # (it snapshots ex.trace); the coordinator's root-fragment
        # execute() records its attempt/operator spans into the same
        # trace, so one timeline covers stages + final drain
        trace = OBS.maybe_trace(self.runner.session, query_id=qid)
        if trace is not None:
            OBS.attach(self.runner.executor, trace)
        sched = StageScheduler(self, dag, qid,
                               stage_hook=self._stage_hook)
        self.last_scheduler = sched
        try:
            rows = sched.run()
            self.last_output_names = getattr(sched, "root_names",
                                             None)
            return rows
        finally:
            if trace is not None:
                OBS.finalize(self.runner.executor, trace,
                             self.runner.session.get("query_trace_dir"))
            self.runner.last_trace = trace

    # ---------------------------------------------------------- execute
    def execute(self, sql: str):
        plan = self.runner.plan(sql)
        ex = self.runner.executor
        retry_attempts = self._retry_attempts()
        # general stage-DAG scheduling (ISSUE 7): "true" forces the
        # DAG scheduler for every distributable plan; "auto" keeps the
        # tuned legacy shapes first and engages the DAG only where
        # they would fall back to a single process (closing ROADMAP
        # item 1's "everything else runs on one worker" gap)
        stage_mode = self.runner.session.get("stage_scheduler")
        if stage_mode == "true":
            dag = self._try_stage_dag(plan)
            if dag is not None:
                self.runner.apply_session()
                return self._execute_dag(dag)
        cut = find_partial_cut(plan)
        partial = coord_plan = partition_cols = split_table = None
        if cut is not None:
            # best shape: PARTIAL/FINAL aggregation split — workers
            # ship tiny accumulator-state pages. PARTITIONED JOIN
            # first (the hash-repartition exchange: both big join
            # sides co-partitioned by key hash, build state 1/N per
            # worker); round-robin split-table fan-out (replicated
            # builds) is the fallback shape
            partition_cols = hash_fanout_plan(
                cut, self.runner.catalogs,
                partition_threshold=self.partition_threshold,
            )
            split_table = largest_table(cut.source,
                                        self.runner.catalogs)
            if partition_cols is not None or (
                split_table is not None
                and fanout_safe(cut, split_table)
            ):
                self.last_distribution = (
                    "hash" if partition_cols is not None
                    else "roundrobin"
                )
                partial = dataclasses.replace(cut, step="partial")
        if partial is None:
            # general shape: UNION CUT — workers execute the topmost
            # row-local subtree (multi-join pipelines, no aggregation
            # required) over their split share; the coordinator unions
            # the pages and runs everything above (sort/topN/window/
            # non-decomposable aggregation). Reference: a leaf-stage
            # fragment under a GATHER exchange.
            split_table = largest_table(plan, self.runner.catalogs)
            ucut = (find_union_cut(plan, split_table)
                    if split_table is not None else None)
            if ucut is None:
                if stage_mode == "auto":
                    # the legacy shapes don't apply — exactly the gap
                    # the general stage-DAG scheduler exists to close.
                    # Auto mode preserves the pre-DAG contract for a
                    # dead pool: such queries used to run locally, so
                    # with no ALIVE workers we still fall back local
                    # instead of failing (forced mode fails loudly,
                    # like any distributable shape with no workers)
                    dag = self._try_stage_dag(plan)
                    if dag is not None and (
                        self._alive_for_submit()
                        if retry_attempts > 0 else self.worker_uris
                    ):
                        self.runner.apply_session()
                        return self._execute_dag(dag)
                # nothing distributable: run locally rather than wrong
                # (no pool computed — local queries never pay dead-node
                # probe timeouts)
                self.last_distribution = "local"
                self.last_pool = []
                res = self.runner.execute(sql)
                self.last_output_names = list(res.column_names)
                return res.rows
            partition_cols = hash_fanout_source(
                ucut, self.runner.catalogs,
                partition_threshold=self.partition_threshold,
            )
            self.last_distribution = (
                "union-hash" if partition_cols is not None
                else "union-roundrobin"
            )
            cut, partial = ucut, ucut
        # coordinator-side final stage honors the same session the
        # workers were sent (also anchors ex.query_deadline from
        # query_max_run_time — query start for deadline purposes)
        self.runner.apply_session()
        deadline = ex.query_deadline

        # the plan IS distributable from here on — now workers are
        # mandatory. task_retry_attempts=0 pins the classic model end
        # to end: all configured workers are picked (no heartbeat
        # gate), the first submit/fetch failure fails the QUERY cleanly
        pool = (self._alive_for_submit() if retry_attempts > 0
                else list(self.worker_uris))
        self.last_pool = list(pool)
        if not pool:
            raise DcnQueryFailed(
                f"no ALIVE workers among {self.worker_uris}"
            )
        # launch one task per pooled worker; the task body carries the
        # SERIALIZED fragment (plan shipping — reference:
        # TaskUpdateRequest.fragment), not SQL to replay
        from presto_tpu import obs as OBS

        fragment = plan_serde.dumps(partial)
        qid = uuid.uuid4().hex[:12]
        # lifecycle tracing for the legacy cuts: one trace covering
        # dispatch, the token-acked fetches, recovery annotations, and
        # the coordinator-side final stage's attempt/operator spans
        trace = OBS.maybe_trace(self.runner.session, query_id=qid,
                                sql=sql)
        if trace is not None:
            OBS.attach(ex, trace)
        tasks: List[_TaskState] = []
        key = f"dcn-{qid}"
        check_payloads = ex._plan_check_on()
        # fleet cache probe (ISSUE 19), classic-path edition: gated
        # so the common miss is free (bloom summaries answer
        # "definitely not cached" locally). Round-robin splits only —
        # the hash split mode's worker-side wrap computes other keys.
        sess = self.runner.session
        probe_on = (
            partition_cols is None and split_table is not None
            and self.cache_index.known()
            and bool(sess.get("result_cache_enabled"))
            and bool(sess.get("result_cache_remote_probe"))
        )
        try:
            for w, uri in enumerate(pool):
                payload = {
                    "taskId": f"{qid}.{w}",
                    "fragment": fragment,
                    "splitTable": split_table,
                    "splitIndex": w,
                    "splitCount": len(pool),
                    "session": self.session_props,
                }
                if trace is not None:
                    payload["trace"] = True
                if partition_cols is not None:
                    payload["splitMode"] = "hash"
                    payload["partitionColumns"] = partition_cols
                if check_payloads:
                    # deterministic-split invariant (exec/plan_check.py):
                    # the PR-5 retry path re-generates EXACTLY this
                    # (splitIndex, splitCount) share on a survivor — a
                    # payload without it could not be re-dispatched.
                    # Same auto gate as the executor's plan verifier.
                    from presto_tpu.exec import plan_check as PC

                    PC.check_task_payload(payload)
                st = _TaskState(uri=uri, task_id=payload["taskId"],
                                payload=payload)
                d0 = trace.now() if trace is not None else 0.0
                hit_uri = (self._probe_cached_task(
                    partial, split_table, w, len(pool),
                    payload["taskId"], pool) if probe_on else None)
                if hit_uri is not None:
                    # some fleet member already holds this split
                    # share's pages — no dispatch; the supplier
                    # fetches the parked pre-finished task over the
                    # ordinary pooled plane (and a mid-fetch loss
                    # still recovers: the payload carries the full
                    # fragment for re-dispatch on a survivor)
                    st.uri = hit_uri
                    ex.cache_remote_hits += 1
                    if trace is not None:
                        now = trace.now()
                        trace.complete("cache",
                                       f"remote-hit:{st.task_id}",
                                       now, now, uri=hit_uri)
                        ex.trace_spans += 1
                else:
                    try:
                        self._post_task(uri, payload)
                    except (urllib.error.URLError, OSError) as e:
                        if retry_attempts <= 0:
                            raise DcnQueryFailed(
                                f"worker {uri}: task submit failed: "
                                f"{e}"
                            ) from e
                        # submit retry: re-dispatch this split share
                        # to a different ALIVE worker (it runs two
                        # tasks)
                        self._recover_task(st, pool, retry_attempts,
                                           deadline, e)
                if trace is not None:
                    st.trace_t0 = d0
                    trace.complete("dispatch", st.task_id, d0,
                                   trace.now(), uri=st.uri)
                    ex.trace_spans += 1
                tasks.append(st)

            # coordinator-side plan: shipped subtree -> RemoteSource
            state_types = tuple(ex.output_types(partial))
            remote = P.RemoteSource(types=state_types, key=key,
                                    origin=partial)
            if partial is cut:  # union cut: consume the union as-is
                coord_plan = _replace_node(plan, cut, remote)
            else:  # aggregation cut: FINAL step over the state pages
                final = dataclasses.replace(cut, step="final",
                                            source=remote)
                coord_plan = _replace_node(plan, cut, final)

            def supplier():
                for st in tasks:
                    # a fresh supplier invocation (coordinator boosted
                    # retry re-pulling the remote source) refetches from
                    # token 0 — workers buffer the full page list; within
                    # ONE invocation next_token advances so a re-dispatched
                    # task resumes at the consumed token (dedupe)
                    st.next_token = 0
                    st.hasher = hashlib.sha256()
                    f0 = trace.now() if trace is not None else 0.0
                    while True:
                        try:
                            yield from self._fetch_pages(st, deadline)
                            break
                        except _TaskLost as e:
                            if retry_attempts <= 0:
                                raise DcnQueryFailed(str(e)) from e
                            # worker death mid-query: exclude the node and
                            # re-run ONLY the lost fragment on a survivor,
                            # resuming the fetch at the consumed token
                            self._recover_task(st, pool, retry_attempts,
                                               deadline, e)
                    if trace is not None:
                        trace.complete("fetch", st.task_id, f0,
                                       trace.now(), uri=st.uri,
                                       pages=st.next_token)
                        ex.trace_spans += 1
                        # one status poll per drained task ingests the
                        # worker's queue/run/attempt spans into the
                        # timeline (the stage-DAG path gets these from
                        # its completion polls; the legacy path must
                        # ask once, or workers record for no reader)
                        ex.trace_spans += trace.ingest(
                            self._task_spans(st), trace.root,
                            st.trace_t0, trace.now())

            ex.remote_sources[key] = supplier
            names, rows = ex.execute(coord_plan)
            self.last_output_names = list(names)
            return rows
        finally:
            ex.remote_sources.pop(key, None)
            # release worker-side page buffers (reference: task
            # expiry) — shared with the stage-DAG scheduler's cleanup
            for st in tasks:
                self._release_task(st.uri, st.task_id)
            if trace is not None:
                OBS.finalize(ex, trace,
                             self.runner.session.get("query_trace_dir"))
            self.runner.last_trace = trace
