"""Page wire format for the DCN (inter-process) boundary.

Reference: presto-main execution/buffer/PagesSerde.java +
SerializedPage (block-encoded pages, LZ4, length-prefixed) fetched by
operator/HttpPageBufferClient.java. The TPU translation keeps raw
arrays on ICI (collectives inside compiled programs, dist/executor.py)
and serializes ONLY at the process boundary, exactly as SURVEY §6.8
prescribes: "the HTTP shapes survive only at the pod boundary".

Format (little-endian, zlib-compressed payload):
    header: JSON {blocks: [{dtype(s), encs, has_nulls, dictionary?,
            type}], capacity} + per-array raw bytes, length-prefixed.
Per-array encodings (the BlockEncoding analog): "raw" ships the full
array; "rle" ships ONE element for a constant run of the page's
capacity (reference: spi/block/RunLengthEncodedBlock — constant
columns, all-false null masks, and all-true validity masks collapse to
one value on the wire). Types are reconstructed by name through
presto_tpu.types; dictionaries ship as JSON value lists (content-equal
on arrival — Dictionary hashes by content).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Iterator, List

import numpy as np

from presto_tpu import types as T
from presto_tpu.exec import xfer as XF
from presto_tpu.page import Block, Dictionary, Page

_MAGIC = b"PTP2"


def _type_to_json(t: T.SqlType):
    return t.display()


def _type_from_json(s: str) -> T.SqlType:
    return T.parse_type(s)


def _arrays_of(block: Block) -> List[np.ndarray]:
    datas = block.data if isinstance(block.data, tuple) else (block.data,)
    return [XF.np_host(d) for d in datas]


def _dic_value_to_json(v):
    """Type-preserving dictionary-value encoding: dictionaries hold
    strings, python ints/floats/bools, bytes (varbinary), None, and
    nested tuples (array/map/row values) — str() would corrupt all but
    the first (reference analog: BlockEncoding serde is typed)."""
    if v is None or isinstance(v, (str, bool)):
        return v
    if isinstance(v, (bytes, bytearray)):
        return {"b": bytes(v).hex()}
    if isinstance(v, (int, float)):
        return {"n": v}
    if isinstance(v, (tuple, list)):
        return {"t": [_dic_value_to_json(x) for x in v]}
    return str(v)


def _dic_value_from_json(v):
    if v is None or isinstance(v, (str, bool)):
        return v
    if isinstance(v, dict):
        if "b" in v:
            return bytes.fromhex(v["b"])
        if "n" in v:
            return v["n"]
        if "t" in v:
            return tuple(_dic_value_from_json(x) for x in v["t"])
    return v


def serialize_page(page: Page) -> bytes:
    """One Page -> bytes (the SerializedPage analog)."""
    header = {"capacity": int(page.capacity), "blocks": []}
    payload = bytearray()

    def put(arr: np.ndarray) -> str:
        arr = np.ascontiguousarray(arr)
        if arr.size > 1 and bool((arr == arr.flat[0]).all()):
            b = arr[:1].tobytes()
            payload.extend(struct.pack("<q", len(b)))
            payload.extend(b)
            return "rle"
        b = arr.tobytes()
        payload.extend(struct.pack("<q", len(b)))
        payload.extend(b)
        return "raw"

    for blk in page.blocks:
        arrays = _arrays_of(blk)
        bh = {
            "type": _type_to_json(blk.type),
            "dtypes": [a.dtype.str for a in arrays],
            "nwords": len(arrays),
            "has_nulls": blk.nulls is not None,
            "dictionary": (
                [_dic_value_to_json(v) for v in blk.dictionary.values]
                if blk.dictionary is not None else None
            ),
        }
        bh["encs"] = [put(a) for a in arrays]
        if blk.nulls is not None:
            bh["nulls_enc"] = put(XF.np_host(blk.nulls))
        header["blocks"].append(bh)
    header["valid_enc"] = put(XF.np_host(page.valid))
    hdr = json.dumps(header).encode()
    body = zlib.compress(bytes(payload), level=1)
    return (_MAGIC + struct.pack("<ii", len(hdr), len(body))
            + hdr + body)


def deserialize_page(buf: bytes) -> Page:
    assert buf[:4] == _MAGIC, "bad page magic"
    hlen, blen = struct.unpack("<ii", buf[4:12])
    header = json.loads(buf[12:12 + hlen].decode())
    payload = zlib.decompress(buf[12 + hlen:12 + hlen + blen])
    pos = 0

    def take(dtype, n, enc="raw"):
        nonlocal pos
        (ln,) = struct.unpack_from("<q", payload, pos)
        pos += 8
        count = 1 if enc == "rle" else n
        arr = np.frombuffer(payload, dtype=dtype, count=count,
                            offset=pos).copy()
        pos += ln
        if enc == "rle":
            arr = np.full((n,), arr[0], dtype=dtype)
        return arr

    cap = header["capacity"]
    blocks = []
    for bh in header["blocks"]:
        arrays = [
            take(np.dtype(d), cap, e)
            for d, e in zip(bh["dtypes"], bh["encs"])
        ]
        nulls = (
            take(np.bool_, cap, bh.get("nulls_enc", "raw"))
            if bh["has_nulls"] else None
        )
        dic = (
            Dictionary([_dic_value_from_json(v)
                        for v in bh["dictionary"]])
            if bh["dictionary"] is not None else None
        )
        data = tuple(arrays) if bh["nwords"] > 1 else arrays[0]
        blocks.append(Block(
            data=data, type=_type_from_json(bh["type"]), nulls=nulls,
            dictionary=dic,
        ))
    valid = take(np.bool_, cap, header.get("valid_enc", "raw"))
    return Page(blocks=tuple(blocks), valid=valid)


def serialize_pages(pages) -> Iterator[bytes]:
    for p in pages:
        yield serialize_page(p)
