"""Page wire format for the DCN (inter-process) boundary.

Reference: presto-main execution/buffer/PagesSerde.java +
SerializedPage (block-encoded pages, per-block encodings +
aircompressor, length-prefixed) fetched by
operator/HttpPageBufferClient.java. The TPU translation keeps raw
arrays on ICI (collectives inside compiled programs, dist/executor.py)
and serializes ONLY at the process boundary, exactly as SURVEY §6.8
prescribes: "the HTTP shapes survive only at the pod boundary".

Wire format v3 (little-endian, per-array codec bytes — ISSUE 16):

    offset 0   b"PTP"      magic
    offset 3   b"3"        version byte (old b"PTP2" blobs carry 0x32
                           here and fail LOUDLY, never misparse)
    offset 4   flags       bit0: header JSON is zlib-compressed
    offset 5   <ii>        header length, payload length
    offset 13  header      JSON {capacity, blocks: [{type, dtypes,
                           nwords, has_nulls, dictionary?}], live?}
    13+hlen    payload     one frame per array, in header order:
                           data words, then nulls (if has_nulls) per
                           block, then the page validity mask

When the header carries "live" < capacity, every frame stores only
the first `live` elements (the prefix through the LAST valid row);
the decoder zero/False-fills the dead tail. Rows past the last valid
row are masked out of every consumer, so their backing values are
wire freight with no information — compacted exchange partitions
with a short live prefix shed most of their bytes here, and the
truncation also removes the live-data -> zero-padding cliff that
would otherwise blow the delta codec's narrow width.

Frame = codec byte | <q> stored length | stored bytes. The codec byte
is `base | 0x80` when the stored bytes are additionally
zlib-compressed (the general compressed fallback). Base codecs (the
BlockEncoding analog):

    0 RAW       full array bytes
    1 RLE       ONE element for a bit-identical constant run
                (reference: spi/block/RunLengthEncodedBlock —
                constant columns, all-false null masks, all-true
                validity masks collapse to one value on the wire;
                constancy is tested on BYTES, so constant-NaN arrays
                collapse and mixed +0.0/-0.0 arrays do not)
    2/3/4 INT8/16/32  narrowest-int downcast of a wider integer
                array whose min/max fit (dictionary code words and
                low-cardinality int64 columns ship 2-8x narrower
                before compression)
    5 BOOLPACK  np.packbits bitmap for boolean arrays (8x)
    6/7/8 DELTA8/16/32  first element full-width + consecutive
                differences downcast to the narrowest signed width
                that fits (differences are taken modulo 2^w, so any
                integer array is representable; the probe only picks
                delta when its stored size beats the plain downcast).
                Scan-ordered key columns (orderkeys, positions)
                delta down to 1 byte/row and then deflate to almost
                nothing — the lever behind the q3-family wire pin.

The codec is chosen per array by a cheap size probe at serialize
time and the choice is DETERMINISTIC, so a replayed or re-fetched
page serializes byte-identically (dist/dcn.py `_prefix_matches`
verifies consumed prefixes by rolling sha256 — the replay contract).
Every frame length is validated against the header's dtype/count on
decode: a truncated or corrupt blob raises PageWireError instead of
np.frombuffer silently reading garbage.

Types are reconstructed by name through presto_tpu.types;
dictionaries ship as JSON value lists (content-equal on arrival —
Dictionary hashes by content).

Wire accounting: serialize_page meters blob bytes (wire) and
pre-codec array bytes (raw) onto module process totals
(`wire_totals()`, overlaid on /metrics + system.metrics like the
exec/xfer.py transfer totals) and onto the thread-bound transfer
sink's registry counters `exchange_wire_bytes`/`exchange_raw_bytes`
(exec/counters.py) when one is installed.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Iterator, List

import numpy as np

from presto_tpu import types as T
from presto_tpu.exec import xfer as XF
from presto_tpu.page import Block, Dictionary, Page

_MAGIC = b"PTP"
_VERSION = b"3"
_FLAG_HDR_ZLIB = 0x01

# base codec bytes (low 7 bits); 0x80 flags a zlib-wrapped frame
_RAW = 0
_RLE = 1
_INT8 = 2
_INT16 = 3
_INT32 = 4
_BOOLPACK = 5
_DELTA8 = 6
_DELTA16 = 7
_DELTA32 = 8
_ZLIB_FLAG = 0x80
_DOWNCAST_SIZE = {_INT8: 1, _INT16: 2, _INT32: 4}
_DELTA_SIZE = {_DELTA8: 1, _DELTA16: 2, _DELTA32: 4}

# the general-fallback compression level. The pre-v3 plane shipped
# whole-payload zlib level 1; per-array framing lets the fallback
# afford a denser level because only incompressible-after-codec
# arrays reach it (ROOFLINE wire-cost table measures both).
_ZLIB_LEVEL = 6
# don't probe zlib below this: the deflate header + probe CPU cannot
# win on tiny frames
_ZLIB_MIN_BYTES = 64

# wire mode: "full" = the v3 per-column codec chooser (default);
# "zlib" = raw/RLE + zlib-only (the pre-ISSUE-16 baseline, kept for
# the measured wire-bytes acceptance pin and A/B grading);
# "raw" = no codecs at all (the uncompressed row-parity reference).
# Mode is process-global: every producer of one exchange must agree,
# and replay determinism holds per mode.
_MODE = "full"

# process-lifetime wire totals (the exec/xfer.py `_totals` pattern:
# monotonically increasing ints, GIL-atomic +=, read by /metrics and
# loadbench for fleet grading where per-query executor gauges from
# worker task threads never surface)
_TOTALS = {"exchange_wire_bytes": 0, "exchange_raw_bytes": 0}


class PageWireError(ValueError):
    """A page blob failed structural validation (bad magic/version,
    truncated frame, length/dtype mismatch, corrupt compressed data).
    Pointed and LOUD — the fetch plane treats it as a poisoned blob,
    never as rows."""


def wire_fingerprint() -> str:
    """Identity of the wire serde FORMAT (magic + version) — the
    persistent result-cache manifest records it so a cache directory
    written by one serde version is dropped loudly, not misdecoded,
    by another (cache/persist.py). Mode is deliberately excluded:
    every mode decodes every mode's frames (the codec byte rides in
    each frame), only the encode choice differs."""
    return (_MAGIC + _VERSION).decode("ascii")


def set_wire_mode(mode: str) -> str:
    """Select the wire codec mode ("full" | "zlib" | "raw"); returns
    the previous mode. Test/bench surface for A/B wire-bytes grading
    — production runs stay on "full"."""
    global _MODE
    if mode not in ("full", "zlib", "raw"):
        raise ValueError(f"unknown wire mode {mode!r}")
    prev, _MODE = _MODE, mode
    return prev


def wire_totals() -> dict:
    """Process-lifetime wire byte totals (serialize side), for the
    /metrics + system.metrics overlay and loadbench deltas."""
    return dict(_TOTALS)


def _count_wire(wire: int, raw: int) -> None:
    _TOTALS["exchange_wire_bytes"] += wire
    _TOTALS["exchange_raw_bytes"] += raw
    sink = XF.current_sink()
    count = getattr(sink, "count_wire", None)
    if count is not None:
        count(wire, raw)


def _type_to_json(t: T.SqlType):
    return t.display()


def _type_from_json(s: str) -> T.SqlType:
    return T.parse_type(s)


def _arrays_of(block: Block) -> List[np.ndarray]:
    datas = block.data if isinstance(block.data, tuple) else (block.data,)
    return [XF.np_host(d) for d in datas]


def _dic_value_to_json(v):
    """Type-preserving dictionary-value encoding: dictionaries hold
    strings, python ints/floats/bools, bytes (varbinary), None, and
    nested tuples (array/map/row values) — str() would corrupt all but
    the first (reference analog: BlockEncoding serde is typed)."""
    if v is None or isinstance(v, (str, bool)):
        return v
    if isinstance(v, (bytes, bytearray)):
        return {"b": bytes(v).hex()}
    if isinstance(v, (int, float)):
        return {"n": v}
    if isinstance(v, (tuple, list)):
        return {"t": [_dic_value_to_json(x) for x in v]}
    return str(v)


def _dic_value_from_json(v):
    if v is None or isinstance(v, (str, bool)):
        return v
    if isinstance(v, dict):
        if "b" in v:
            return bytes.fromhex(v["b"])
        if "n" in v:
            return v["n"]
        if "t" in v:
            return tuple(_dic_value_from_json(x) for x in v["t"])
    return v


# ------------------------------------------------------------ encode
def _is_constant(arr: np.ndarray) -> bool:
    """Bit-identical constant run? Tested on BYTES, not values: NaN
    compares unequal to itself under `==` (the pre-v3 RLE detector
    never collapsed constant-NaN float columns) while -0.0 compares
    EQUAL to +0.0 (value-equality would corrupt the sign bit on the
    wire). A first/last element precheck short-circuits the O(n)
    scan for the common non-constant case."""
    if arr.size <= 1:
        return False
    first = arr[:1].tobytes()
    if arr[-1:].tobytes() != first:
        return False
    return arr.tobytes() == first * arr.size


def _downcast(arr: np.ndarray):
    """Narrowest-int downcast probe: (codec, narrow_array) when the
    array's min/max fit a strictly narrower integer width, else
    None. min/max is the cheap O(n) size probe; the choice is a pure
    function of the data, so re-serialization is byte-stable."""
    kind = arr.dtype.kind
    if kind not in "iu" or arr.dtype.itemsize <= 1 or arr.size == 0:
        return None
    lo = int(arr.min())
    hi = int(arr.max())
    for codec in (_INT8, _INT16, _INT32):
        size = _DOWNCAST_SIZE[codec]
        if size >= arr.dtype.itemsize:
            return None
        info = np.iinfo(f"{kind}{size}")
        if info.min <= lo and hi <= info.max:
            return codec, arr.astype(f"<{kind}{size}")
    return None


def _delta(arr: np.ndarray):
    """Delta-encode probe: (codec, narrow_diff_array) when the
    consecutive differences (taken modulo 2^width, so ANY integer
    array is representable without overflow) fit a strictly narrower
    signed width, else None. Sorted or clustered key columns have
    tiny deltas even when their values need the full width. Like
    _downcast, a pure function of the data — byte-stable."""
    if arr.dtype.kind not in "iu" or arr.dtype.itemsize <= 1 or arr.size < 2:
        return None
    w = arr.dtype.itemsize
    # unsigned view -> wraparound subtract -> reinterpret signed:
    # the modular delta, exact for any input including i64 min->max
    ud = np.diff(arr.view(f"<u{w}"))
    sd = ud.view(f"<i{w}")
    lo = int(sd.min())
    hi = int(sd.max())
    for codec in (_DELTA8, _DELTA16, _DELTA32):
        size = _DELTA_SIZE[codec]
        if size >= w:
            return None
        info = np.iinfo(f"i{size}")
        if info.min <= lo and hi <= info.max:
            return codec, sd.astype(f"<i{size}")
    return None


def _encode_array(arr: np.ndarray, out: bytearray) -> int:
    """Append one frame (codec byte | <q len> | bytes) for `arr`;
    returns the array's raw byte size for wire accounting."""
    arr = np.ascontiguousarray(arr)
    raw = arr.tobytes()
    if _MODE == "raw":
        out.append(_RAW)
        out.extend(struct.pack("<q", len(raw)))
        out.extend(raw)
        return len(raw)

    if _is_constant(arr):
        one = raw[: arr.dtype.itemsize]
        out.append(_RLE)
        out.extend(struct.pack("<q", len(one)))
        out.extend(one)
        return len(raw)

    codec, base = _RAW, raw
    if _MODE == "full":
        if arr.dtype.kind == "b":
            packed = np.packbits(arr.view(np.uint8)).tobytes()
            if len(packed) < len(raw):
                codec, base = _BOOLPACK, packed
        else:
            # size-probe the integer codecs; smallest stored size
            # wins, plain downcast preferred on ties (cheaper decode)
            down = _downcast(arr)
            if down is not None:
                codec, base = down[0], down[1].tobytes()
            delta = _delta(arr)
            if delta is not None:
                dbase = raw[: arr.dtype.itemsize] + delta[1].tobytes()
                if len(dbase) < len(base):
                    codec, base = delta[0], dbase

    # general compressed fallback, chosen by probe: wrap when the
    # deflate stream is strictly smaller (deterministic — zlib at a
    # fixed level is a pure function of its input)
    if len(base) >= _ZLIB_MIN_BYTES:
        level = _ZLIB_LEVEL if _MODE == "full" else 1
        comp = zlib.compress(base, level)
        if len(comp) < len(base):
            out.append(codec | _ZLIB_FLAG)
            out.extend(struct.pack("<q", len(comp)))
            out.extend(comp)
            return len(raw)
    out.append(codec)
    out.extend(struct.pack("<q", len(base)))
    out.extend(base)
    return len(raw)


def serialize_page(page: Page) -> bytes:
    """One Page -> bytes (the SerializedPage analog)."""
    cap = int(page.capacity)
    valid_np = np.ascontiguousarray(XF.np_host(page.valid))
    header = {"capacity": cap, "blocks": []}
    payload = bytearray()
    raw_bytes = 0

    # live-prefix truncation: rows past the LAST valid row are dead
    # in every consumer (masked by `valid`), so ship only the prefix.
    # Raw accounting still counts the full arrays — the wire/raw
    # ratio is "bytes shipped per byte of page".
    live = cap
    if _MODE == "full" and valid_np.size == cap:
        live = (int(cap - np.argmax(valid_np[::-1]))
                if valid_np.any() else 0)
        if live < cap:
            header["live"] = live

    def _enc(a: np.ndarray) -> None:
        nonlocal raw_bytes
        raw_bytes += a.nbytes
        if live < a.shape[0]:
            a = a[:live]
        _encode_array(a, payload)

    for blk in page.blocks:
        arrays = _arrays_of(blk)
        bh = {
            "type": _type_to_json(blk.type),
            "dtypes": [a.dtype.str for a in arrays],
            "nwords": len(arrays),
            "has_nulls": blk.nulls is not None,
            "dictionary": (
                [_dic_value_to_json(v) for v in blk.dictionary.values]
                if blk.dictionary is not None else None
            ),
        }
        header["blocks"].append(bh)
        for a in arrays:
            _enc(a)
        if blk.nulls is not None:
            _enc(XF.np_host(blk.nulls))
    _enc(valid_np)

    hdr = json.dumps(header).encode()
    flags = 0
    if _MODE != "raw" and len(hdr) >= 256:
        # dictionary-heavy headers (varchar columns ship their value
        # lists as JSON) dominate some pages — same probe discipline
        chdr = zlib.compress(hdr, _ZLIB_LEVEL if _MODE == "full" else 1)
        if len(chdr) < len(hdr):
            hdr, flags = chdr, _FLAG_HDR_ZLIB
    blob = (_MAGIC + _VERSION + bytes([flags])
            + struct.pack("<ii", len(hdr), len(payload))
            + hdr + bytes(payload))
    _count_wire(len(blob), raw_bytes)
    return blob


# ------------------------------------------------------------ decode
def _fail(msg: str):
    raise PageWireError(f"page blob: {msg}")


def deserialize_page(buf: bytes) -> Page:
    if len(buf) < 13 or buf[:3] != _MAGIC:
        _fail("bad magic (not a presto-tpu page)")
    if buf[3:4] != _VERSION:
        _fail(f"unsupported wire-format version {buf[3:4]!r} "
              f"(this build speaks {_VERSION!r})")
    flags = buf[4]
    hlen, blen = struct.unpack("<ii", buf[5:13])
    if hlen < 0 or blen < 0 or 13 + hlen + blen > len(buf):
        _fail(f"header/payload lengths ({hlen}, {blen}) overrun the "
              f"{len(buf)}-byte blob")
    hdr = buf[13:13 + hlen]
    if flags & _FLAG_HDR_ZLIB:
        try:
            hdr = zlib.decompress(hdr)
        except zlib.error as e:
            _fail(f"corrupt compressed header: {e}")
    try:
        header = json.loads(hdr.decode())
    except (ValueError, UnicodeDecodeError) as e:
        _fail(f"corrupt header JSON: {e}")
    payload = buf[13 + hlen:13 + hlen + blen]
    pos = 0

    def take(dtype: np.dtype, n: int) -> np.ndarray:
        nonlocal pos
        if pos + 9 > len(payload):
            _fail(f"truncated frame at payload offset {pos}")
        codec = payload[pos]
        (ln,) = struct.unpack_from("<q", payload, pos + 1)
        pos += 9
        if ln < 0 or pos + ln > len(payload):
            _fail(f"frame length {ln} at offset {pos} overruns the "
                  f"{len(payload)}-byte payload")
        data = payload[pos:pos + ln]
        pos += ln
        base = codec & ~_ZLIB_FLAG
        if codec & _ZLIB_FLAG:
            try:
                data = zlib.decompress(data)
            except zlib.error as e:
                _fail(f"corrupt compressed frame (codec {base}): {e}")
        if base == _RAW:
            if len(data) != n * dtype.itemsize:
                _fail(f"raw frame holds {len(data)} bytes, expected "
                      f"{n} x {dtype.itemsize} ({dtype})")
            return np.frombuffer(data, dtype=dtype).copy()
        if base == _RLE:
            if len(data) != dtype.itemsize:
                _fail(f"rle frame holds {len(data)} bytes, expected "
                      f"one {dtype.itemsize}-byte element ({dtype})")
            one = np.frombuffer(data, dtype=dtype)
            # broadcast+copy fills by BIT PATTERN — np.full would
            # round-trip the element through a python scalar, which
            # is lossy for NaN payloads
            return np.broadcast_to(one, (n,)).copy()
        if base in _DOWNCAST_SIZE:
            size = _DOWNCAST_SIZE[base]
            if dtype.kind not in "iu" or size >= dtype.itemsize:
                _fail(f"int{size * 8} downcast frame for "
                      f"non-widening dtype {dtype}")
            if len(data) != n * size:
                _fail(f"int{size * 8} frame holds {len(data)} bytes, "
                      f"expected {n} x {size}")
            narrow = np.frombuffer(data, dtype=f"<{dtype.kind}{size}")
            return narrow.astype(dtype)
        if base in _DELTA_SIZE:
            size = _DELTA_SIZE[base]
            w = dtype.itemsize
            if dtype.kind not in "iu" or size >= w:
                _fail(f"delta{size * 8} frame for non-widening "
                      f"dtype {dtype}")
            want = w + max(n - 1, 0) * size
            if len(data) != want:
                _fail(f"delta{size * 8} frame holds {len(data)} "
                      f"bytes, expected {want} for {n} rows of "
                      f"{dtype}")
            if n == 0:
                return np.empty(0, dtype=dtype)
            first = np.frombuffer(data, dtype=f"<u{w}", count=1)
            sd = np.frombuffer(data, dtype=f"<i{size}", offset=w)
            out = np.empty(n, dtype=f"<u{w}")
            out[0] = first[0]
            if n > 1:
                # sign-extend the narrow deltas, then wraparound
                # prefix-sum — the exact inverse of the modular diff
                np.cumsum(sd.astype(f"<u{w}"), out=out[1:])
                out[1:] += first[0]
            return out.view(dtype)
        if base == _BOOLPACK:
            if dtype.kind != "b":
                _fail(f"boolpack frame for non-bool dtype {dtype}")
            if len(data) != (n + 7) // 8:
                _fail(f"boolpack frame holds {len(data)} bytes, "
                      f"expected {(n + 7) // 8} for {n} rows")
            bits = np.unpackbits(
                np.frombuffer(data, dtype=np.uint8), count=n)
            return bits.astype(np.bool_)
        _fail(f"unknown codec byte {codec:#x}")

    try:
        cap = int(header["capacity"])
        live = int(header.get("live", cap))
        block_headers = header["blocks"]
    except (KeyError, TypeError, ValueError) as e:
        _fail(f"malformed header: {e}")
    if not 0 <= live <= cap:
        _fail(f"live prefix {live} outside page capacity {cap}")

    def pad(a: np.ndarray) -> np.ndarray:
        # zero/False-fill the dead tail dropped by the live-prefix
        # truncation (rows past the last valid row)
        if live == cap:
            return a
        full = np.zeros(cap, dtype=a.dtype)
        full[:live] = a
        return full

    blocks = []
    for bh in block_headers:
        arrays = [pad(take(np.dtype(d), live)) for d in bh["dtypes"]]
        nulls = (pad(take(np.dtype(np.bool_), live))
                 if bh["has_nulls"] else None)
        dic = (
            Dictionary([_dic_value_from_json(v)
                        for v in bh["dictionary"]])
            if bh["dictionary"] is not None else None
        )
        data = tuple(arrays) if bh["nwords"] > 1 else arrays[0]
        blocks.append(Block(
            data=data, type=_type_from_json(bh["type"]), nulls=nulls,
            dictionary=dic,
        ))
    valid = pad(take(np.dtype(np.bool_), live))
    if pos != len(payload):
        _fail(f"{len(payload) - pos} trailing payload bytes after "
              f"the last frame")
    return Page(blocks=tuple(blocks), valid=valid)


def serialize_pages(pages) -> Iterator[bytes]:
    for p in pages:
        yield serialize_page(p)
