"""Page wire format for the DCN (inter-process) boundary.

Reference: presto-main execution/buffer/PagesSerde.java +
SerializedPage (block-encoded pages, LZ4, length-prefixed) fetched by
operator/HttpPageBufferClient.java. The TPU translation keeps raw
arrays on ICI (collectives inside compiled programs, dist/executor.py)
and serializes ONLY at the process boundary, exactly as SURVEY §6.8
prescribes: "the HTTP shapes survive only at the pod boundary".

Format (little-endian, zlib-compressed payload):
    header: JSON {blocks: [{dtype(s), has_nulls, dictionary?, type}],
            capacity} + per-array raw bytes, length-prefixed.
Types are reconstructed by name through presto_tpu.types; dictionaries
ship as JSON value lists (content-equal on arrival — Dictionary hashes
by content).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Iterator, List

import numpy as np

from presto_tpu import types as T
from presto_tpu.page import Block, Dictionary, Page

_MAGIC = b"PTP1"


def _type_to_json(t: T.SqlType):
    return t.display()


def _type_from_json(s: str) -> T.SqlType:
    return T.parse_type(s)


def _arrays_of(block: Block) -> List[np.ndarray]:
    datas = block.data if isinstance(block.data, tuple) else (block.data,)
    return [np.asarray(d) for d in datas]


def serialize_page(page: Page) -> bytes:
    """One Page -> bytes (the SerializedPage analog)."""
    header = {"capacity": int(page.capacity), "blocks": []}
    payload = bytearray()

    def put(arr: np.ndarray):
        b = np.ascontiguousarray(arr).tobytes()
        payload.extend(struct.pack("<q", len(b)))
        payload.extend(b)

    for blk in page.blocks:
        arrays = _arrays_of(blk)
        header["blocks"].append({
            "type": _type_to_json(blk.type),
            "dtypes": [a.dtype.str for a in arrays],
            "nwords": len(arrays),
            "has_nulls": blk.nulls is not None,
            "dictionary": (
                [None if v is None else str(v)
                 for v in blk.dictionary.values]
                if blk.dictionary is not None else None
            ),
        })
        for a in arrays:
            put(a)
        if blk.nulls is not None:
            put(np.asarray(blk.nulls))
    put(np.asarray(page.valid))
    hdr = json.dumps(header).encode()
    body = zlib.compress(bytes(payload), level=1)
    return (_MAGIC + struct.pack("<ii", len(hdr), len(body))
            + hdr + body)


def deserialize_page(buf: bytes) -> Page:
    assert buf[:4] == _MAGIC, "bad page magic"
    hlen, blen = struct.unpack("<ii", buf[4:12])
    header = json.loads(buf[12:12 + hlen].decode())
    payload = zlib.decompress(buf[12 + hlen:12 + hlen + blen])
    pos = 0

    def take(dtype, n):
        nonlocal pos
        (ln,) = struct.unpack_from("<q", payload, pos)
        pos += 8
        arr = np.frombuffer(payload, dtype=dtype, count=n,
                            offset=pos).copy()
        pos += ln
        return arr

    cap = header["capacity"]
    blocks = []
    for bh in header["blocks"]:
        arrays = [take(np.dtype(d), cap) for d in bh["dtypes"]]
        nulls = take(np.bool_, cap) if bh["has_nulls"] else None
        dic = (Dictionary(bh["dictionary"])
               if bh["dictionary"] is not None else None)
        data = tuple(arrays) if bh["nwords"] > 1 else arrays[0]
        blocks.append(Block(
            data=data, type=_type_from_json(bh["type"]), nulls=nulls,
            dictionary=dic,
        ))
    valid = take(np.bool_, cap)
    return Page(blocks=tuple(blocks), valid=valid)


def serialize_pages(pages) -> Iterator[bytes]:
    for p in pages:
        yield serialize_page(p)
