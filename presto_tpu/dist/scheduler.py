"""General stage-DAG scheduler: walk a fragmented plan DAG in
dependency order and dispatch it task-by-task across the DCN worker
pool, with every inter-stage exchange SPOOLED on the producing worker.

Reference: presto-main execution/scheduler/SqlQueryScheduler.java
(stage-by-stage scheduling over PlanFragment DAGs) crossed with
Project Tardigrade's fault-tolerant execution ("A Decade of SQL
Analytics at Meta", VLDB 2023): stages run to completion and publish
their output into durable-enough exchange spools (PageStore host/disk
tiers on each worker, server/worker._TaskSpool), so recovery is a
SCHEDULER POLICY rather than a special case —

  - a lost LEAF task re-generates its split share deterministically on
    a survivor (the PR-5 model, unchanged);
  - a lost NON-LEAF task replays on a survivor by re-reading its input
    partitions from the surviving upstream spools (`nonleaf_replays`),
    something the un-spooled PR-5 model could not express at all;
  - a dead node additionally invalidates the spools it hosted: every
    task it ran that is still NEEDED (its consumers or the coordinator
    have not finished with it) replays in topological order, and
    consumers long-poll the replacement spools — no barrier logic, the
    token-indexed data plane provides the waiting;
  - straggler SPECULATION races a re-dispatched copy of a stage's
    slowest task on another worker and takes whichever placement
    finishes first (`speculative_tasks_won/lost`); fragments are
    deterministic, so both copies produce byte-identical spools and
    the loser is simply cancelled — nothing has consumed either copy
    before the stage barrier;
  - the worker pool is recomputed per STAGE (`DcnRunner.
    _alive_for_submit`), so an excluded node whose heartbeat recovers
    MID-QUERY rejoins at the next stage boundary instead of waiting
    for the next query.

The coordinator itself executes the DAG's root fragment, consuming the
final stages through the PR-5 token-dedupe + sha256-verified-prefix
fetch (dist/dcn._fetch_pages), so a node death during the final drain
recovers the same way.

Session properties: `stage_scheduler` (auto/true/false — auto engages
when the legacy special-cased shapes don't apply), `speculation_enabled`,
`spool_exchange_bytes` (worker-side spool tiering), plus the PR-5 knobs
(`task_retry_attempts`, `retry_backoff_ms`, `query_max_run_time`)
which govern replay exactly as they govern leaf retry.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time
import urllib.error
from typing import Dict, List, Optional

from presto_tpu import events as E_events
from presto_tpu.dist import connpool as CONNPOOL
from presto_tpu.dist import plan_serde
from presto_tpu.dist.fragmenter import (
    StageDag,
    clip_for_shipping,
    stage_key,
)


@dataclasses.dataclass
class _Placement:
    uri: str
    task_id: str


@dataclasses.dataclass
class _SchedTask:
    """One logical task of one stage and its current placement."""

    fid: int
    index: int
    base_id: str                      # qid.f<fid>.t<index>
    placement: Optional[_Placement] = None
    done: bool = False
    counted: bool = False  # spooled pages counted once per LOGICAL
    # task — a replay re-publishes the identical spool, not new volume
    retries: int = 0
    dispatched_at: float = 0.0
    wall: float = 0.0
    spec: Optional[_Placement] = None  # speculation copy in flight
    spec_count: int = 0
    span: object = None  # obs trace span for this LOGICAL task
    # spool-stats plane (ISSUE 15): the final status body's
    # per-partition row/byte counts, kept for the stage-boundary
    # re-planner's coordinator-side summation
    status: Optional[Dict] = None


class _NodeDown(RuntimeError):
    pass


class StageScheduler:
    """Schedules one StageDag over a DcnRunner's worker pool."""

    def __init__(self, coord, dag: StageDag, qid: str,
                 stage_hook=None):
        self.coord = coord
        self.dag = dag
        self.qid = qid
        self.ex = coord.runner.executor
        # query-lifecycle tracing (obs/trace.py): the DcnRunner
        # attaches the trace to the coordinator executor BEFORE
        # constructing the scheduler; None = tracing off and every
        # recording site below is one attr check
        self.trace = self.ex.trace
        # test/chaos hook: called with the fragment id after each
        # stage completes (deterministic mid-query fault injection)
        self.stage_hook = stage_hook
        # introspection: the pool each stage dispatched over — pins
        # the mid-query re-admission contract in tests
        self.stage_pools: List[List[str]] = []
        # shipped blobs carry only the origin chains type resolution
        # needs (clip_for_shipping) — payloads stay linear in plan
        # size down arbitrarily deep stage chains
        self._frag_blob: Dict[int, str] = {
            f.fid: plan_serde.dumps(clip_for_shipping(f.root))
            for f in dag.fragments
        }
        self.tasks: Dict[int, List[_SchedTask]] = {}
        self._root_done = False
        self._ntasks: Dict[int, int] = {}
        # adaptive execution (ISSUE 15): partition count each
        # dispatched producer actually spooled (broadcast reads of a
        # flipped edge must name every spooled partition), worker-side
        # boost/skew tallies settled onto the coordinator counters
        # AFTER the root execute (which resets per-query gauges)
        self._spooled_parts: Dict[int, int] = {}
        self._worker_boosts = 0
        self._worker_skew = 0
        self.replanner = None  # set by run() when adaptive is on
        # ICI exchange plane (ISSUE 18): producer fids whose
        # repartition edge lowers to an in-program all_to_all, the
        # synthetic finished-task placement holding each one's
        # partition pages, and the coordinator-built stage stats the
        # re-planner reads in place of the (raw, unpartitioned)
        # worker spool stats
        self._mesh_fids: set = set()
        self._mesh_placement: Dict[int, _Placement] = {}
        self._mesh_stats: Dict[int, object] = {}
        # fleet cache probe (ISSUE 19): deserialized fragment roots
        # for coordinator-side key computation, one loads() per
        # fragment instead of per task
        self._probe_roots: Dict[int, object] = {}

    # ------------------------------------------------------ plumbing
    def _retry_attempts(self) -> int:
        return self.coord._retry_attempts()

    def _deadline(self) -> Optional[float]:
        return self.ex.query_deadline

    def _check_deadline(self) -> None:
        self.coord._check_deadline(self._deadline())

    def _pool(self) -> List[str]:
        from presto_tpu.dist.dcn import DcnQueryFailed

        # task_retry_attempts=0 pins the classic model end to end,
        # same as the legacy path: all configured workers are picked
        # (no heartbeat gate, no silent placement changes) and the
        # first submit/fetch failure fails the QUERY cleanly
        pool = (self.coord._alive_for_submit()
                if self._retry_attempts() > 0
                else list(self.coord.worker_uris))
        if not pool:
            raise DcnQueryFailed(
                f"no ALIVE workers among {self.coord.worker_uris} "
                f"(stage-DAG scheduler)"
            )
        return pool

    def _consumer_tasks(self, fid: int) -> int:
        """Spool partition count of a repartition edge = the consumer
        stage's task count (consumer task t reads partition t)."""
        for f in self.dag.fragments:
            if fid in f.inputs:
                return self._ntasks[f.fid]
        return 1  # root consumer (always a gather) or unknown

    # ------------------------------------------- ICI exchange plane
    def _mesh_eligible(self, fid: int, pool: List[str]) -> bool:
        """Whether this stage's repartition edge lowers to the ICI
        all_to_all plane (ISSUE 18). auto/true = only when the whole
        dispatch pool is co-resident in THIS process (the mesh the
        coordinator's collective runs on IS the mesh the spools live
        on — zero-copy collection, zero-copy consumer reads) and the
        consumer task count maps onto the local device mesh. Any miss
        is a shape, not an error: the stage simply keeps the spooled
        HTTP plane, which stays authoritative for DCN-remote
        consumers and replay recovery."""
        import jax

        from presto_tpu.server import worker as W

        mode = self.coord.runner.session.get("mesh_exchange_mode")
        if mode == "false":
            return False
        frag = self.dag.fragment(fid)
        if frag.output_kind != "repartition" or not frag.output_keys:
            return False
        if not frag.sharded:
            return False
        nparts = self._consumer_tasks(fid)
        if nparts < 2 or (nparts & (nparts - 1)) != 0:
            return False
        if nparts > len(jax.devices()):
            return False
        return all(W.local_runtime(uri) is not None for uri in pool)

    def _run_mesh_exchange(self, fid: int) -> None:
        """After the stage barrier: collect the producers' RAW device
        pages straight out of their same-process spools, run the
        all_to_all partitioning program, and park the partition pages
        in ONE synthetic finished task on the first producer's
        runtime — consumers then read partition t.index from it over
        the unchanged spool data plane. Any trace/shape failure falls
        back LOUDLY (counted, logged) to the spool partitioner with
        BIT-IDENTICAL splitmix64 routing, so the fallback's partition
        contents equal the collective's."""
        import logging

        from presto_tpu.adaptive import StageStats
        from presto_tpu.dist import executor as DX
        from presto_tpu.dist import spool as SPOOL
        from presto_tpu.server import worker as W
        from presto_tpu.server.worker import _TaskSpool

        ex = self.ex
        frag = self.dag.fragment(fid)
        keys = tuple(frag.output_keys)
        nparts = self._consumer_tasks(fid)
        pages = []
        for t in self.tasks[fid]:
            it = SPOOL.local_source_pages(
                t.placement.uri, t.placement.task_id, 0)
            if it is None:
                # placement migrated off-process mid-stage (replay on
                # a remote survivor): nothing to collect locally —
                # loud fallback is impossible too, so the consumers
                # must read the raw spool; surface as a hard error
                # (eligibility pinned every pool member local, and a
                # replay lands back on the same local pool)
                raise RuntimeError(
                    f"mesh exchange: producer spool for stage {fid} "
                    f"not local at {t.placement.uri}")
            pages.extend(it)
        from presto_tpu.exec.executor import page_bytes

        total_bytes = sum(page_bytes(p) for p in pages)
        ici = False
        try:
            parts, nbytes = DX.ici_exchange_pages(
                ex, pages, keys, nparts)
            ex.ici_exchanges += 1
            ex.ici_bytes += nbytes
            ici = True
        except Exception as e:  # noqa: BLE001 - loud fallback below
            ex.mesh_exchange_fallbacks += 1
            logging.getLogger("presto_tpu.dist").warning(
                "mesh exchange for stage %d fell back to the spool "
                "partitioner: %r", fid, e)
            from presto_tpu.exec import shapes as SH

            # the coordinator owns this exchange, so the spool
            # partitioner's deferred overflow flags settle HERE (a
            # worker defers them into its stream_fragment attempt
            # loop); each overflowing round re-partitions everything
            # one ladder rung up
            boost0 = ex._capacity_boost
            try:
                while True:
                    n0 = len(ex._pending_overflow)
                    parts = [[] for _ in range(nparts)]
                    for page in pages:
                        for p, part_page in \
                                SPOOL.device_partition_pages(
                                    ex, page, keys, nparts):
                            parts[p].append(part_page)
                    flags = ex._pending_overflow[n0:]
                    del ex._pending_overflow[n0:]
                    if not any(bool(f) for f in flags):
                        break
                    ex._capacity_boost = SH.next_boost(
                        ex._capacity_boost)
                    ex.capacity_boost_retries += 1
                    if ex._capacity_boost > SH.DEVICE_FAULT_ROWS:
                        raise RuntimeError(
                            "mesh-exchange fallback overflow did not "
                            "settle on the boost ladder")
            finally:
                ex._capacity_boost = boost0
        # host budget 0 = never demote: the landing caps already
        # bound HBM residency, and a demotion would serialize
        # (spool_blob d2h) behind the plane's zero-crossing contract
        spool = _TaskSpool(nparts, 0)
        for p in range(nparts):
            for page in parts[p]:
                spool.put_page(p, page, rows=0)
        uri = self.tasks[fid][0].placement.uri
        task_id = f"{self.qid}.f{fid}.mesh"
        W.local_runtime(uri).register_finished_task(task_id, spool)
        self._mesh_placement[fid] = _Placement(uri, task_id)
        # stage stats for the re-planner: the mesh path never pulls
        # per-partition counts (that d2h is exactly what it deletes),
        # so rows/bytes are the STATIC capacity upper bounds;
        # ici_bytes>0 marks the freight as interconnect-resident for
        # the broadcast-flip cost model (adaptive/replanner.py)
        self._mesh_stats[fid] = StageStats(
            fid=fid,
            rows=sum(p.capacity for p in pages),
            bytes=total_bytes,
            part_rows=tuple(
                sum(pg.capacity for pg in parts[p])
                for p in range(nparts)),
            part_bytes=tuple(
                total_bytes // nparts for _ in range(nparts)),
            task_rows=tuple(
                sum(p.capacity for p in pages)
                for _ in self.tasks[fid]),
            wire_bytes=0,
            ici_bytes=total_bytes if ici else 0,
        )

    def _payload_for(self, t: _SchedTask, task_id: str) -> Dict:
        frag = self.dag.fragment(t.fid)
        n = self._ntasks[t.fid]
        payload: Dict = {
            "taskId": task_id,
            "fragment": self._frag_blob[t.fid],
            "splitIndex": t.index,
            "splitCount": n,
            "session": self.coord.session_props,
        }
        if frag.split_table is not None:
            payload["splitTable"] = frag.split_table
        if self.trace is not None:
            # workers record queue/run/attempt spans and ship them on
            # the status plane for the cross-node timeline
            payload["trace"] = True
        if t.fid in self._mesh_fids:
            # ICI exchange plane (ISSUE 18): the producer spools its
            # RAW device pages to ONE partition and the coordinator
            # runs the all_to_all partitioning itself after the stage
            # barrier — the worker skips per-page hashing, P-way
            # compaction, and the spool-stats d2h pull entirely
            payload["outputPartitions"] = 1
            payload["meshExchange"] = True
        elif frag.output_kind == "repartition":
            payload["outputPartitions"] = self._consumer_tasks(t.fid)
            payload["outputKeys"] = list(frag.output_keys)
        else:
            # gather / broadcast / adaptive passthrough: ONE spool
            # partition per task (a passthrough consumer reads its
            # same-index producer task's whole spool)
            payload["outputPartitions"] = 1
        self._spooled_parts.setdefault(
            t.fid, int(payload["outputPartitions"]))
        if self.dag.hints.get(t.fid, {}).get("skew"):
            # adaptive skew pre-engagement (ISSUE 15): the upstream
            # spool histogram showed a hot partition — the worker's
            # executor starts in the position-chunked rebalance mode
            payload["skewHint"] = True
        if frag.inputs:
            # sources rebuilt from CURRENT placements at every
            # (re)dispatch — a replayed consumer reads the replacement
            # spools, not the dead node's
            payload["sources"] = {}
            for u in frag.inputs:
                read = self.dag.read_kind(t.fid, u)
                tasks = [
                    {"uri": ut.placement.uri,
                     "taskId": ut.placement.task_id}
                    for ut in self.tasks[u]
                ]
                spec: Dict = {"tasks": tasks}
                up_kind = self.dag.fragment(u).output_kind
                if (u in self._mesh_placement
                        and up_kind == "repartition"
                        and read == "repartition"):
                    # mesh-lowered producer: every consumer task reads
                    # its partition from the ONE synthetic task the
                    # coordinator's all_to_all landed — same spool
                    # data plane (local fast path or HTTP), one
                    # producer placement instead of N
                    mp = self._mesh_placement[u]
                    spec["tasks"] = [{"uri": mp.uri,
                                      "taskId": mp.task_id}]
                    spec["partition"] = t.index
                elif up_kind == "repartition" and read == "broadcast":
                    # adaptive dist flip: the producer ALREADY spooled
                    # P hash partitions; draining every one of them
                    # from every producer task is exactly the full
                    # build a broadcast spool would have held
                    spec["partitions"] = list(range(
                        self._spooled_parts.get(u) or 1))
                elif up_kind == "passthrough":
                    # consumer task t reads producer task t only —
                    # task counts agree (both stages shard over the
                    # same pool; verify_dag pins sharded-ness)
                    spec["partition"] = 0
                    spec["tasks"] = [tasks[t.index]]
                elif up_kind == "repartition":
                    spec["partition"] = t.index
                else:
                    spec["partition"] = 0
                payload["sources"][stage_key(u)] = spec
        return payload

    def _post(self, uri: str, payload: Dict) -> None:
        if self.ex._plan_check_on():
            from presto_tpu.exec import plan_check as PC

            PC.check_task_payload(payload)
        self.coord._post_task(uri, payload)

    def _status(self, pl: _Placement) -> Dict:
        last: Optional[BaseException] = None
        for _ in range(2):
            try:
                with CONNPOOL.request(
                    f"{pl.uri}/v1/task/{pl.task_id}", timeout=5
                ) as r:
                    return json.loads(r.read().decode())
            except (urllib.error.URLError, ConnectionError,
                    OSError) as e:
                last = e
                time.sleep(0.05)
        raise _NodeDown(f"{pl.uri}: {last}")

    def _delete(self, pl: _Placement) -> None:
        self.coord._release_task(pl.uri, pl.task_id)

    # ----------------------------------------------------- adaptive
    def _adaptive_on(self) -> bool:
        """adaptive_execution resolution: "auto" = ON under the stage
        scheduler (this IS the stage-boundary barrier adaptive
        engines need — there is nowhere cheaper to re-plan), "false"
        kills the path, "true" forces (same as auto here)."""
        mode = self.coord.runner.session.get("adaptive_execution")
        return mode != "false"

    def _make_replanner(self):
        from presto_tpu.adaptive import Replanner

        opts = self.coord.runner._session_dist_options()
        return Replanner(
            self.ex, self.dag,
            broadcast_rows=opts.get("broadcast_rows"),
            broadcast_bytes=opts.get("broadcast_bytes"),
            max_replans=int(self.coord.runner.session.get(
                "adaptive_max_replans")),
        )

    def _stage_stats(self, fid: int):
        from presto_tpu.adaptive import stats_from_statuses

        if fid in self._mesh_stats:
            # mesh-lowered stage: the workers spooled RAW pages with
            # no per-partition stats (the d2h pull the plane
            # deletes); the coordinator-built capacity-bound stats
            # stand in (ISSUE 18)
            return self._mesh_stats[fid]
        bodies = [t.status for t in self.tasks[fid]
                  if t.status is not None]
        if len(bodies) != len(self.tasks[fid]):
            return None
        return stats_from_statuses(fid, bodies)

    def _maybe_replan(self, fid: int, dispatched) -> None:
        """The stage-boundary barrier: the just-completed stage's
        exact spool stats feed the re-planner, which may mutate the
        not-yet-dispatched DAG suffix (re-verified, or rolled back
        and counted). Mutated fragments re-serialize so every later
        dispatch ships the re-planned tree."""
        rp = self.replanner
        st = self._stage_stats(fid)
        if st is not None:
            rp.observe(st)
        tr = self.trace
        t0 = tr.now() if tr is not None else 0.0
        outcome = rp.replan(dispatched)
        if outcome is None:
            return
        if outcome.rejected:
            self.ex.adaptive_replan_rejected += 1
        else:
            self.ex.adaptive_replans += 1
            self.ex.adaptive_dist_flips += outcome.dist_flips
            self.ex.adaptive_capacity_seeds += outcome.capacity_seeds
            for mfid in outcome.mutated_fids:
                self._frag_blob[mfid] = plan_serde.dumps(
                    clip_for_shipping(self.dag.fragment(mfid).root))
        if tr is not None:
            tr.complete(
                "replan", f"stage{fid}", t0, tr.now(),
                rejected=outcome.rejected,
                flips=outcome.dist_flips,
                seeds=outcome.capacity_seeds,
                skew_hints=outcome.skew_hints,
                reason=outcome.reason[:120],
            )
            self.ex.trace_spans += 1

    # -------------------------------------------------- run the DAG
    def run(self) -> list:
        """Execute the DAG; returns the materialized row list."""
        dag, ex = self.dag, self.ex
        pool0 = self._pool()
        self.coord.last_pool = list(pool0)
        n = len(pool0)
        for f in dag.fragments:
            self._ntasks[f.fid] = n if f.sharded else 1
            self.tasks[f.fid] = [
                _SchedTask(fid=f.fid, index=i,
                           base_id=f"{self.qid}.f{f.fid}.t{i}")
                for i in range(self._ntasks[f.fid])
            ]
        if self._adaptive_on():
            self.replanner = self._make_replanner()
        dispatched: set = set()
        ckpt = getattr(self.coord, "checkpoint_handle", None)
        try:
            for f in dag.fragments:
                self._run_stage(f.fid)
                dispatched.add(f.fid)
                if self.stage_hook is not None:
                    self.stage_hook(f.fid)
                if self.replanner is not None:
                    self._maybe_replan(f.fid, dispatched)
                if ckpt is not None:
                    # spooled-stage barrier checkpoint (ISSUE 20):
                    # placements + re-dispatchable payloads; the
                    # payload rebuild reads CURRENT placements, so a
                    # restarted coordinator re-POSTs exactly what a
                    # live replay would have
                    self._checkpoint_stage(ckpt, f.fid)
            # coordinator-side root fragment over the final stages
            if ckpt is not None:
                self._checkpoint_root(ckpt)
            self._pre_root_hook()
            for fid in dag.root_inputs:
                ex.remote_sources[stage_key(fid)] = \
                    self._root_supplier(fid)
            names, rows = ex.execute(dag.root)
            self.root_names = list(names)
            self._root_done = True
            # settle worker-side ladder outcomes onto the coordinator
            # gauges AFTER execute() (which resets them): EXPLAIN
            # ANALYZE / system.metrics then show the QUERY's total
            # boost retries, stage tasks included
            ex.capacity_boost_retries += self._worker_boosts
            ex.skew_preempted += self._worker_skew
            return rows
        finally:
            for fid in dag.root_inputs:
                ex.remote_sources.pop(stage_key(fid), None)
            # release worker-side spools (task expiry); skips on dead
            # workers are counted, never swallowed
            for ts in self.tasks.values():
                for t in ts:
                    if t.placement is not None:
                        self._delete(t.placement)
                    if t.spec is not None:
                        self._delete(t.spec)
            # synthetic mesh-exchange tasks release like any other
            # placement (task expiry frees their partition pages)
            for pl in self._mesh_placement.values():
                self._delete(pl)

    # ------------------------------------------- checkpoint barriers
    def _checkpoint_stage(self, ckpt, fid: int) -> None:
        """Journal one completed stage: every live placement + the
        full re-dispatchable payload (ISSUE 20). Best-effort — a
        serialization failure drops THIS barrier loudly (counted) and
        the query runs on; recovery then falls back to the re-run
        rung instead of the spool-resume rung."""
        try:
            tasks = [
                {"uri": t.placement.uri,
                 "task_id": t.placement.task_id,
                 "payload": self._payload_for(
                     t, t.placement.task_id)}
                for t in self.tasks[fid] if t.placement is not None
            ]
            ckpt.record_stage(
                fid, key=stage_key(fid),
                parts=self._spooled_parts.get(fid, 1),
                tasks=tasks, replan_gen=self.ex.adaptive_replans)
        except Exception as e:  # noqa: BLE001 - checkpoint barriers
            # are best-effort: the QUERY must never fail because its
            # journal write did; the drop is counted and logged
            self.ex.checkpoint_drops += 1
            logging.getLogger("presto_tpu.dist").warning(
                "stage %d checkpoint dropped: %r", fid, e)

    def _checkpoint_root(self, ckpt) -> None:
        """Final-stage registration barrier: the coordinator-side
        root fragment blob + which stages feed it."""
        try:
            blob = plan_serde.dumps(clip_for_shipping(self.dag.root))
        except Exception as e:  # noqa: BLE001 - same best-effort
            # contract as _checkpoint_stage: count, log, run on
            blob = None
            self.ex.checkpoint_drops += 1
            logging.getLogger("presto_tpu.dist").warning(
                "root checkpoint blob dropped: %r", e)
        ckpt.record_root(blob, list(self.dag.root_inputs))

    def _pre_root_hook(self) -> None:
        """Deterministic fault window between the last stage barrier
        and the final drain: FAULT_COORD_STALL_MS parks the
        coordinator here (the chaos harness SIGKILLs it mid-stall
        with every producer spool live), and a test-installed
        coord._root_hook can park or kill synchronously."""
        stall = os.environ.get("FAULT_COORD_STALL_MS")
        if stall:
            time.sleep(int(stall) / 1000.0)
        hook = getattr(self.coord, "_root_hook", None)
        if hook is not None:
            hook(self)

    # ------------------------------------------------------- stages
    def _probe_key(self, t: _SchedTask, frag) -> Optional[str]:
        """The fragment-cache key THIS task's execution would compute
        on a worker (dist/cacheprobe.fragment_cache_key mirrors the
        worker's split wrap + salt), or None when the fragment is not
        root-cacheable. Advisory: any failure here reads as a miss."""
        root = self._probe_roots.get(t.fid)
        if root is None:
            try:
                root = plan_serde.loads(self._frag_blob[t.fid])
            except Exception:  # noqa: BLE001 - advisory probe
                return None
            self._probe_roots[t.fid] = root
        from presto_tpu.dist.cacheprobe import fragment_cache_key

        try:
            return fragment_cache_key(
                root, self.coord.runner.catalogs,
                split_table=frag.split_table, split_index=t.index,
                split_count=self._ntasks[t.fid],
                collect_k=self.ex.collect_k,
                page_rows=self.ex.page_rows,
            )
        except Exception:  # noqa: BLE001 - advisory probe
            return None

    def _probe_cache(self, t: _SchedTask, pool) -> bool:
        """Pre-dispatch fleet cache probe (ISSUE 19): True iff some
        fleet member served this leaf task's fragment from its result
        cache (the task is then already placed + done). Gated so the
        common miss is FREE: bloom summaries refreshed on heartbeats
        answer "definitely not cached" without a round trip; only a
        "maybe" costs one pooled POST. Leaf split fragments with
        single-partition output only — a repartition producer's P-way
        spool and the mesh plane's raw-page contract are not what the
        cache holds."""
        coord = self.coord
        idx = getattr(coord, "cache_index", None)
        if idx is None or not idx.known():
            return False
        sess = coord.runner.session
        if not (bool(sess.get("result_cache_enabled"))
                and bool(sess.get("result_cache_remote_probe"))):
            return False
        frag = self.dag.fragment(t.fid)
        if frag.inputs or frag.split_table is None \
                or t.fid in self._mesh_fids \
                or frag.output_kind == "repartition":
            return False
        key = self._probe_key(t, frag)
        if key is None:
            return False
        timeout = coord._probe_budget(self.ex)
        if timeout is None:
            return False  # deadline can't afford a probe: dispatch
        for uri in pool:
            if uri in coord._excluded or \
                    not idx.might_contain(uri, key):
                continue
            try:
                with CONNPOOL.request(
                    f"{uri}/v1/cache/task",
                    method="POST",
                    data=json.dumps(
                        {"taskId": t.base_id, "key": key}).encode(),
                    headers={"Content-Type": "application/json"},
                    timeout=timeout,
                ) as r:
                    out = json.loads(r.read().decode())
            except (urllib.error.URLError, ConnectionError,
                    OSError, ValueError):
                continue  # bloom false positive / slow peer: dispatch
            if out.get("hit"):
                t.placement = _Placement(uri, t.base_id)
                t.dispatched_at = time.monotonic()
                t.done = True
                t.counted = True
                self.ex.cache_remote_hits += 1
                tr = self.trace
                if tr is not None:
                    now = tr.now()
                    tr.complete("cache", f"remote-hit:{t.base_id}",
                                now, now, uri=uri, key=key)
                    self.ex.trace_spans += 1
                return True
        return False

    def _run_stage(self, fid: int) -> None:
        # pool recomputed per stage: an excluded node whose heartbeat
        # recovered rejoins HERE, mid-query (re-admission probes are
        # rate-limited inside _alive_for_submit)
        pool = self._pool()
        self.stage_pools.append(list(pool))
        if self._mesh_eligible(fid, pool):
            # decided BEFORE dispatch: every task payload of this
            # stage must carry the meshExchange contract (raw
            # one-partition spools) for the post-barrier collective
            self._mesh_fids.add(fid)
        stage = self.tasks[fid]
        tr = self.trace
        sspan = None
        s_start = time.monotonic()
        spooled0 = self.ex.spooled_exchange_pages
        if tr is not None:
            sspan = tr.begin("stage", f"stage{fid}",
                             tasks=len(stage), pool=len(pool))
            self.ex.trace_spans += 1
        for t in stage:
            if self._probe_cache(t, pool):
                # fleet cache hit (ISSUE 19): some worker already
                # holds this split fragment's pages — the task is
                # DONE without dispatch; consumers/gather read the
                # parked spool over the ordinary fetch plane
                continue
            if pool[t.index % len(pool)] in self.coord._excluded:
                # an earlier submit in THIS wave excluded a node:
                # refresh the pool so the remaining tasks neither
                # burn their retry budget nor pay connect timeouts
                # against a known-dead target
                pool = self._pool()
                self.stage_pools[-1] = list(pool)
            target = pool[t.index % len(pool)]
            if tr is not None:
                t.span = tr.begin("task", t.base_id, parent=sspan,
                                  uri=target)
                self.ex.trace_spans += 1
            try:
                d0 = tr.now() if tr is not None else 0.0
                self._post(target, self._payload_for(t, t.base_id))
                t.placement = _Placement(target, t.base_id)
                t.dispatched_at = time.monotonic()
                if tr is not None:
                    tr.complete("dispatch", t.base_id, d0, tr.now(),
                                parent=t.span, uri=target)
                    self.ex.trace_spans += 1
            except (urllib.error.URLError, OSError) as e:
                # submit failure: recover through the shared path
                # (exclude + re-dispatch to a survivor) — not a spool
                # replay, the task never ran (replay=False)
                self.coord._exclude(target)
                t.placement = _Placement(target, t.base_id)
                self._redispatch(t, cause=e, replay=False)
        self.ex.stages_scheduled += 1
        self._wait(stage)
        if fid in self._mesh_fids:
            # stage barrier passed: run the ICI all_to_all over the
            # producers' raw spools before any consumer dispatches
            self._run_mesh_exchange(fid)
        if tr is not None:
            tr.end(sspan)
        # the EventListener SPI fires traced or not (span stats ride
        # along when tracing is on; walls come from monotonic either
        # way — the timing-source rule)
        E_events.dispatch(
            self.coord.listeners, "stage_completed",
            E_events.StageCompletedEvent(
                query_id=self.qid, stage_id=f"stage{fid}",
                task_count=len(stage),
                wall_ms=int((time.monotonic() - s_start) * 1000),
                retries=sum(t.retries for t in stage),
                # per-STAGE delta, not the query-cumulative counter
                # (the counter is coordinator-lifetime; a listener
                # summing stage events must see each page once)
                spooled_pages=(self.ex.spooled_exchange_pages
                               - spooled0),
            ),
            on_error=self.ex.count_listener_error,
        )
        if self._retry_attempts() <= 0:
            # pinned classic mode: no replay will ever need these
            # spools again once the consumer stage is done — ack
            # (release) consumed input partitions eagerly
            self._ack_inputs(fid)

    def _wait(self, stage: List[_SchedTask]) -> None:
        # status polls back off geometrically (20 ms -> 250 ms cap):
        # short tasks resolve fast, long stages stop hammering the
        # workers' HTTP threads (which also serve the spool data plane)
        delay = 0.02
        while True:
            self._check_deadline()
            # replayed earlier-stage tasks ride along in the poll set:
            # their completion unblocks this stage's long-polling
            # consumers, and a FAILED replay must surface
            pending = [t for ts in self.tasks.values() for t in ts
                       if t.placement is not None and not t.done]
            if all(t.done for t in stage):
                return
            progressed = False
            for t in pending:
                self._poll_task(t)
                progressed = progressed or t.done
            self._maybe_speculate(stage)
            delay = 0.02 if progressed else min(delay * 1.5, 0.25)
            time.sleep(delay)

    def _poll_task(self, t: _SchedTask) -> None:
        # speculation copy first: a finished copy wins immediately
        if t.spec is not None:
            try:
                st = self._status(t.spec)
                if st["state"] == "FINISHED":
                    self.ex.speculative_tasks_won += 1
                    loser = t.placement
                    t.placement, t.spec = t.spec, None
                    self._complete(t, st)
                    if loser is not None:
                        self._delete(loser)
                    return
                if st["state"] == "FAILED":
                    t.spec = None  # copy died; original keeps running
            except _NodeDown:
                t.spec = None
        try:
            st = self._status(t.placement)
        except _NodeDown:
            self._node_lost(t.placement.uri)
            return
        if st["state"] == "FINISHED":
            if t.spec is not None:
                self.ex.speculative_tasks_lost += 1
                self._delete(t.spec)
                t.spec = None
            self._complete(t, st)
        elif st["state"] == "FAILED":
            msg = str(st.get("error") or "task failed")
            if "[source-lost " in msg:
                # the task died because an UPSTREAM spool vanished:
                # replay the upstream placements on that node first,
                # then re-dispatch this consumer with rebuilt sources
                src_uri = msg.split("[source-lost ", 1)[1].split()[0]
                if src_uri:
                    self._node_lost(src_uri)
            self._redispatch(t, cause=RuntimeError(msg))

    def _complete(self, t: _SchedTask, st: Dict) -> None:
        t.done = True
        t.wall = time.monotonic() - t.dispatched_at
        # spool-stats plane: the LAST status body wins (a replay
        # re-publishes identical stats — deterministic spools)
        t.status = st
        if not t.counted:
            t.counted = True
            self.ex.spooled_exchange_pages += int(
                st.get("spooledPages") or 0)
            # worker-side executor outcomes, settled onto the
            # coordinator's registry counters after the root execute
            # (ISSUE 15: "first-run overflow boosts driven to zero"
            # must be measurable where EXPLAIN ANALYZE reads)
            self._worker_boosts += int(st.get("boostRetries") or 0)
            self._worker_skew += int(st.get("skewPreempted") or 0)
        # cross-node timeline assembly: the worker's queue/run/attempt
        # spans (offsets from ITS task creation) nest into this task's
        # coordinator-side window, clamped so clock/queue skew can
        # never produce a negative interval (obs/trace.ingest)
        tr = self.trace
        queue_ms = run_ms = 0
        remote = st.get("spans") or []
        for d in remote:
            try:
                ms = int((float(d["t1"]) - float(d["t0"])) * 1000)
            except (KeyError, TypeError, ValueError):
                continue
            if d.get("kind") == "queue":
                queue_ms += max(ms, 0)
            elif d.get("kind") == "run":
                run_ms += max(ms, 0)
        if tr is not None and t.span is not None:
            if remote:
                self.ex.trace_spans += tr.ingest(
                    remote, t.span, t.span.t0, tr.now())
            tr.end(t.span, pages=int(st.get("pages") or 0),
                   spooled=int(st.get("spooledPages") or 0),
                   retries=t.retries, uri=t.placement.uri)
        E_events.dispatch(
            self.coord.listeners, "task_completed",
            E_events.TaskCompletedEvent(
                query_id=self.qid, task_id=t.placement.task_id,
                stage_id=f"stage{t.fid}", uri=t.placement.uri,
                state="FINISHED", wall_ms=int(t.wall * 1000),
                queue_ms=queue_ms, run_ms=run_ms,
                pages=int(st.get("pages") or 0), retries=t.retries,
                speculative=t.spec_count > 0,
            ),
            on_error=self.ex.count_listener_error,
        )

    # ----------------------------------------------------- recovery
    def _stage_done(self, fid: int) -> bool:
        return all(t.done for t in self.tasks[fid])

    def _still_needed(self, fid: int) -> bool:
        """Whether a stage's spools can still be consumed: by a
        not-yet-finished consumer stage, or by the coordinator's root
        fragment until the query completes."""
        if fid in self.dag.root_inputs and not self._root_done:
            return True
        return any(not self._stage_done(c)
                   for c in self.dag.consumers(fid))

    def _node_lost(self, uri: str) -> None:
        """A node died: exclude it and replay, in topological order,
        every task it hosted whose output is still needed — leaf tasks
        re-generate their split share, non-leaf tasks re-read the
        surviving upstream spools. Consumers long-poll the replacement
        spools, so no explicit stage barrier is re-run.

        Neededness is evaluated with EVERY hosted task pessimistically
        marked un-done first: a dead node's stage-k spool is needed
        whenever its stage-k+1 consumer (possibly on the same node)
        must replay, even if stage k+1 had finished — evaluating
        against the pre-death done flags would skip the upstream spool
        and doom the consumer's first replay to a [source-lost]
        failure, burning a retry."""
        self.coord._exclude(uri)
        cand = [
            t for ts in self.tasks.values() for t in ts
            if t.placement is not None and t.placement.uri == uri
        ]
        was_done = [(t, t.done) for t in cand]
        for t in cand:
            t.done = False
        lost = [t for t, done in was_done
                if not done or self._still_needed(t.fid)]
        for t, done in was_done:
            if done and t not in lost:
                t.done = True  # genuinely unneeded: nothing consumes it
        for t in sorted(lost, key=lambda x: x.fid):
            self._redispatch(t, cause=_NodeDown(uri))

    def _redispatch(self, t: _SchedTask, cause: BaseException,
                    replay: bool = True) -> None:
        """Re-dispatch one task to a survivor. replay=False marks an
        initial-submit failure (the task never ran; nothing is being
        replayed from a spool) so the nonleaf_replays counter stays an
        honest measure of the spooled-replay path."""
        from presto_tpu import events as E
        from presto_tpu.dist.dcn import DcnQueryFailed

        retry_attempts = self._retry_attempts()
        deadline = self._deadline()
        while True:
            if retry_attempts <= 0 or t.retries >= retry_attempts:
                raise DcnQueryFailed(
                    f"stage task {t.base_id}: {cause} (task retries "
                    f"exhausted: task_retry_attempts={retry_attempts})"
                ) from cause
            t.retries += 1
            self.coord._sleep_backoff(t.retries, deadline)
            self._check_deadline()
            pool = self._pool()
            old_uri = t.placement.uri if t.placement else None
            survivors = sorted(pool, key=lambda u: u == old_uri)
            target = survivors[(t.retries - 1) % len(survivors)]
            new_id = f"{t.base_id}.r{t.retries}"
            from_uri = old_uri or "?"
            try:
                self._post(target, self._payload_for(t, new_id))
            except (urllib.error.URLError, OSError) as e:
                self.coord._exclude(target)
                cause = e
                continue
            if t.spec is not None:
                # cancel an in-flight speculation copy of the OLD
                # placement — orphaning it would leak its spool on
                # the worker until task expiry
                self._delete(t.spec)
            if self.trace is not None and t.span is not None:
                # trace annotation: the fault-tolerance path is part
                # of the timeline (replay=True marks a spooled replay)
                self.trace.complete(
                    "retry", new_id, self.trace.now(),
                    self.trace.now(), parent=t.span,
                    attempt=t.retries, to=target,
                    cause=str(cause)[:120], replay=bool(replay))
                self.ex.trace_spans += 1
            t.placement = _Placement(target, new_id)
            t.done = False
            t.spec = None
            t.dispatched_at = time.monotonic()
            self.ex.task_retries += 1
            if replay and self.dag.fragment(t.fid).inputs:
                # the recovery the spool tier exists for: a NON-LEAF
                # task replaying from spooled upstream pages
                self.ex.nonleaf_replays += 1
            E.dispatch(
                self.coord.listeners, "task_retried",
                E.TaskRetryEvent(
                    query_id=self.qid, task_id=new_id,
                    from_uri=from_uri, to_uri=target,
                    attempt=t.retries, cause=str(cause)[:400],
                ),
                on_error=self.ex.count_listener_error,
            )
            return

    # -------------------------------------------------- speculation
    def _maybe_speculate(self, stage: List[_SchedTask]) -> None:
        if not bool(self.coord.runner.session.get(
                "speculation_enabled")):
            return
        running = [t for t in stage if not t.done]
        if len(running) != 1:
            return
        t = running[0]
        if t.spec is not None or t.spec_count >= 2 or \
                t.placement is None:
            return
        walls = sorted(x.wall for x in stage if x.done)
        if not walls:
            return
        median = walls[len(walls) // 2]
        if time.monotonic() - t.dispatched_at < max(0.25, 2 * median):
            return
        others = [u for u in self.coord._alive_for_submit()
                  if u != t.placement.uri]
        if not others:
            return
        t.spec_count += 1
        sid = f"{t.base_id}.s{t.spec_count}"
        try:
            self._post(others[0], self._payload_for(t, sid))
            t.spec = _Placement(others[0], sid)
            if self.trace is not None and t.span is not None:
                self.trace.complete(
                    "speculate", sid, self.trace.now(),
                    self.trace.now(), parent=t.span, uri=others[0])
                self.ex.trace_spans += 1
        except (urllib.error.URLError, OSError):
            pass  # speculation is best-effort; the original runs on

    # --------------------------------------------------------- acks
    def _ack_inputs(self, fid: int) -> None:
        from presto_tpu.dist import spool as SPOOL

        frag = self.dag.fragment(fid)
        for u in frag.inputs:
            # every partition the producer actually spooled (recorded
            # at dispatch) — correct for repartition, gather, and the
            # adaptive passthrough / broadcast-read modes alike
            parts = range(self._spooled_parts.get(u) or 1)
            for ut in self.tasks[u]:
                if ut.placement is None:
                    continue
                for part in parts:
                    SPOOL.ack_spool(ut.placement.uri,
                                    ut.placement.task_id, part)

    # --------------------------------------------- root-stage drain
    def _root_supplier(self, fid: int):
        from presto_tpu.dist.dcn import DcnQueryFailed, _TaskLost
        from presto_tpu.dist.dcn import _TaskState

        stage = self.tasks[fid]
        ckpt = getattr(self.coord, "checkpoint_handle", None)

        def supplier():
            from presto_tpu.dist import spool as SPOOL

            deadline = self._deadline()
            tr = self.trace
            for t in stage:
                # mesh-local fast path (ISSUE 13): a same-process
                # placement's spool serves its Pages directly — no
                # HTTP, no serde, no sha256 prefix bookkeeping (there
                # is no wire prefix to verify), and zero metered
                # crossings when the spool is device-resident. A
                # stopped/unregistered runtime falls through to the
                # HTTP path, whose _TaskLost handling replays as ever.
                f0 = tr.now() if tr is not None else 0.0
                local = SPOOL.local_source_pages(
                    t.placement.uri, t.placement.task_id, 0)
                if local is not None:
                    self.ex.count_mesh_local()
                    npages = 0
                    for page in local:  # streams page-at-a-time
                        npages += 1
                        yield page
                    if tr is not None:
                        tr.complete("fetch", t.placement.task_id, f0,
                                    tr.now(), pages=npages,
                                    uri=t.placement.uri, local=True)
                        self.ex.trace_spans += 1
                    continue
                # fresh state per supplier invocation: a coordinator
                # boosted retry re-pulls from token 0 (spools retain
                # the full partition); within ONE invocation a
                # replayed task resumes at the consumed token after
                # sha256 prefix verification
                st = _TaskState(
                    uri=t.placement.uri,
                    task_id=t.placement.task_id,
                    payload=self._payload_for(
                        t, t.placement.task_id),
                )
                f0 = tr.now() if tr is not None else 0.0
                while True:
                    try:
                        yield from self.coord._fetch_pages(st, deadline)
                        break
                    except _TaskLost as e:
                        if self._retry_attempts() <= 0:
                            raise DcnQueryFailed(str(e)) from e
                        self._recover_root_fetch(t, st, e)
                if ckpt is not None:
                    # final-drain barrier: consumed token + rolling
                    # prefix digest for this task (ISSUE 20)
                    ckpt.record_drain(fid, t.index, st.next_token,
                                      st.hasher.hexdigest())
                if tr is not None:
                    # root-parented: the drain happens AFTER the task
                    # span closed (task completion ≠ consumption) — a
                    # fetch child would escape its parent's interval
                    tr.complete("fetch", t.placement.task_id, f0,
                                tr.now(), pages=st.next_token,
                                uri=t.placement.uri)
                    self.ex.trace_spans += 1

        return supplier

    def _recover_root_fetch(self, t: _SchedTask, st, cause) -> None:
        from presto_tpu.dist.dcn import DcnQueryFailed

        if getattr(cause, "task_error", False):
            # same [source-lost] handling as _poll_task: if the task
            # failed because an UPSTREAM spool vanished, replay that
            # node's placements first, or every re-dispatch of this
            # task would rebuild sources naming the same dead node
            msg = str(cause)
            if "[source-lost " in msg:
                src_uri = msg.split("[source-lost ", 1)[1].split()[0]
                if src_uri:
                    self._node_lost(src_uri)
            self._redispatch(t, cause=cause)
        else:
            # node death during the final drain: the dead node's
            # still-needed tasks (this one included) replay in topo
            # order; consumers long-poll the replacements
            self._node_lost(st.uri)
            if t.placement.uri == st.uri:
                # the lost task was already done and its stage had no
                # unfinished consumers tracked — force its own replay
                self._redispatch(t, cause=cause)
        if st.next_token and not self.coord._prefix_matches(
            t.placement.uri, t.placement.task_id, st,
            self._deadline()
        ):
            raise DcnQueryFailed(
                f"task {t.placement.task_id}: the replayed placement "
                f"regenerated a DIFFERENT page sequence for the "
                f"already-consumed prefix ({st.next_token} pages) — "
                f"non-deterministic fragment output; failing loudly "
                f"instead of silently skipping or duplicating rows"
            ) from cause
        st.uri = t.placement.uri
        st.task_id = t.placement.task_id
