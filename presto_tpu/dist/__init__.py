"""Distributed execution over a jax device Mesh.

Reference: presto-main's distribution stack — AddExchanges (distribution
choice), PlanFragmenter (stage cutting), PartitionedOutputOperator /
ExchangeOperator (HTTP shuffle). TPU-native redesign (SURVEY §3.3, §8.1.5):
the pod presents as ONE fat worker; pages are global jax.Arrays sharded
row-wise over the mesh; exchanges are XLA collectives compiled into the
stage programs (all_to_all repartition, all_gather broadcast/gather)
instead of serialized HTTP pages.
"""

from presto_tpu.dist.fragmenter import (
    Fragment,
    StageDag,
    add_exchanges,
    fragment_dag,
)
from presto_tpu.dist.executor import DistExecutor, make_mesh

__all__ = ["add_exchanges", "fragment_dag", "Fragment", "StageDag",
           "DistExecutor", "make_mesh"]
