"""Physical-plan fragment serde for DCN plan SHIPPING.

Reference: presto-main server/TaskUpdateRequest.java carries a
serialized PlanFragment (JSON via airlift/jackson of the PlanNode
tree); workers execute exactly the fragment the coordinator planned.
Until round 5 this engine replayed the SQL text on the worker and
re-took the same cut — planner nondeterminism or version skew between
coordinator and worker could silently diverge results. This module
closes that gap: the coordinator serializes the physical subtree it
wants executed and the worker executes THAT tree, byte-for-byte.

Encoding: every plan/expression/type object in this engine is a frozen
dataclass composed of tuples and scalars (exec/plan.py, expr/ir.py,
types.py, ops/sort.SortKey, ops/window.WindowFunc) — so one generic
tagged-JSON walker covers the whole IR with no per-node code:

    dataclass  -> {"$c": "ClassName", "fieldname": value, ...}
    tuple      -> {"$t": [items...]}
    bytes      -> {"$b": base64}
    Decimal    -> {"$d": str}
    non-finite -> {"$fl": "nan" | "inf" | "-inf"}
    None/bool/int/str/finite float -> JSON natives

The class registry is built from the IR modules' own dataclass
members; an unknown class name on decode is an error (version skew
surfaces loudly, never as silent divergence).
"""

from __future__ import annotations

import base64
import dataclasses
import decimal
import json
import math
from typing import Any, Dict


def _registry() -> Dict[str, type]:
    import presto_tpu.types as T
    from presto_tpu.exec import plan as P
    from presto_tpu.expr import ir as E
    from presto_tpu.ops import window as W
    from presto_tpu.ops.sort import SortKey

    reg: Dict[str, type] = {}
    for mod in (T, P, E):
        for name in dir(mod):
            cls = getattr(mod, name)
            if isinstance(cls, type) and dataclasses.is_dataclass(cls):
                reg[name] = cls
    reg["SortKey"] = SortKey
    reg["WindowFunc"] = W.WindowFunc
    return reg


_REG: Dict[str, type] = {}


def _reg() -> Dict[str, type]:
    global _REG
    if not _REG:
        _REG = _registry()
    return _REG


def to_obj(x: Any) -> Any:
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        name = type(x).__name__
        if name not in _reg():
            raise TypeError(f"unregistered plan class: {name}")
        out = {"$c": name}
        for f in dataclasses.fields(x):
            if not f.init:  # class-constant (e.g. SqlType.name)
                continue
            out[f.name] = to_obj(getattr(x, f.name))
        return out
    if isinstance(x, tuple):
        return {"$t": [to_obj(v) for v in x]}
    if isinstance(x, bytes):
        return {"$b": base64.b64encode(x).decode()}
    if isinstance(x, decimal.Decimal):
        return {"$d": str(x)}
    if isinstance(x, float) and not math.isfinite(x):
        return {"$fl": "nan" if math.isnan(x)
                else ("inf" if x > 0 else "-inf")}
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, list):
        return [to_obj(v) for v in x]
    raise TypeError(f"unserializable plan value: {type(x).__name__}")


def from_obj(x: Any) -> Any:
    if isinstance(x, dict):
        if "$c" in x:
            cls = _reg().get(x["$c"])
            if cls is None:
                raise TypeError(
                    f"unknown plan class {x['$c']!r} (coordinator/"
                    "worker version skew?)")
            kwargs = {k: from_obj(v) for k, v in x.items() if k != "$c"}
            return cls(**kwargs)
        if "$t" in x:
            return tuple(from_obj(v) for v in x["$t"])
        if "$b" in x:
            return base64.b64decode(x["$b"])
        if "$d" in x:
            return decimal.Decimal(x["$d"])
        if "$fl" in x:
            return float(x["$fl"])
        raise TypeError(f"unrecognized tagged object: {list(x)[:4]}")
    if isinstance(x, list):
        return [from_obj(v) for v in x]
    return x


def dumps(node: Any) -> str:
    return json.dumps(to_obj(node), separators=(",", ":"))


def loads(s: str) -> Any:
    return from_obj(json.loads(s))
