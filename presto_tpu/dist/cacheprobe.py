"""Fleet-shared fragment-cache lookup (ISSUE 19, layer b).

Reference: the exchange-client direction — a stage's input does not
care WHERE its pages come from, only that they arrive over the one
spool data plane. This module lets the DCN coordinator discover that
some fleet member already HOLDS a leaf fragment's result pages and
short-circuit the task: instead of dispatching the fragment for
execution, it posts one ``/v1/cache/task`` probe and, on a hit, the
worker parks the cached pages in a pre-finished task spool
(``TaskRuntime.register_finished_task`` — the ICI landing surface
from ISSUE 18), so the gather/consumer path replays them through the
EXISTING pooled spool-fetch plane with no new protocol.

Two pieces:

- ``fragment_cache_key``: the coordinator-side mirror of the key a
  worker's executor computes for a split leaf fragment — same
  SplitFilterConnector wrap (split identity IS part of the snapshot
  token), same cache/rules selection, same collect_k/page_rows salt.
  Any drift between this and the worker's ``_select_cache_points``
  shows up as a probe miss, never a wrong answer (the worker serves
  only what its OWN store holds under the exact key).

- ``RemoteCacheIndex``: per-worker bloom-style summaries of cached
  fragment keys, refreshed on the heartbeat plane (``/v1/info`` ships
  ``cacheSummary``; server/heartbeat.py feeds ``update_from_info``).
  A probe goes on the wire only when the bloom says "maybe" — the
  common miss costs ZERO round trips; a bloom false positive costs
  one pooled POST.
"""

from __future__ import annotations

import base64
import hashlib
from typing import Dict, Iterable, Optional

from presto_tpu.obs.sanitizer import make_lock, register_owner

# 1024 bits / 4 hashes: ~2% false-positive rate at 100 cached
# fragments per worker, 128 bytes per heartbeat — noise on the wire
_BLOOM_BITS = 1024
_BLOOM_HASHES = 4


def _bit_positions(key: str):
    h = hashlib.sha256(key.encode()).digest()
    for i in range(_BLOOM_HASHES):
        yield int.from_bytes(h[4 * i:4 * i + 4], "little") % _BLOOM_BITS


def bloom_summary(keys: Iterable[str]) -> str:
    """Base64 bloom filter over a worker's cached fragment keys — the
    ``cacheSummary`` field on /v1/info."""
    bits = bytearray(_BLOOM_BITS // 8)
    for k in keys:
        for pos in _bit_positions(k):
            bits[pos // 8] |= 1 << (pos % 8)
    return base64.b64encode(bytes(bits)).decode("ascii")


def _bloom_contains(bits: bytes, key: str) -> bool:
    return all(bits[pos // 8] & (1 << (pos % 8))
               for pos in _bit_positions(key))


def fragment_cache_key(root, catalogs, *, split_table: str,
                       split_index: int, split_count: int,
                       collect_k: int,
                       page_rows: int) -> Optional[str]:
    """The exact fragment-cache key a worker executing this leaf
    fragment's split would compute, or None when the fragment's ROOT
    is not itself a cache point (an interior-only point cannot
    short-circuit the whole task). Mirrors server/worker._run_task's
    catalog wrap + runner salt — see module docstring."""
    from presto_tpu.cache.rules import select_cache_points
    from presto_tpu.connectors.split_filter import SplitFilterConnector

    wrapped = {
        name: SplitFilterConnector(conn, split_table,
                                   split_index, split_count)
        for name, conn in catalogs.items()
    }
    for key, node, _tables, _snap, _fam in select_cache_points(
            root, wrapped).values():
        if node is root:
            return f"{key}:k{collect_k}.p{page_rows}"
    return None


class RemoteCacheIndex:
    """Coordinator-held map of worker uri -> bloom summary of that
    worker's cached fragment keys, refreshed by the heartbeat
    detector's /v1/info polls. No summary for a worker means "probe
    nothing there" — absence fails CLOSED to keep misses free."""

    # lock discipline (tools/lint `locks` rule): heartbeat threads
    # write summaries while scheduler dispatch threads read them
    _shared_attrs = ("_blooms",)

    def __init__(self):
        self._lock = make_lock("dist.cacheprobe.RemoteCacheIndex._lock")
        self._blooms: Dict[str, bytes] = {}
        register_owner(self)

    def update(self, uri: str, summary_b64: Optional[str]) -> None:
        try:
            bits = base64.b64decode(summary_b64) if summary_b64 else b""
        except (ValueError, TypeError):
            bits = b""
        with self._lock:
            if len(bits) == _BLOOM_BITS // 8:
                self._blooms[uri] = bits
            else:
                # a worker that stopped advertising (restarted with an
                # empty cache, or pre-ISSUE-19 peer) must stop
                # attracting probes
                self._blooms.pop(uri, None)

    def update_from_info(self, uri: str, info) -> None:
        """Heartbeat callback (server/heartbeat.py on_info): tolerant
        of pre-ISSUE-19 peers whose /v1/info has no cacheSummary."""
        summary = None
        if isinstance(info, dict):
            summary = info.get("cacheSummary")
        self.update(uri, summary)

    def might_contain(self, uri: str, key: str) -> bool:
        with self._lock:
            bits = self._blooms.get(uri)
        return bits is not None and _bloom_contains(bits, key)

    def known(self) -> bool:
        with self._lock:
            return bool(self._blooms)
