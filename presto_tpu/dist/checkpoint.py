"""Durable coordinator query-state journal + crash re-attach (ISSUE 20).

Reference: Presto's Project-Tardigrade fault-tolerant execution keeps
intermediate exchange data in an external spool so a failed node's
work is recoverable; the missing piece for COORDINATOR loss is a
durable record of what each in-flight query had already accomplished.
This engine's spool tier (PR 7) already survives the coordinator —
worker spools hold every completed stage's pages until task expiry —
so coordinator HA reduces to journaling three things at barriers the
engine already has:

  admission        statement, session props, resource group, query id
  stage barrier    fragment blob (plan_serde), task placements,
                   spool partition counts, re-plan generation
  final drain      per-task consumed spool tokens + sha256 prefix
                   digests; the client-protocol token + per-page
                   digests of everything already handed to the client

The journal rides the generation-numbered ManifestStore from
cache/persist.py (satellite 1): one record per query, O(1) appends at
each barrier, threshold compaction, loud-drop recovery — the SAME
tested manifest lifecycle as the result-cache warm tier. All file I/O
happens outside the registered locks (the store's drain loop).

On restart, ``PrestoTpuServer(checkpoint_dir=...)`` replays the
journal: RUNNING queries whose producer spools still answer
re-register final-stage suppliers straight from the persisted
placements (``reattach_query`` below) and the client's ``nextUri``
stream resumes at the persisted token after per-page digest
verification; dead placements re-dispatch from the persisted payloads
through the ordinary PR-5/PR-7 replay ladder; anything non-recoverable
re-runs from the persisted SQL, or surfaces FAILED with
``CoordinatorRestarted`` — loudly, never a hang, never duplicate or
missing rows.
"""

from __future__ import annotations

import hashlib
import json
import logging
import urllib.error
from typing import Dict, List, Optional

from presto_tpu.cache.persist import ManifestStore
from presto_tpu.obs.sanitizer import make_lock, register_owner

log = logging.getLogger("presto_tpu.dist")

CHECKPOINT_VERSION = 1
_STEM = "journal"


class CoordinatorRestarted(RuntimeError):
    """A query could not be carried across a coordinator restart: its
    spools are gone AND its statement was not re-runnable (or the
    resumed stream failed digest verification). Clients see this as a
    FAILED query with errorName CoordinatorRestarted — the loud
    alternative to a silent hang or a wrong row stream."""


def _serde_check(header: Dict) -> Optional[str]:
    from presto_tpu.dist.serde import wire_fingerprint

    if header.get("serde") != wire_fingerprint():
        return (f"serde fingerprint {header.get('serde')!r} != "
                f"{wire_fingerprint()!r}")
    return None


def page_digest(chunk: List) -> str:
    """Digest of ONE client-protocol page (a q.rows slice, already
    JSON-shaped). The restart path regenerates the rows and verifies
    every already-delivered page against these digests before letting
    the client's nextUri stream continue — byte-stable because
    json.dumps over JSON-shaped rows is deterministic."""
    return hashlib.sha256(
        json.dumps(chunk, separators=(",", ":")).encode()
    ).hexdigest()


class CheckpointJournal:
    """One coordinator's durable query journal: a ManifestStore of
    qid -> record, plus the in-memory mirror the barrier hooks mutate.
    Mutations happen under this journal's lock; the durable publish
    (store append / compaction) runs OUTSIDE it on the store's own
    drain loop."""

    _shared_attrs = ("_records",)

    def __init__(self, directory: str, counter_ex=None):
        from presto_tpu.dist.serde import wire_fingerprint

        self.directory = directory
        self._lock = make_lock(
            "dist.checkpoint.CheckpointJournal._lock")
        self._store = ManifestStore(
            directory, stem=_STEM, version=CHECKPOINT_VERSION,
            header_extra={"serde": wire_fingerprint()},
            header_check=_serde_check,
        )
        self._records: Dict[str, Dict] = dict(
            self._store.entries_snapshot())
        self.counter_ex = counter_ex
        if counter_ex is not None and self._store.broken_count:
            counter_ex.checkpoint_drops += self._store.broken_count
        for why in self._store.broken_reasons:
            log.warning("checkpoint journal %s: %s", directory, why)
        register_owner(self)

    # ------------------------------------------------------ lifecycle
    def admit(self, qid: str, sql: str, session_props: Dict,
              group: Optional[str]) -> "QueryCheckpoint":
        rec = {
            "state": "admitted",
            "sql": sql,
            "session": dict(session_props or {}),
            "group": group,
            "token": 0,
            "page_sha": {},
            "stages": {},
            "drain": {},
        }
        with self._lock:
            self._records[qid] = rec
            snap = json.loads(json.dumps(rec))
        self._publish_rec(qid, snap)
        return QueryCheckpoint(self, qid)

    def pending(self) -> Dict[str, Dict]:
        """Every journaled query a restarted coordinator must pick up
        (delivered queries were removed at stream completion)."""
        with self._lock:
            return {q: dict(r) for q, r in self._records.items()}

    def claim_reattach(self) -> bool:
        """True exactly once per journal directory + process — the
        re-attach pass must not run twice on one boot."""
        return self._store.claim_once("reattach")

    # ------------------------------------------------------ internals
    def _mutate(self, qid: str, fn) -> Optional[Dict]:
        """Apply ``fn(record)`` under the lock; returns a snapshot for
        publishing (None when the query is unknown/detached)."""
        with self._lock:
            rec = self._records.get(qid)
            if rec is None:
                return None
            fn(rec)  # concheck: blocking-ok - every mutator is a
            # tiny dict update closure from QueryCheckpoint (no I/O,
            # no device work); the durable publish runs after the
            # lock is released
            return json.loads(json.dumps(rec))  # deep, JSON-safe copy

    def _publish_rec(self, qid: str, snapshot: Dict) -> None:
        self._store.publish(qid, snapshot)
        ex = self.counter_ex
        if ex is not None:
            ex.checkpoints_written += 1
            tr = getattr(ex, "trace", None)
            if tr is not None:
                now = tr.now()
                tr.complete(
                    "checkpoint", qid, now, now,
                    state=snapshot.get("state"),
                    bytes=len(json.dumps(snapshot)))
                ex.trace_spans += 1

    def _remove(self, qid: str) -> None:
        with self._lock:
            self._records.pop(qid, None)
        self._store.remove([qid])


class QueryCheckpoint:
    """Per-query handle the server/scheduler barriers write through.
    ``detach()`` voids it — a superseded coordinator's parked threads
    can never corrupt the journal a successor owns."""

    def __init__(self, journal: CheckpointJournal, qid: str):
        self.journal: Optional[CheckpointJournal] = journal
        self.qid = qid

    def detach(self) -> None:
        self.journal = None

    def _apply(self, fn) -> None:
        j = self.journal
        if j is None:
            return
        snap = j._mutate(self.qid, fn)
        if snap is not None:
            j._publish_rec(self.qid, snap)

    # ----------------------------------------------------- barriers
    def running(self) -> None:
        self._apply(lambda r: r.__setitem__("state", "running"))

    def record_stage(self, fid: int, key: str, parts: int,
                     tasks: List[Dict], replan_gen: int) -> None:
        """One spooled-stage boundary: every task's placement + the
        full re-dispatchable payload (fragment blob included — the
        restart path can re-POST it verbatim)."""
        def mut(r):
            r["stages"][str(fid)] = {
                "key": key, "parts": int(parts),
                "replan_gen": int(replan_gen), "tasks": tasks,
            }
        self._apply(mut)

    def record_root(self, root_blob: Optional[str],
                    root_inputs: List[int]) -> None:
        """Final-stage registration: the coordinator-side root
        fragment (plan_serde blob) + which stages feed it."""
        def mut(r):
            if root_blob is not None:
                r["root"] = root_blob
            r["root_inputs"] = [int(f) for f in root_inputs]
        self._apply(mut)

    def record_drain(self, fid: int, index: int, next_token: int,
                     sha: str) -> None:
        """Consumed-spool progress for one final-stage task: tokens +
        rolling sha256 of the consumed prefix (diagnostics + the
        ROOFLINE cost model; resume correctness rides the client-page
        digests, not these)."""
        def mut(r):
            r["drain"].setdefault(str(fid), {})[str(index)] = {
                "next_token": int(next_token), "sha": sha}
        self._apply(mut)

    def note_client_token(self, token: int, sha: str) -> None:
        """The client consumed protocol page ``token - 1`` (its next
        fetch names ``token``): the restart path replays the stream
        from here after verifying each already-delivered page's
        digest."""
        def mut(r):
            r["token"] = int(token)
            r["page_sha"][str(token - 1)] = sha
        self._apply(mut)

    def finished(self, columns: List[Dict], nrows: int) -> None:
        def mut(r):
            r["state"] = "finished"
            r["columns"] = columns
            r["nrows"] = int(nrows)
        self._apply(mut)

    def failed(self, message: str, error_name: str = "") -> None:
        def mut(r):
            r["state"] = "failed"
            r["error"] = {"message": str(message)[:2000],
                          "errorName": error_name or "QueryFailed"}
        self._apply(mut)

    def delivered(self) -> None:
        """The client drained the whole stream: nothing left to
        recover — drop the record (journal size governance)."""
        j = self.journal
        if j is not None:
            j._remove(self.qid)


# ---------------------------------------------------------------------
# restart-side recovery


class ReattachResult:
    def __init__(self, column_names, rows, resumed: bool,
                 redispatches: int):
        self.column_names = list(column_names or [])
        self.rows = rows
        # True when the spooled fast path served (zero producer
        # re-launches beyond counted re-dispatches); False when the
        # statement re-ran from SQL
        self.resumed = resumed
        self.redispatches = redispatches


def _spool_alive(uri: str, task_id: str) -> bool:
    """Does this persisted placement's spool still answer? FINISHED is
    the only state a checkpointed producer can legitimately be in —
    anything else (FAILED, RELEASED, unreachable, restarted worker
    that forgot the task) reads as dead."""
    from presto_tpu.dist import connpool as CONNPOOL

    try:
        with CONNPOOL.request(f"{uri}/v1/task/{task_id}",
                              timeout=5) as r:
            return json.loads(
                r.read().decode()).get("state") == "FINISHED"
    except (urllib.error.URLError, ConnectionError, OSError,
            ValueError):
        return False


def _redispatch_dead(rec: Dict, dcn, ex) -> int:
    """Probe every final-stage placement; re-POST the persisted
    payload for dead ones onto the live pool (new ``.ra<n>`` task id —
    the worker regenerates the fragment deterministically, the PR-5
    contract). Mutates rec's task dicts in place so the suppliers read
    the replacement placements. Raises on an unrecoverable pool."""
    from presto_tpu.dist.dcn import DcnQueryFailed

    pool = dcn._alive_for_submit()
    if not pool:
        raise DcnQueryFailed(
            f"re-attach: no ALIVE workers among {dcn.worker_uris}")
    n = 0
    for fid in rec.get("root_inputs", []):
        stage = rec["stages"].get(str(fid))
        if stage is None:
            raise DcnQueryFailed(
                f"re-attach: stage {fid} never checkpointed")
        for t in stage["tasks"]:
            if _spool_alive(t["uri"], t["task_id"]):
                continue
            n += 1
            base = t["task_id"].split(".r", 1)[0].split(".ra", 1)[0]
            new_id = f"{base}.ra{n}"
            payload = dict(t["payload"], taskId=new_id)
            target = pool[n % len(pool)]
            dcn._post_task(target, payload)
            t["uri"], t["task_id"], t["payload"] = \
                target, new_id, payload
            ex.count_reattach_redispatch()
    return n


def _persisted_supplier(stage: Dict, dcn, deadline, retry_attempts,
                        pool):
    """A final-stage supplier built from PERSISTED placements — the
    restart-side twin of StageScheduler._root_supplier, riding the
    same token-acked fetch + replay ladder (_fetch_pages /
    _recover_task)."""
    from presto_tpu.dist.dcn import (DcnQueryFailed, _TaskLost,
                                     _TaskState)

    def supplier():
        for t in stage["tasks"]:
            st = _TaskState(uri=t["uri"], task_id=t["task_id"],
                            payload=t["payload"])
            while True:
                try:
                    yield from dcn._fetch_pages(st, deadline)
                    break
                except _TaskLost as e:
                    if retry_attempts <= 0:
                        raise DcnQueryFailed(str(e)) from e
                    dcn._recover_task(st, pool, retry_attempts,
                                      deadline, e)

    return supplier


def reattach_query(rec: Dict, dcn, ex) -> ReattachResult:
    """Recover one journaled query on a restarted coordinator.

    Ladder: (1) spooled fast path — the persisted root fragment
    re-executes against suppliers reading the SURVIVING producer
    spools (dead placements re-dispatched from persisted payloads,
    counted); (2) full re-run of the persisted SQL through the normal
    dispatch planes; (3) CoordinatorRestarted, loudly. A successful
    recovery (either path) counts ``coordinator_reattaches``."""
    from presto_tpu.dist import plan_serde
    from presto_tpu.dist.fragmenter import stage_key

    root_blob = rec.get("root")
    root_inputs = rec.get("root_inputs") or []
    redis = 0
    if (dcn is not None and root_blob and root_inputs
            and all(str(f) in rec.get("stages", {})
                    for f in root_inputs)):
        keys: List[str] = []
        try:
            root = plan_serde.loads(root_blob)
            redis = _redispatch_dead(rec, dcn, ex)
            dcn.runner.apply_session()
            deadline = ex.query_deadline
            retry_attempts = dcn._retry_attempts()
            pool = dcn._alive_for_submit() or list(dcn.worker_uris)
            try:
                for fid in root_inputs:
                    k = stage_key(fid)
                    keys.append(k)
                    ex.remote_sources[k] = _persisted_supplier(
                        rec["stages"][str(fid)], dcn, deadline,
                        retry_attempts, pool)
                names, rows = ex.execute(root)
                ex.count_reattach()
                return ReattachResult(names, rows, True, redis)
            finally:
                for k in keys:
                    ex.remote_sources.pop(k, None)
                # spools die with the query, exactly as the
                # scheduler's own finally would have released them
                for stage in rec.get("stages", {}).values():
                    for t in stage["tasks"]:
                        dcn._release_task(t["uri"], t["task_id"])
        except Exception as e:  # noqa: BLE001 - recovery ladder:
            # the fast path's failure reason is logged, then the
            # statement re-runs from SQL below (rung 2); only a
            # missing statement makes this terminal
            log.warning("re-attach fast path failed (%r) — "
                        "re-running statement", e)
    sql = rec.get("sql")
    if sql:
        if dcn is not None:
            rows = dcn.execute(sql)
            names = dcn.last_output_names
        else:
            raise CoordinatorRestarted(
                "re-attach: no dispatch plane to re-run on")
        ex.count_reattach()
        return ReattachResult(names, rows, False, redis)
    raise CoordinatorRestarted(
        "query state was not recoverable after a coordinator "
        "restart: producer spools gone and no re-runnable statement "
        "in the journal")
