"""Spooled-exchange data plane helpers for the stage-DAG scheduler.

Reference: presto-main operator/PartitionedOutputOperator.java (the
producer half of a hash-repartition exchange: route each row to a
partition buffer by hash(keys) % P) and operator/ExchangeClient.java
(the consumer half: token-acked page fetch from every producer task).
The Project-Tardigrade twist: partition buffers are SPOOLED — they
outlive the producing task's execution on the worker (PageStore
host/disk tiers, exec/pagestore.py), so a lost downstream task replays
from its upstream spools instead of failing the query.

Two partitioning tiers (ISSUE 13). The HOST tier below is numpy on
already-device_get pages: the split happens at the serialization
boundary where the page has left the device anyway (SURVEY §6.8: HTTP
shapes survive only at the pod boundary). The DEVICE tier
(`device_partition_pages`) computes the SAME splitmix64 value-hash as
a jitted kernel and compacts each partition to a ladder-bucket
capacity on device — pages never cross to host at the exchange, and
the worker spool holds device Pages that materialize to host bytes
LAZILY (`spool_blob`) only when a replay or a DCN-remote consumer
actually fetches over HTTP. A Pallas partition-id variant engages only
on explicit pallas_join_enabled=true (session-distributed, so every
producer of one exchange resolves it identically — a per-process
backend auto-probe could disagree across a mixed pool). Parity between
the tiers is test-pinned per key type incl. the NULL sentinel
(tests/test_device_exchange.py); skew still rides the boosted-retry
ladder — a partition overflowing its bucket raises the deferred flag.

Client split (deliberate, not drift): `fetch_spool_blobs` below is the
WORKER-side exchange client — plain token-dedupe fetch between stage
tasks. The COORDINATOR's drain of final stages keeps using
`dcn.DcnRunner._fetch_pages`, which layers the PR-5 resume machinery
(rolling sha256 of consumed bytes + byte-identical prefix verification
after a replay) that worker-to-worker ingest does not need — a
re-dispatched consumer restarts its stream from token 0. Both speak
the same `/v1/task/{id}/results/{token}?part=p` protocol.

Hash discipline: partitioning needs only SELF-consistency across the
two sides of one exchange (co-partitioned join sides / all producers
of one aggregation exchange), not agreement with the device kernels'
hash. Keys hash from VALUE encodings — int64 bit-views, IEEE-754
bit-views with -0.0/NaN normalization, dictionary VALUES (not codes) —
mixed with a splitmix64 finalizer and the reference's 31*h+x combiner,
so equal SQL values land in the same partition regardless of which
producer task emitted them. NULL keys hash to a fixed sentinel (every
null row lands on a deterministic partition — inner join keys never
match NULL, and NULL group keys co-locate).
"""

from __future__ import annotations

import functools
import json
import struct
import time
import urllib.error
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.exec import shapes as SH
from presto_tpu.exec import xfer as XF
from presto_tpu.ops.compact import compact_indices, scatter_column
from presto_tpu.ops.hashing import xxhash64_host
from presto_tpu.page import Block, Page

_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_NULL_SENTINEL = np.uint64(0x9E3779B185EBCA87)
_NAN_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)
_C31 = np.uint64(31)


def _mix64(h: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (vectorized, natural uint64 wraparound)."""
    with np.errstate(over="ignore"):
        h = (h ^ (h >> np.uint64(30))) * _MIX1
        h = (h ^ (h >> np.uint64(27))) * _MIX2
        return h ^ (h >> np.uint64(31))


@functools.lru_cache(maxsize=64)
def _dict_value_hashes(dictionary) -> np.ndarray:
    """Per-code value hashes of one Dictionary, memoized — dictionaries
    are shared across every page of a scan, and Dictionary hashes by
    CONTENT, so the Python-level hashing loop runs once per distinct
    dictionary instead of once per page per key channel."""
    return np.array(
        [xxhash64_host(repr(v).encode()) for v in dictionary.values],
        dtype=np.uint64,
    )


def _block_value_u64(blk: Block) -> np.ndarray:
    """Per-row uint64 VALUE encoding of one key block (host numpy)."""
    data = blk.data
    if isinstance(data, tuple):
        # long decimal (hi, lo): combine the two words
        arrs = [XF.np_host(d) for d in data]
        if any(a.ndim != 1 for a in arrs):
            raise TypeError(
                "collect-state blocks cannot be exchange partition keys"
            )
        h = np.zeros(arrs[0].shape[0], dtype=np.uint64)
        with np.errstate(over="ignore"):
            for a in arrs:
                h = h * _C31 + a.astype(np.int64).view(np.uint64)
        return h
    arr = XF.np_host(data)
    if blk.dictionary is not None:
        # hash the dictionary VALUES, not the table-local codes —
        # producer tasks with different dictionaries stay consistent
        vh = _dict_value_hashes(blk.dictionary)
        if len(vh) == 0:
            return np.zeros(arr.shape[0], dtype=np.uint64)
        codes = np.clip(arr.astype(np.int64), 0, len(vh) - 1)
        return vh[codes]
    if arr.dtype == np.bool_:
        return arr.astype(np.uint64)
    if np.issubdtype(arr.dtype, np.floating):
        f = arr.astype(np.float64)
        f = np.where(f == 0.0, 0.0, f)  # -0.0 == +0.0 (SQL equality)
        bits = f.view(np.uint64)
        return np.where(np.isnan(f), _NAN_KEY, bits)
    return arr.astype(np.int64).view(np.uint64)


def row_hash_u64(page: Page, keys: Sequence[int]) -> np.ndarray:
    """Per-row partition hash over the key channels (31*h + mix(col),
    the reference's CombineHashFunction shape over splitmix-dispersed
    column encodings)."""
    cap = XF.np_host(page.valid).shape[0]
    h = np.zeros(cap, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for k in keys:
            blk = page.block(k)
            col = _mix64(_block_value_u64(blk))
            if blk.nulls is not None:
                col = np.where(XF.np_host(blk.nulls), _NULL_SENTINEL,
                               col)
            h = h * _C31 + col
    return _mix64(h)


def take_rows_host(page: Page, idx: np.ndarray) -> Page:
    """Compact the given row indices of a HOST page into a fresh page
    whose capacity sits on the shapes.py bucket ladder (restreamed
    exchange pages must not mint off-ladder program shapes
    downstream)."""
    n = len(idx)
    cap = SH.bucket(max(n, 1))
    pad = np.zeros(cap, dtype=np.int64)
    pad[:n] = idx
    blocks: List[Block] = []
    for blk in page.blocks:
        if isinstance(blk.data, tuple):
            data = tuple(XF.np_host(d)[pad] for d in blk.data)
        else:
            data = XF.np_host(blk.data)[pad]
        nulls = (XF.np_host(blk.nulls)[pad]
                 if blk.nulls is not None else None)
        blocks.append(Block(data=data, type=blk.type, nulls=nulls,
                            dictionary=blk.dictionary))
    valid = np.zeros(cap, dtype=bool)
    valid[:n] = True
    return Page(blocks=tuple(blocks), valid=valid)


def partition_host_page(
    page: Page, keys: Sequence[int], nparts: int
) -> List[Tuple[int, Page]]:
    """Split one host page into per-partition compacted pages.
    Partitions with zero rows are skipped (deterministically — replay
    regenerates the same skips, so token sequences stay stable)."""
    valid = XF.np_host(page.valid)
    if nparts <= 1:
        return [(0, page)] if valid.any() else []
    part = (row_hash_u64(page, keys) % np.uint64(nparts)).astype(
        np.int64)
    out: List[Tuple[int, Page]] = []
    for p in range(nparts):
        idx = np.nonzero(valid & (part == p))[0]
        if len(idx):
            out.append((p, take_rows_host(page, idx)))
    return out


# ------------------------------------------------- device partitioning
def _mix64_dev(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer traced in jnp — bit-identical to the host
    `_mix64` (uint64 multiplies wrap in XLA exactly like numpy's)."""
    h = (h ^ (h >> jnp.uint64(30))) * jnp.uint64(_MIX1)
    h = (h ^ (h >> jnp.uint64(27))) * jnp.uint64(_MIX2)
    return h ^ (h >> jnp.uint64(31))


def _block_value_u64_dev(blk: Block, vh) -> jnp.ndarray:
    """Traced mirror of `_block_value_u64`: per-row uint64 VALUE
    encoding of one key block. `vh` is the block's staged dictionary
    value-hash LUT (device uint64 array) or None."""
    data = blk.data
    if isinstance(data, tuple):
        # long decimal (hi, lo): int64 -> uint64 astype wraps two's-
        # complement, the same bits .view reinterprets on the host
        h = jnp.zeros(data[0].shape[0], dtype=jnp.uint64)
        for a in data:
            h = h * jnp.uint64(_C31) + a.astype(jnp.int64).astype(
                jnp.uint64)
        return h
    if vh is not None:
        if vh.shape[0] == 0:
            return jnp.zeros(data.shape[0], dtype=jnp.uint64)
        codes = jnp.clip(data.astype(jnp.int64), 0, vh.shape[0] - 1)
        return vh[codes]
    if data.dtype == jnp.bool_:
        return data.astype(jnp.uint64)
    if jnp.issubdtype(data.dtype, jnp.floating):
        f = data.astype(jnp.float64)
        f = jnp.where(f == 0.0, 0.0, f)  # -0.0 == +0.0 (SQL equality)
        bits = jax.lax.bitcast_convert_type(f, jnp.uint64)
        return jnp.where(jnp.isnan(f), jnp.uint64(_NAN_KEY), bits)
    return data.astype(jnp.int64).astype(jnp.uint64)


def device_row_hash_u64(page: Page, keys: Sequence[int],
                        dict_luts=()) -> jnp.ndarray:
    """Traced mirror of `row_hash_u64`: 31*h + mix(col) over splitmix-
    dispersed column encodings, NULL keys to the fixed sentinel.
    `dict_luts` aligns with `keys` (device LUT or None per key)."""
    luts = tuple(dict_luts) or (None,) * len(keys)
    h = jnp.zeros(page.valid.shape[0], dtype=jnp.uint64)
    for k, vh in zip(keys, luts):
        blk = page.block(k)
        col = _mix64_dev(_block_value_u64_dev(blk, vh))
        if blk.nulls is not None:
            col = jnp.where(blk.nulls, jnp.uint64(_NULL_SENTINEL), col)
        h = h * jnp.uint64(_C31) + col
    return _mix64_dev(h)


def _pallas_part_ids(page: Page, keys: Sequence[int], dict_luts,
                     nparts: int, *, interpret: bool) -> jnp.ndarray:
    """Pallas partition-id variant: the 64-bit value encodings split
    into 32-bit words (Mosaic has no uint64 lanes — the pallas_join
    discipline) and mix through the fmix32 finalizer inside one VPU
    kernel. NOT hash-compatible with the splitmix64 tier — partition
    routing needs only SELF-consistency across one exchange's
    producers, which is why the gate is the session-distributed
    pallas_join_enabled=true, never a per-process backend probe."""
    from jax.experimental import pallas as pl

    from presto_tpu.ops.pallas_join import _mix32, _split64

    luts = tuple(dict_luts) or (None,) * len(keys)
    los, his = [], []
    for k, vh in zip(keys, luts):
        blk = page.block(k)
        enc = _block_value_u64_dev(blk, vh)
        if blk.nulls is not None:
            enc = jnp.where(blk.nulls, jnp.uint64(_NULL_SENTINEL), enc)
        lo, hi = _split64(enc)
        los.append(lo)
        his.append(hi)
    lo2 = jnp.stack(los)  # [C, N] int32
    hi2 = jnp.stack(his)

    def kernel(lo_ref, hi_ref, out_ref):
        acc = jnp.zeros(lo_ref.shape[1:], dtype=jnp.uint32)
        for c in range(lo_ref.shape[0]):
            acc = acc * jnp.uint32(31) + _mix32(lo_ref[c], hi_ref[c])
        acc = _mix32(acc.astype(jnp.int32),
                     jnp.zeros_like(acc).astype(jnp.int32))
        out_ref[...] = (acc % jnp.uint32(nparts)).astype(jnp.int32)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(lo2.shape[1:], jnp.int32),
        interpret=interpret,
    )(lo2, hi2)


def device_partition_pages(
    ex, page: Page, keys: Sequence[int], nparts: int,
    with_counts: bool = False,
) -> List[Tuple[int, Page]]:
    """Device-tier `partition_host_page`: ONE jitted program computes
    every partition assignment and compacts all `nparts` output pages
    to their ladder-bucket capacity without the page ever crossing to
    host (ISSUE 13 — the ROOFLINE §11 d2h/h2d exchange pair deletes).
    Every partition is emitted (empties carry all-False validity) so a
    replayed task regenerates an identical page sequence. The
    OR-reduced per-partition overflow flag joins the executor's
    deferred ladder: skew degrades to a boosted retry, exactly like
    the host tier's take_rows_host bucket.

    ``with_counts=True`` (the spool-stats plane, ISSUE 15) also
    returns the exact per-partition row counts — computed INSIDE the
    same program (the compaction already counts them) and pulled as
    one nparts-long vector through the metered choke point, so the
    stats cost is a handful of d2h bytes per page, never a second
    kernel or a whole-mask pull. Return shape then is
    ``(pairs, counts_np)``."""
    cap_in = page.valid.shape[0]
    if nparts <= 1:
        if with_counts:
            v = page.valid
            n = (int(XF.np_host(page.num_rows(), label="spool-stats"))
                 if isinstance(v, jax.Array)
                 else int(XF.np_host(v).sum()))
            return [(0, page)], np.asarray([n], dtype=np.int64)
        return [(0, page)]
    # host-resident input (a cache replay at the fragment root) stages
    # through the metered choke point; device pages pass through free
    page = XF.to_device(page, label="spool-stage")
    dicts = tuple(page.block(k).dictionary for k in keys)
    luts = tuple(
        XF.to_device(_dict_value_hashes(d), label="dict-hash")
        if d is not None else None
        for d in dicts
    )
    boost = ex._capacity_boost
    cap = SH.exchange_partition_cap(cap_in, nparts, boost)
    use_pallas = ex._pallas_exchange_on()
    if use_pallas:
        ex.pallas_kernels_used += 1

    def body(pg: Page, *vhs):
        vh_by_key = iter(vhs)
        full = tuple(next(vh_by_key) if d is not None else None
                     for d in dicts)
        if use_pallas:
            part = _pallas_part_ids(
                pg, keys, full, nparts,
                interpret=jax.default_backend() != "tpu")
        else:
            h = device_row_hash_u64(pg, keys, full)
            part = (h % jnp.uint64(nparts)).astype(jnp.int32)
        outs = []
        nums = []
        overflow = jnp.asarray(False)
        for p in range(nparts):
            mask = pg.valid & (part == p)
            targets, out_valid, num = compact_indices(mask, cap)
            blocks = []
            for blk in pg.blocks:
                if isinstance(blk.data, tuple):
                    data = tuple(scatter_column(d, targets, cap)
                                 for d in blk.data)
                else:
                    data = scatter_column(blk.data, targets, cap)
                nulls = (scatter_column(blk.nulls, targets, cap)
                         if blk.nulls is not None else None)
                blocks.append(blk.with_data(data, nulls=nulls))
            outs.append(Page(blocks=tuple(blocks), valid=out_valid))
            nums.append(num)
            overflow = overflow | (num > cap)
        if with_counts:
            return tuple(outs), jnp.stack(nums), overflow
        return tuple(outs), overflow

    fn = ex._jit(
        ("dev_repart", tuple(keys), nparts, cap, cap_in, dicts,
         use_pallas, with_counts),
        body,
    )
    out = fn(page, *[v for v in luts if v is not None])
    if with_counts:
        outs, nums, overflow = out
        ex._pending_overflow.append(overflow)
        counts = XF.np_host(nums, label="spool-stats").astype(np.int64)
        # counts are EXACT published rows: an overflowing partition
        # never publishes (the deferred flag re-runs the attempt and
        # on_attempt resets the spool), so clamping to the landing cap
        # only guards the transient pre-retry value
        return list(enumerate(outs)), np.minimum(counts, cap)
    outs, overflow = out
    ex._pending_overflow.append(overflow)
    return list(enumerate(outs))


def spool_blob(page: Page) -> bytes:
    """Materialize one spooled page to wire bytes — THE lazy host
    materialization of the device-resident spool tier. Called only
    when host bytes are actually needed (an HTTP fetch from a
    DCN-remote consumer or a replay, or spool budget demotion); the
    d2h is metered at the choke point. Deterministic, so a re-fetch
    or a replayed prefix serializes byte-identically."""
    from presto_tpu.dist import serde

    return serde.serialize_page(XF.to_host(page, label="spool-blob"))


# ------------------------------------------------------------ client
class SourceTaskFailed(RuntimeError):
    """The upstream task itself failed (X-Task-Error): deterministic,
    re-dispatching the CONSUMER alone will not help."""


class SourceLost(RuntimeError):
    """An upstream task's spool is unreachable (node death): the
    scheduler must replay the upstream task before the consumer can
    make progress. The message carries the placement for diagnosis."""

    def __init__(self, uri: str, task_id: str, cause: str):
        super().__init__(
            f"[source-lost {uri} {task_id}] {cause}")
        self.uri = uri
        self.task_id = task_id


# the default bounded in-flight-bytes window for one streaming fetch
# response (ISSUE 16): the server packs consecutive page frames into
# one response only up to this many bytes, and the client decodes
# frame-at-a-time off the socket — so consumer host memory per edge
# stays O(window), not O(partition), while fetch overlaps decode.
FETCH_WINDOW_BYTES = 4 << 20


def pack_frames(blobs: Sequence[bytes]) -> bytes:
    """Server-side framing of a streamed results response: each page
    blob rides as `<q len | bytes>` so the consumer can decode pages
    incrementally off the socket (dedupe-by-token still holds — the
    token advances one per frame on both ends)."""
    out = bytearray()
    for b in blobs:
        out.extend(struct.pack("<q", len(b)))
        out.extend(b)
    return bytes(out)


def _read_exact(r, n: int, *, eof_ok: bool = False) -> Optional[bytes]:
    """Read exactly n bytes from a response; None at a clean EOF when
    `eof_ok` (frame boundary). A mid-frame EOF raises ConnectionError
    — the transport-retry ladders treat it like any broken fetch."""
    chunks = []
    got = 0
    while got < n:
        c = r.read(n - got)
        if not c:
            if eof_ok and got == 0:
                return None
            raise ConnectionError(
                f"truncated page frame: got {got} of {n} bytes")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def iter_response_frames(r) -> Iterator[bytes]:
    """Incremental client half of `pack_frames`: yield each page blob
    as it comes off the socket, holding at most ONE frame in memory."""
    while True:
        head = _read_exact(r, 8, eof_ok=True)
        if head is None:
            return
        (ln,) = struct.unpack("<q", head)
        if ln < 0:
            raise ConnectionError(f"corrupt page-frame length {ln}")
        yield _read_exact(r, ln)


def fetch_spool_blobs(
    uri: str,
    task_id: str,
    part: int,
    *,
    start_token: int = 0,
    retries: int = 3,
    backoff_s: float = 0.1,
    timeout: float = 60.0,
    deadline: Optional[float] = None,
    window_bytes: Optional[int] = None,
) -> Iterator[bytes]:
    """Token-acked streaming fetch of one spool partition
    (at-least-once + dedupe-by-token, the HttpPageBufferClient
    protocol with the partition dimension added). Each request drains
    up to `window_bytes` of consecutive page frames on a pooled
    keep-alive connection (dist/connpool.py); the token advances one
    per yielded frame, so a mid-stream transport failure resumes at
    the first unconsumed page. Raises SourceTaskFailed on
    X-Task-Error, SourceLost after bounded transport retries."""
    from presto_tpu.dist import connpool as CONNPOOL

    token = start_token
    window = FETCH_WINDOW_BYTES if window_bytes is None \
        else int(window_bytes)
    while True:
        attempt = 0
        while True:
            if deadline is not None and time.monotonic() > deadline:
                from presto_tpu.exec.executor import (
                    QueryDeadlineExceeded,
                )

                raise QueryDeadlineExceeded(
                    "query exceeded query_max_run_time in a spool "
                    "fetch"
                )
            try:
                with CONNPOOL.request(
                    f"{uri}/v1/task/{task_id}/results/{token}"
                    f"?part={part}&max={window}", timeout=timeout,
                ) as r:
                    if r.status == 204:
                        if r.headers.get("X-Done") == "1":
                            return
                        break  # long-poll timeout; re-ask same token
                    for body in iter_response_frames(r):
                        token += 1
                        yield body
                    break
            except urllib.error.HTTPError as e:
                if e.headers.get("X-Task-Error"):
                    try:
                        msg = json.loads(e.read().decode()).get(
                            "error", "")
                    except (ValueError, OSError):
                        msg = str(e)
                    raise SourceTaskFailed(
                        f"upstream task {task_id} on {uri} FAILED: "
                        f"{msg}"
                    ) from e
                if e.code == 410:
                    # the partition was acked/released: deterministic
                    # and permanent — retrying or replaying the
                    # (healthy) producer node would not bring the
                    # spool back
                    raise SourceTaskFailed(
                        f"spool partition {part} of task {task_id} on "
                        f"{uri} was already released (acked) — the "
                        f"scheduler consumed it before this fetch"
                    ) from e
                attempt += 1
                if attempt > retries:
                    raise SourceLost(uri, task_id, str(e)) from e
                time.sleep(backoff_s * attempt)
            except (urllib.error.URLError, ConnectionError,
                    OSError) as e:
                attempt += 1
                if attempt > retries:
                    raise SourceLost(uri, task_id, str(e)) from e
                time.sleep(backoff_s * attempt)


def local_source_pages(uri: str, task_id: str,
                       part: int) -> Optional[Iterator[Page]]:
    """Mesh-local exchange fast path (ISSUE 13): when `uri` names a
    task runtime in THIS process and the task has finished, return an
    iterator over its spooled partition Pages — no HTTP, no serde for
    lazy entries, and no h2d re-stage for device-resident spools.
    None = not local (or not yet done): the caller falls back to the
    metered HTTP fetch, which also provides the long-poll wait and
    the fault-injection surface.

    Race discipline: the released/done checks AND the entry-list
    snapshot happen under the task lock, so a concurrent ack/release
    can never yield a silently-empty stream (the HTTP path's 410
    contract); pages then materialize ONE AT A TIME outside the lock
    — blob entries whose store was closed mid-iteration raise
    SourceTaskFailed loudly, and lazy Page entries stay valid by
    reference regardless of release."""
    from presto_tpu.server.worker import local_runtime

    rt = local_runtime(uri)
    if rt is None:
        return None
    task = rt.get_task(task_id)
    if task is None:
        return None
    with task.lock:
        done, err = task.done, task.error
        spool = task.spool
        released = task.part_released(part)
        entries = (
            list(spool.parts[part]._entries)
            if (done and not err and not released and spool is not None
                and part < len(spool.parts))
            else []
        )
    if err:
        raise SourceTaskFailed(
            f"upstream task {task_id} on {uri} FAILED: {err}")
    if released:
        raise SourceTaskFailed(
            f"spool partition {part} of task {task_id} on {uri} was "
            f"already released (acked) — the scheduler consumed it "
            f"before this fetch")
    if not done or spool is None:
        return None

    def gen() -> Iterator[Page]:
        from presto_tpu.dist import serde

        for entry in entries:
            if entry[0] == "page":
                yield entry[1]
                continue
            store, i = entry
            try:
                blob = store.blob_at(i)
            except (OSError, IndexError) as e:
                raise SourceTaskFailed(
                    f"spool partition {part} of task {task_id} on "
                    f"{uri} was released (acked) during a mesh-local "
                    f"read") from e
            yield serde.deserialize_page(blob)

    return gen()


def iter_source_pages(
    spec: dict,
    *,
    retries: int = 3,
    backoff_s: float = 0.1,
    deadline: Optional[float] = None,
    on_local=None,
):
    """Worker-side exchange ingest: yield deserialized pages of one
    RemoteSource edge — partition `spec['partition']` of every
    producer task, in payload order (deterministic, so a re-dispatched
    consumer regenerates an identical stream from identical spools).
    Same-process producers serve their spooled Pages directly
    (`local_source_pages`; `on_local` fires once per edge task so the
    consumer's executor can count mesh_local_exchanges).

    An adaptive BROADCAST READ of a repartitioned spool (ISSUE 15)
    passes ``spec['partitions']`` — an explicit partition list; the
    consumer drains every listed partition of every producer task
    (their union is the full producer output, so a join build flipped
    to broadcast after its producer already spooled P hash partitions
    reads exactly the rows a broadcast spool would have held)."""
    from presto_tpu.dist import serde

    parts = [int(p) for p in (spec.get("partitions")
                              or (spec.get("partition", 0),))]
    for t in spec["tasks"]:
        for part in parts:
            pages = local_source_pages(t["uri"], t["taskId"], part)
            if pages is not None:
                if on_local is not None:
                    on_local()
                yield from pages
                continue
            for blob in fetch_spool_blobs(
                t["uri"], t["taskId"], part, retries=retries,
                backoff_s=backoff_s, deadline=deadline,
            ):
                yield serde.deserialize_page(blob)


def ack_spool(uri: str, task_id: str, part: int,
              timeout: float = 5.0) -> bool:
    """Release one consumed spool partition on the producer (the ack
    half of the fetch/ack protocol). Best-effort: a dead producer has
    nothing left to free."""
    from presto_tpu.dist import connpool as CONNPOOL

    try:
        with CONNPOOL.request(
            f"{uri}/v1/task/{task_id}/spool/{part}", method="DELETE",
            timeout=timeout,
        ) as r:
            r.read()
        return True
    except (urllib.error.URLError, OSError, TimeoutError):
        return False
