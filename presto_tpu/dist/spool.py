"""Spooled-exchange data plane helpers for the stage-DAG scheduler.

Reference: presto-main operator/PartitionedOutputOperator.java (the
producer half of a hash-repartition exchange: route each row to a
partition buffer by hash(keys) % P) and operator/ExchangeClient.java
(the consumer half: token-acked page fetch from every producer task).
The Project-Tardigrade twist: partition buffers are SPOOLED — they
outlive the producing task's execution on the worker (PageStore
host/disk tiers, exec/pagestore.py), so a lost downstream task replays
from its upstream spools instead of failing the query.

Everything here is host-side numpy on already-device_get pages: the
partition split happens at the serialization boundary where the page
has left the device anyway, so the device never pays for the exchange
(SURVEY §6.8: HTTP shapes survive only at the pod boundary).

Client split (deliberate, not drift): `fetch_spool_blobs` below is the
WORKER-side exchange client — plain token-dedupe fetch between stage
tasks. The COORDINATOR's drain of final stages keeps using
`dcn.DcnRunner._fetch_pages`, which layers the PR-5 resume machinery
(rolling sha256 of consumed bytes + byte-identical prefix verification
after a replay) that worker-to-worker ingest does not need — a
re-dispatched consumer restarts its stream from token 0. Both speak
the same `/v1/task/{id}/results/{token}?part=p` protocol.

Hash discipline: partitioning needs only SELF-consistency across the
two sides of one exchange (co-partitioned join sides / all producers
of one aggregation exchange), not agreement with the device kernels'
hash. Keys hash from VALUE encodings — int64 bit-views, IEEE-754
bit-views with -0.0/NaN normalization, dictionary VALUES (not codes) —
mixed with a splitmix64 finalizer and the reference's 31*h+x combiner,
so equal SQL values land in the same partition regardless of which
producer task emitted them. NULL keys hash to a fixed sentinel (every
null row lands on a deterministic partition — inner join keys never
match NULL, and NULL group keys co-locate).
"""

from __future__ import annotations

import functools
import json
import time
import urllib.error
import urllib.request
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.exec import shapes as SH
from presto_tpu.exec import xfer as XF
from presto_tpu.ops.hashing import xxhash64_host
from presto_tpu.page import Block, Page

_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_NULL_SENTINEL = np.uint64(0x9E3779B185EBCA87)
_NAN_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)
_C31 = np.uint64(31)


def _mix64(h: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (vectorized, natural uint64 wraparound)."""
    with np.errstate(over="ignore"):
        h = (h ^ (h >> np.uint64(30))) * _MIX1
        h = (h ^ (h >> np.uint64(27))) * _MIX2
        return h ^ (h >> np.uint64(31))


@functools.lru_cache(maxsize=64)
def _dict_value_hashes(dictionary) -> np.ndarray:
    """Per-code value hashes of one Dictionary, memoized — dictionaries
    are shared across every page of a scan, and Dictionary hashes by
    CONTENT, so the Python-level hashing loop runs once per distinct
    dictionary instead of once per page per key channel."""
    return np.array(
        [xxhash64_host(repr(v).encode()) for v in dictionary.values],
        dtype=np.uint64,
    )


def _block_value_u64(blk: Block) -> np.ndarray:
    """Per-row uint64 VALUE encoding of one key block (host numpy)."""
    data = blk.data
    if isinstance(data, tuple):
        # long decimal (hi, lo): combine the two words
        arrs = [XF.np_host(d) for d in data]
        if any(a.ndim != 1 for a in arrs):
            raise TypeError(
                "collect-state blocks cannot be exchange partition keys"
            )
        h = np.zeros(arrs[0].shape[0], dtype=np.uint64)
        with np.errstate(over="ignore"):
            for a in arrs:
                h = h * _C31 + a.astype(np.int64).view(np.uint64)
        return h
    arr = XF.np_host(data)
    if blk.dictionary is not None:
        # hash the dictionary VALUES, not the table-local codes —
        # producer tasks with different dictionaries stay consistent
        vh = _dict_value_hashes(blk.dictionary)
        if len(vh) == 0:
            return np.zeros(arr.shape[0], dtype=np.uint64)
        codes = np.clip(arr.astype(np.int64), 0, len(vh) - 1)
        return vh[codes]
    if arr.dtype == np.bool_:
        return arr.astype(np.uint64)
    if np.issubdtype(arr.dtype, np.floating):
        f = arr.astype(np.float64)
        f = np.where(f == 0.0, 0.0, f)  # -0.0 == +0.0 (SQL equality)
        bits = f.view(np.uint64)
        return np.where(np.isnan(f), _NAN_KEY, bits)
    return arr.astype(np.int64).view(np.uint64)


def row_hash_u64(page: Page, keys: Sequence[int]) -> np.ndarray:
    """Per-row partition hash over the key channels (31*h + mix(col),
    the reference's CombineHashFunction shape over splitmix-dispersed
    column encodings)."""
    cap = XF.np_host(page.valid).shape[0]
    h = np.zeros(cap, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for k in keys:
            blk = page.block(k)
            col = _mix64(_block_value_u64(blk))
            if blk.nulls is not None:
                col = np.where(XF.np_host(blk.nulls), _NULL_SENTINEL,
                               col)
            h = h * _C31 + col
    return _mix64(h)


def take_rows_host(page: Page, idx: np.ndarray) -> Page:
    """Compact the given row indices of a HOST page into a fresh page
    whose capacity sits on the shapes.py bucket ladder (restreamed
    exchange pages must not mint off-ladder program shapes
    downstream)."""
    n = len(idx)
    cap = SH.bucket(max(n, 1))
    pad = np.zeros(cap, dtype=np.int64)
    pad[:n] = idx
    blocks: List[Block] = []
    for blk in page.blocks:
        if isinstance(blk.data, tuple):
            data = tuple(XF.np_host(d)[pad] for d in blk.data)
        else:
            data = XF.np_host(blk.data)[pad]
        nulls = (XF.np_host(blk.nulls)[pad]
                 if blk.nulls is not None else None)
        blocks.append(Block(data=data, type=blk.type, nulls=nulls,
                            dictionary=blk.dictionary))
    valid = np.zeros(cap, dtype=bool)
    valid[:n] = True
    return Page(blocks=tuple(blocks), valid=valid)


def partition_host_page(
    page: Page, keys: Sequence[int], nparts: int
) -> List[Tuple[int, Page]]:
    """Split one host page into per-partition compacted pages.
    Partitions with zero rows are skipped (deterministically — replay
    regenerates the same skips, so token sequences stay stable)."""
    valid = XF.np_host(page.valid)
    if nparts <= 1:
        return [(0, page)] if valid.any() else []
    part = (row_hash_u64(page, keys) % np.uint64(nparts)).astype(
        np.int64)
    out: List[Tuple[int, Page]] = []
    for p in range(nparts):
        idx = np.nonzero(valid & (part == p))[0]
        if len(idx):
            out.append((p, take_rows_host(page, idx)))
    return out


# ------------------------------------------------------------ client
class SourceTaskFailed(RuntimeError):
    """The upstream task itself failed (X-Task-Error): deterministic,
    re-dispatching the CONSUMER alone will not help."""


class SourceLost(RuntimeError):
    """An upstream task's spool is unreachable (node death): the
    scheduler must replay the upstream task before the consumer can
    make progress. The message carries the placement for diagnosis."""

    def __init__(self, uri: str, task_id: str, cause: str):
        super().__init__(
            f"[source-lost {uri} {task_id}] {cause}")
        self.uri = uri
        self.task_id = task_id


def fetch_spool_blobs(
    uri: str,
    task_id: str,
    part: int,
    *,
    start_token: int = 0,
    retries: int = 3,
    backoff_s: float = 0.1,
    timeout: float = 60.0,
    deadline: Optional[float] = None,
) -> Iterator[bytes]:
    """Token-acked fetch of one spool partition (at-least-once +
    dedupe-by-token, the HttpPageBufferClient protocol with the
    partition dimension added). Raises SourceTaskFailed on
    X-Task-Error, SourceLost after bounded transport retries."""
    token = start_token
    while True:
        attempt = 0
        while True:
            if deadline is not None and time.monotonic() > deadline:
                from presto_tpu.exec.executor import (
                    QueryDeadlineExceeded,
                )

                raise QueryDeadlineExceeded(
                    "query exceeded query_max_run_time in a spool "
                    "fetch"
                )
            try:
                req = urllib.request.Request(
                    f"{uri}/v1/task/{task_id}/results/{token}"
                    f"?part={part}"
                )
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    if r.status == 204:
                        if r.headers.get("X-Done") == "1":
                            return
                        break  # long-poll timeout; re-ask same token
                    body = r.read()
                    token = int(r.headers["X-Next-Token"])
                    yield body
                    break
            except urllib.error.HTTPError as e:
                if e.headers.get("X-Task-Error"):
                    try:
                        msg = json.loads(e.read().decode()).get(
                            "error", "")
                    except (ValueError, OSError):
                        msg = str(e)
                    raise SourceTaskFailed(
                        f"upstream task {task_id} on {uri} FAILED: "
                        f"{msg}"
                    ) from e
                if e.code == 410:
                    # the partition was acked/released: deterministic
                    # and permanent — retrying or replaying the
                    # (healthy) producer node would not bring the
                    # spool back
                    raise SourceTaskFailed(
                        f"spool partition {part} of task {task_id} on "
                        f"{uri} was already released (acked) — the "
                        f"scheduler consumed it before this fetch"
                    ) from e
                attempt += 1
                if attempt > retries:
                    raise SourceLost(uri, task_id, str(e)) from e
                time.sleep(backoff_s * attempt)
            except (urllib.error.URLError, ConnectionError,
                    OSError) as e:
                attempt += 1
                if attempt > retries:
                    raise SourceLost(uri, task_id, str(e)) from e
                time.sleep(backoff_s * attempt)


def iter_source_pages(
    spec: dict,
    *,
    retries: int = 3,
    backoff_s: float = 0.1,
    deadline: Optional[float] = None,
):
    """Worker-side exchange ingest: yield deserialized pages of one
    RemoteSource edge — partition `spec['partition']` of every
    producer task, in payload order (deterministic, so a re-dispatched
    consumer regenerates an identical stream from identical spools)."""
    from presto_tpu.dist import serde

    part = int(spec.get("partition", 0))
    for t in spec["tasks"]:
        for blob in fetch_spool_blobs(
            t["uri"], t["taskId"], part, retries=retries,
            backoff_s=backoff_s, deadline=deadline,
        ):
            yield serde.deserialize_page(blob)


def ack_spool(uri: str, task_id: str, part: int,
              timeout: float = 5.0) -> bool:
    """Release one consumed spool partition on the producer (the ack
    half of the fetch/ack protocol). Best-effort: a dead producer has
    nothing left to free."""
    try:
        req = urllib.request.Request(
            f"{uri}/v1/task/{task_id}/spool/{part}", method="DELETE"
        )
        urllib.request.urlopen(req, timeout=timeout).close()
        return True
    except (urllib.error.URLError, OSError, TimeoutError):
        return False
